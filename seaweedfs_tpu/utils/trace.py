"""Cluster-wide request tracing: per-plane latency attribution (ISSUE 7).

Six PRs of perf work were tuned with aggregate counters — this module is
the missing per-request view: take one slow S3 GET and say how much of
its wall was filer cache miss, volume group-commit wait, EC dispatch
queue wait, or device matmul. Span contexts propagate as W3C
`traceparent` over both HTTP headers and gRPC metadata (pb/rpc.py
injects/extracts them centrally), every server keeps a bounded
in-process ring buffer of finished spans, and the interesting traces
are pinned past ring churn by tail-based retention:

  * keep-if-error: a span that exited with an exception (or was marked
    via set_error) always pins its trace;
  * keep-if-slow: any span >= SWFS_TRACE_SLOW_MS (default 250) pins its
    trace — the p99 tail is exactly what aggregate histograms can't
    explain;
  * head sampling (SWFS_TRACE_SAMPLE, default 1.0) caps the recording
    rate at the ROOT so the whole request tree is either recorded or
    not (partial trees attribute nothing).

Surfaces: `/debug/traces` JSON on master/filer/volume/s3, the
`X-Trace-Id` response header, the shell's `trace.dump` (gathers one
trace's spans from every server it touched), and histogram exemplars
in utils/stats.py (a p99 bucket in /metrics links to a retained trace
id).

Cheap enough to leave on: a span is one perf_counter pair, one dict,
and one deque append — no locks on the hot path beyond the store's
(bench.py --trace-ab pins <= 2% median overhead on the smallfile A/B,
BENCH_AB_ISSUE7.json). SWFS_TRACE=0 turns the whole plane into no-ops.

Timing discipline (lint rule SWFS002): spans must never read the wall
clock per-event — `time.time()` is not monotonic and a step (NTP slew,
manual set) would corrupt durations. All timing derives from
`time.perf_counter()`; wall-clock timestamps come from a single
monotonic-anchored epoch captured at import.
"""

from __future__ import annotations

import os
import random
import threading
from collections import OrderedDict, deque

import time

# Wall-clock anchor: captured ONCE at import; every span timestamp is
# anchor + perf_counter delta, so spans are strictly monotonic within a
# process and never see a clock step mid-trace. This line is the single
# sanctioned wall-clock read (lint rule SWFS002, tools/lint.py).
_EPOCH_ANCHOR = time.time_ns() / 1e9  # lint: allow-wall-clock-anchor
_PC_ANCHOR = time.perf_counter()

TRACEPARENT = "traceparent"
_VERSION = "00"
_HEX = set("0123456789abcdef")

DEFAULT_SLOW_MS = 250.0
DEFAULT_RING_SPANS = 4096
DEFAULT_RETAIN_TRACES = 128
# hard cap on spans held per RETAINED trace: a client reusing one fixed
# traceparent on every request funnels everything into one trace id —
# without this, the first slow span would pin a list that then grows
# forever (the "all bounds are hard" contract)
RETAINED_TRACE_SPAN_CAP = 512


def now_unix() -> float:
    """Monotonic-anchored wall-clock seconds (the only sanctioned span
    timestamp source — see the module docstring on SWFS002)."""
    return _EPOCH_ANCHOR + (time.perf_counter() - _PC_ANCHOR)


# Config cache: os.environ reads cost ~2us each (str encode + Mapping
# machinery) — three per span would dominate the span itself. The env
# stays the knob (flippable at runtime, e.g. the A/B alternates
# SWFS_TRACE between segments), re-read at most every _CFG_TTL_S;
# refresh_config() forces it (tests that flip the env mid-function).
_CFG_TTL_S = 0.25
_cfg_cache = {"t": -1.0, "enabled": True, "sample": 1.0,
              "slow": DEFAULT_SLOW_MS}


def _cfg() -> dict:
    c = _cfg_cache
    now = time.monotonic()
    if now - c["t"] > _CFG_TTL_S:
        c["enabled"] = os.environ.get("SWFS_TRACE", "1").lower() not in (
            "0", "false", "off")
        try:
            c["sample"] = float(os.environ.get("SWFS_TRACE_SAMPLE", "1"))
        except ValueError:
            c["sample"] = 1.0
        try:
            c["slow"] = float(os.environ.get("SWFS_TRACE_SLOW_MS",
                                             str(DEFAULT_SLOW_MS)))
        except ValueError:
            c["slow"] = DEFAULT_SLOW_MS
        c["t"] = now
    return c


def refresh_config() -> None:
    """Drop the cached env config so the next span sees fresh values."""
    _cfg_cache["t"] = -1.0


def enabled() -> bool:
    """SWFS_TRACE gates the whole plane (default on)."""
    return _cfg()["enabled"]


def sample_rate() -> float:
    """Head-sampling probability applied at trace ROOTS (default 1.0:
    record everything — retention, not sampling, bounds memory)."""
    return _cfg()["sample"]


def slow_ms() -> float:
    """Tail-retention threshold: any span at least this slow pins its
    whole trace past ring churn."""
    return _cfg()["slow"]


# -- process identity ------------------------------------------------------

_identity = {"component": "", "server": ""}


def set_identity(component: str, server: str) -> None:
    """Stamp this process's spans with who it is (called by every
    server's start()). Multiple in-process servers (tests, `weed
    server`) each re-stamp on ingress via the span's component=."""
    _identity["component"] = component
    _identity["server"] = server


# -- context propagation ---------------------------------------------------

_tls = threading.local()


def _rand_hex(nbytes: int) -> str:
    return f"{random.getrandbits(nbytes * 8):0{nbytes * 2}x}"


def parse_traceparent(value) -> tuple[str, str, bool] | None:
    """W3C traceparent `00-<32 hex>-<16 hex>-<2 hex>` ->
    (trace_id, parent_span_id, sampled); anything malformed -> None
    (callers re-root — a hostile header must never 500)."""
    if not isinstance(value, str):
        return None
    parts = value.strip().split("-")
    if len(parts) < 4:
        return None
    ver, tid, sid, flags = parts[0], parts[1], parts[2], parts[3]
    if len(ver) != 2 or not set(ver) <= _HEX or ver == "ff":
        return None
    if len(tid) != 32 or not set(tid) <= _HEX or set(tid) == {"0"}:
        return None
    if len(sid) != 16 or not set(sid) <= _HEX or set(sid) == {"0"}:
        return None
    if len(flags) != 2 or not set(flags) <= _HEX:
        return None
    return tid, sid, bool(int(flags, 16) & 0x01)


class Span:
    """One timed operation. Attributes are plain JSON-able values; the
    span records itself into the process trace store on close (when its
    trace is sampled). Kept deliberately thin — a span on the write hot
    path is two perf_counter reads, one 8-byte random id, and one deque
    append; the JSON view is built lazily at READ time (to_dict), never
    per request."""

    __slots__ = ("trace_id", "span_id", "parent_id", "name", "component",
                 "server", "sampled", "_t0", "attrs", "error",
                 "duration_ms")

    def __init__(self, name: str, trace_id: str, parent_id: str,
                 sampled: bool, component: str = "", server: str = ""):
        self.name = name
        self.trace_id = trace_id
        self.span_id = _rand_hex(8)
        self.parent_id = parent_id
        self.sampled = sampled
        self.component = component or _identity["component"]
        self.server = server or _identity["server"]
        self._t0 = time.perf_counter()
        self.attrs: dict = {}
        self.error = ""
        self.duration_ms = -1.0

    @property
    def start_unix(self) -> float:
        # derived, not stored: the anchor arithmetic runs at read time
        return _EPOCH_ANCHOR + (self._t0 - _PC_ANCHOR)

    def set_attr(self, **attrs) -> None:
        self.attrs.update(attrs)

    def set_error(self, err) -> None:
        self.error = str(err)[:300]

    def traceparent(self) -> str:
        return (f"{_VERSION}-{self.trace_id}-{self.span_id}-"
                f"{'01' if self.sampled else '00'}")

    def context(self) -> tuple[str, str, bool]:
        """Portable parent handle for cross-thread span creation (sink
        threads, thread pools): pass to span(parent=...)."""
        return self.trace_id, self.span_id, self.sampled

    def finish(self) -> None:
        self.duration_ms = (time.perf_counter() - self._t0) * 1000.0
        if self.sampled:
            STORE.record(self)

    def to_dict(self) -> dict:
        return {
            "traceId": self.trace_id, "spanId": self.span_id,
            "parentId": self.parent_id, "name": self.name,
            "component": self.component, "server": self.server,
            "startUnix": round(self.start_unix, 6),
            "durationMs": round(self.duration_ms, 3),
            "error": self.error, "attrs": dict(self.attrs),
        }


class _NoopSpan:
    """Returned when tracing is off or the span is suppressed: callers
    never branch — set_attr/set_error are absorbing no-ops."""

    __slots__ = ()
    trace_id = ""
    span_id = ""
    sampled = False
    duration_ms = -1.0

    def set_attr(self, **attrs) -> None:
        pass

    def set_error(self, err) -> None:
        pass

    def traceparent(self) -> str:
        return ""

    def context(self) -> None:
        return None


NOOP = _NoopSpan()


def current() -> Span | None:
    """The active span on this thread (None outside any span)."""
    sp = getattr(_tls, "span", None)
    return sp if isinstance(sp, Span) else None


def current_context() -> tuple[str, str, bool] | None:
    sp = current()
    return sp.context() if sp is not None else None


def traceparent() -> str:
    """Header/metadata value for the active span ("" when none): the
    single injection source pb/rpc.py and the HTTP clients use."""
    sp = current()
    return sp.traceparent() if sp is not None else ""


def inject_headers(headers: dict | None = None) -> dict:
    """Add the active span's traceparent to an outgoing-header dict
    (no-op passthrough when no span is active)."""
    headers = headers if headers is not None else {}
    tp = traceparent()
    if tp:
        headers[TRACEPARENT] = tp
    return headers


def carrier_has_context(carrier) -> bool:
    """True when the carrier (HTTP headers / gRPC metadata) names a
    traceparent at all — servers use this to skip span creation for
    untraced background chatter (heartbeats, lease refills)."""
    return _header_value(carrier) is not None


def _header_value(carrier) -> str | None:
    """traceparent out of an HTTP header mapping or a gRPC invocation-
    metadata iterable of (key, value) pairs."""
    if carrier is None:
        return None
    get = getattr(carrier, "get", None)
    if get is not None:
        # one lookup: HTTP header mappings (email.Message) are case-
        # insensitive already, and W3C mandates the lowercase form
        v = get(TRACEPARENT)
        return v if isinstance(v, str) else None
    try:
        for k, v in carrier:
            if str(k).lower() == TRACEPARENT:
                return v if isinstance(v, str) else None
    except TypeError:
        return None
    return None


class _SpanCtx:
    """Slotted context manager around one Span — a plain class instead
    of @contextmanager because the generator machinery costs more than
    the span itself on the write hot path."""

    __slots__ = ("sp", "activate", "_prev")

    def __init__(self, sp: Span, activate: bool):
        self.sp = sp
        self.activate = activate
        self._prev = None

    def __enter__(self) -> Span:
        if self.activate:
            self._prev = getattr(_tls, "span", None)
            _tls.span = self.sp
        return self.sp

    def __exit__(self, et, ev, tb):
        if self.activate:
            _tls.span = self._prev
        sp = self.sp
        if ev is not None and not sp.error:
            sp.set_error(f"{et.__name__}: {ev}")
        sp.finish()
        return False


class _NoopCtx:
    __slots__ = ()

    def __enter__(self):
        return NOOP

    def __exit__(self, *exc):
        return False


_NOOP_CTX = _NoopCtx()


def span(name: str, *, carrier=None, parent=None, child_only: bool = False,
         component: str = "", server: str = "", activate: bool = True,
         **attrs):
    """The one way spans are made.

      * carrier=: server ingress — parse the request's traceparent; a
        missing/malformed header re-roots (fresh trace id, head-sampled).
      * parent=: explicit cross-thread parent (a span.context() tuple).
      * neither: child of this thread's active span, else a new root.
      * child_only=True: record NOTHING unless a parent is active —
        internal client ops (lookups, leases) must not root noise
        traces of their own.
      * activate=False: time + record the span but don't install it as
        the thread's current context (streaming gRPC handlers, whose
        generator bodies suspend mid-`with` and would leak the TLS).

    Exceptions propagate; they mark the span as an error first
    (keep-if-error retention)."""
    if child_only and parent is None and carrier is None \
            and not isinstance(getattr(_tls, "span", None), Span):
        # fast path: internal client ops outside any trace — the
        # common case on hot client threads; skip even the config read
        return _NOOP_CTX
    if not enabled():
        return _NOOP_CTX
    parent_span = current()
    tid = sid = None
    sampled = True
    if carrier is not None:
        parsed = parse_traceparent(_header_value(carrier))
        if parsed is not None:
            tid, sid, sampled = parsed
        elif parent_span is None:
            # re-root: hostile/absent header, no surrounding span
            tid, sid = _rand_hex(16), ""
            sampled = random.random() < sample_rate()
    if tid is None and parent is not None:
        try:
            tid, sid, sampled = parent
        except (TypeError, ValueError):
            tid = None
    if tid is None:
        if parent_span is not None:
            tid = parent_span.trace_id
            sid = parent_span.span_id
            sampled = parent_span.sampled
        elif child_only:
            return _NOOP_CTX
        else:
            tid, sid = _rand_hex(16), ""
            sampled = random.random() < sample_rate()
    sp = Span(name, tid, sid or "", sampled, component=component,
              server=server)
    if attrs:
        sp.attrs.update(attrs)
    return _SpanCtx(sp, activate)


# -- the per-process span store --------------------------------------------


class TraceStore:
    """Bounded two-tier store: a ring of recent spans (every sampled
    span lands here; serves /debug/traces for just-finished requests)
    plus a FIFO-bounded map of RETAINED traces (pinned by error/slow
    spans; the ones histogram exemplars and incident debugging link
    to). All bounds are hard — tracing can be left on forever.

    Hot-path discipline: record() takes ONE lock, appends the Span
    OBJECT (the JSON dict is built lazily at read time), and counts
    into plain ints — the SeaweedFS_trace_* metric families PULL from
    here at scrape time instead of charging every span a metric lock."""

    def __init__(self, ring_spans: int | None = None,
                 retain_traces: int | None = None):
        if ring_spans is None:
            ring_spans = int(os.environ.get("SWFS_TRACE_BUF",
                                            str(DEFAULT_RING_SPANS)))
        if retain_traces is None:
            retain_traces = int(os.environ.get("SWFS_TRACE_RETAIN",
                                               str(DEFAULT_RETAIN_TRACES)))
        self._ring: deque = deque(maxlen=max(ring_spans, 16))
        self._retained: "OrderedDict[str, list[Span]]" = OrderedDict()
        self._retain_max = max(retain_traces, 4)
        self._lock = threading.Lock()
        self.recorded = 0
        self.retained_total = 0
        self._span_counts: dict[str, int] = {}       # by component
        self._retained_counts: dict[str, int] = {}   # by reason

    def record(self, sp: Span) -> None:
        pin = bool(sp.error) or sp.duration_ms >= slow_ms()
        with self._lock:
            self.recorded += 1
            comp = sp.component or "-"
            self._span_counts[comp] = self._span_counts.get(comp, 0) + 1
            self._ring.append(sp)
            spans = self._retained.get(sp.trace_id)
            if spans is not None:
                # trace already pinned: keep feeding it, but never past
                # the per-trace cap (the ring still holds the overflow
                # briefly, so a fresh dump sees the most recent spans)
                if len(spans) < RETAINED_TRACE_SPAN_CAP:
                    spans.append(sp)
                return
            if not pin:
                return
            # promote: pull the trace's earlier spans out of the ring
            # so the retained view is the whole tree seen so far
            self.retained_total += 1
            reason = "error" if sp.error else "slow"
            self._retained_counts[reason] = \
                self._retained_counts.get(reason, 0) + 1
            self._retained[sp.trace_id] = [
                s for s in self._ring if s.trace_id == sp.trace_id]
            while len(self._retained) > self._retain_max:
                self._retained.popitem(last=False)

    def span_counts(self) -> dict[str, int]:
        """component -> spans recorded (the SeaweedFS_trace_spans pull
        source)."""
        with self._lock:
            return dict(self._span_counts)

    def retained_counts(self) -> dict[str, int]:
        """reason -> traces pinned (SeaweedFS_trace_retained_traces)."""
        with self._lock:
            return dict(self._retained_counts)

    def trace(self, trace_id: str) -> list[dict]:
        """Every span of one trace this process still holds (retained
        first, then un-pinned ring residents), deduped by span id."""
        with self._lock:
            spans = list(self._retained.get(trace_id, ()))
            seen = {s.span_id for s in spans}
            for s in self._ring:
                if s.trace_id == trace_id and s.span_id not in seen:
                    spans.append(s)
                    seen.add(s.span_id)
        out = [s.to_dict() for s in spans]
        out.sort(key=lambda s: s["startUnix"])
        return out

    def retained_summaries(self, limit: int = 64) -> list[dict]:
        with self._lock:
            items = [(tid, list(spans)) for tid, spans in
                     list(self._retained.items())[-limit:]]
        out = []
        for tid, spans in items:
            if not spans:
                continue
            root = min(spans, key=lambda s: s._t0)
            slowest = max(spans, key=lambda s: s.duration_ms)
            out.append({
                "traceId": tid, "spans": len(spans),
                "root": root.name, "server": root.server,
                "startUnix": round(root.start_unix, 6),
                "maxDurationMs": round(slowest.duration_ms, 3),
                "error": next((s.error for s in spans if s.error), ""),
            })
        out.sort(key=lambda s: s["startUnix"], reverse=True)
        return out

    def stats(self) -> dict:
        with self._lock:
            return {
                "enabled": enabled(),
                "recordedSpans": self.recorded,
                "ringSpans": len(self._ring),
                "retainedTraces": len(self._retained),
                "retainedTotal": self.retained_total,
                "slowMs": slow_ms(),
                "sampleRate": sample_rate(),
            }

    def clear(self) -> None:
        with self._lock:
            self._ring.clear()
            self._retained.clear()


STORE = TraceStore()


def debug_traces_payload(query: dict | None = None) -> dict:
    """The `/debug/traces` JSON every server serves: one trace's spans
    with ?trace=<id>, else the retained summaries + store stats."""
    q = query or {}
    tid = q.get("trace", "")
    if tid:
        return {"traceId": tid, "spans": STORE.trace(tid)}
    return {"retained": STORE.retained_summaries(),
            "store": STORE.stats()}
