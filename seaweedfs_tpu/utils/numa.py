"""NUMA/host-thread pinning for the EC dispatch hot loop (ISSUE 12).

The per-chip flush path and the encode pipeline's reader/writer threads
move tens of MB per batch between page cache, arena buffers, and the
device driver. On a multi-socket host the scheduler is free to migrate
those threads across NUMA nodes mid-batch, turning every one of those
passes into cross-node traffic (the exact class of memory-access cost
arXiv:2108.02692 measures dominating software EC). Pinning each thread
to one node's CPU set keeps a flush's arena, its page-cache reads, and
its matmul on local memory.

Everything here is OPTIONAL and fails soft:

  * gated by ``SWFS_EC_DISPATCH_PIN`` (default off — laptops, CI
    containers, and cgroup-restricted pods must behave identically with
    the gate closed);
  * topology is read from ``/sys/devices/system/node`` and falls back to
    a single all-CPU node when absent (macOS, restricted /sys);
  * ``os.sched_setaffinity`` failures (EPERM in a locked-down container,
    non-Linux hosts without the call) degrade to a counted no-op.

Threads register through :func:`pin_thread`. COOPERATING threads must
share a node: an encode pipeline's reader packs buffers its shard
writers drain, so the pipeline draws ONE node via :func:`next_node` and
passes it to every member as the ``node_hint`` — only unrelated threads
(independent pipelines, the shared dispatch flusher) round-robin, which
spreads load across nodes without splitting a producer/consumer pair.
The volume server's ``/status.EcDispatch`` surfaces
:func:`pinning_stats`.
"""

from __future__ import annotations

import glob
import itertools
import os
import threading

_GATE = "SWFS_EC_DISPATCH_PIN"

_lock = threading.Lock()
_rr = itertools.count()
_pinned = 0  # threads successfully pinned
_noops = 0  # pin attempts that degraded to a no-op
_nodes_cache: list[list[int]] | None = None


def enabled() -> bool:
    """True iff the operator opted in (default OFF: pinning a thread in
    a cgroup-limited container can easily hurt)."""
    return os.environ.get(_GATE, "0").lower() in ("1", "true", "on")


def _parse_cpulist(text: str) -> list[int]:
    """Kernel cpulist format: "0-3,8,10-11" -> [0,1,2,3,8,10,11]."""
    cpus: list[int] = []
    for part in text.strip().split(","):
        if not part:
            continue
        if "-" in part:
            lo, hi = part.split("-", 1)
            cpus.extend(range(int(lo), int(hi) + 1))
        else:
            cpus.append(int(part))
    return cpus


def node_cpus(sys_root: str = "/sys/devices/system/node") -> list[list[int]]:
    """Per-NUMA-node CPU lists from /sys, cached. A host without the
    sysfs tree (or with a single node) yields one all-CPU pseudo-node,
    so callers never special-case topology absence."""
    global _nodes_cache
    with _lock:
        if _nodes_cache is not None and sys_root == "/sys/devices/system/node":
            return _nodes_cache
    nodes: list[list[int]] = []
    try:
        for path in sorted(glob.glob(os.path.join(sys_root, "node[0-9]*"))):
            with open(os.path.join(path, "cpulist")) as f:
                cpus = _parse_cpulist(f.read())
            if cpus:
                nodes.append(cpus)
    except OSError:
        nodes = []
    if not nodes:
        # graceful fallback: one pseudo-node spanning the process's
        # current affinity mask (or every online CPU)
        try:
            cpus = sorted(os.sched_getaffinity(0))
        except (AttributeError, OSError):
            cpus = list(range(os.cpu_count() or 1))
        nodes = [cpus]
    if sys_root == "/sys/devices/system/node":
        with _lock:
            _nodes_cache = nodes
    return nodes


def next_node() -> int | None:
    """Draw a node index for a NEW thread group (an encode/rebuild
    pipeline): every member then pins with this value as its
    ``node_hint`` so producer and consumers share memory locality.
    None when the gate is closed (callers pass it straight through)."""
    if not enabled():
        return None
    return next(_rr) % len(node_cpus())


def pin_thread(node_hint: int | None = None) -> tuple[int, ...] | None:
    """Pin the CALLING thread to one NUMA node's CPUs.

    ``node_hint`` selects the node (modulo the node count) — pass one
    :func:`next_node` draw to every thread of a cooperating group;
    without a hint threads round-robin across nodes. Returns the CPU
    set applied, or None when pinning was a no-op (gate closed,
    single-node-single-CPU host, or EPERM)."""
    global _pinned, _noops
    if not enabled():
        return None
    nodes = node_cpus()
    idx = next(_rr) if node_hint is None else node_hint
    cpus = tuple(nodes[idx % len(nodes)])
    setter = getattr(os, "sched_setaffinity", None)
    if setter is None:
        with _lock:
            _noops += 1
        return None
    try:
        setter(0, cpus)
    except OSError:
        with _lock:
            _noops += 1
        return None
    with _lock:
        _pinned += 1
    return cpus


def pinning_stats() -> dict:
    """Snapshot for /status: gate state, topology, realized pins."""
    with _lock:
        pinned, noops = _pinned, _noops
    return {
        "enabled": enabled(),
        "nodes": len(node_cpus()) if enabled() else 0,
        "threadsPinned": pinned,
        "noops": noops,
    }


def _reset_for_tests() -> None:
    global _pinned, _noops, _nodes_cache, _rr
    with _lock:
        _pinned = _noops = 0
        _nodes_cache = None
        _rr = itertools.count()
