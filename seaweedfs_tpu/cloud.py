"""Minimal wire-protocol clients for GCS, Azure Blob, and Backblaze B2.

The reference reaches these providers through their vendor SDKs
(/root/reference/weed/remote_storage/gcs/gcs_storage_client.go:1,
 azure/azure_storage_client.go:1, replication/sink/b2sink/b2_sink.go:1);
none of those SDKs are in this image, so these are direct REST/JSON
implementations of the handful of calls the framework needs:

- GCS JSON API (storage/v1): media upload, alt=media download (ranged),
  object list with pageToken paging, delete. Auth is a static bearer
  token (service-account JWT exchange needs RSA signing, which the
  stdlib cannot do — a `token` is accepted from config or metadata-
  server-style injection; anonymous works against emulators).
- Azure Blob REST with real SharedKey request signing (HMAC-SHA256 over
  the canonicalized headers/resource — pure stdlib): Put Blob,
  Get Blob (ranged), Delete Blob, List Blobs (XML, marker paging).
- B2 native API v2: b2_authorize_account (basic auth),
  b2_get_upload_url / b2_upload_file (sha1-checked), b2_list_file_names,
  b2_delete_file_version, ranged file download; 401-expiry re-auth.

Every client speaks to any endpoint URL, so the test suite runs them
e2e against in-repo fake servers (tests/fake_cloud.py) that verify the
wire format — including the Azure signature — independently.
"""

from __future__ import annotations

import base64
import hashlib
import hmac
import json
import time
import urllib.parse
import xml.etree.ElementTree as ET

import requests


class CloudObject:
    """One remote object as the storage layers see it."""

    __slots__ = ("name", "size", "mtime", "etag", "extra")

    def __init__(self, name: str, size: int, mtime: int = 0,
                 etag: str = "", extra: dict | None = None):
        self.name = name
        self.size = size
        self.mtime = mtime
        self.etag = etag
        self.extra = extra or {}

    def __repr__(self):  # pragma: no cover
        return f"CloudObject({self.name!r}, {self.size})"


# ---------------------------------------------------------------------------
# GCS


class GcsClient:
    """GCS JSON API subset (objects: insert/get/list/delete)."""

    def __init__(self, bucket: str, *, token: str = "",
                 endpoint: str = "https://storage.googleapis.com",
                 project_id: str = ""):
        self.bucket = bucket
        self.token = token
        self.endpoint = endpoint.rstrip("/")
        self.project_id = project_id

    def _headers(self, extra: dict | None = None) -> dict:
        h = dict(extra or {})
        if self.token:
            h["Authorization"] = f"Bearer {self.token}"
        return h

    def _obj_url(self, name: str) -> str:
        return (f"{self.endpoint}/storage/v1/b/{self.bucket}/o/"
                f"{urllib.parse.quote(name, safe='')}")

    def put_object(self, name: str, data: bytes,
                   content_type: str = "application/octet-stream"
                   ) -> CloudObject:
        url = (f"{self.endpoint}/upload/storage/v1/b/{self.bucket}/o"
               f"?uploadType=media&name="
               f"{urllib.parse.quote(name, safe='')}")
        r = requests.post(url, data=data, headers=self._headers(
            {"Content-Type": content_type}), timeout=300)
        if r.status_code >= 300:
            raise IOError(f"gcs upload {name}: {r.status_code} {r.text[:200]}")
        meta = r.json()
        return CloudObject(name, int(meta.get("size", len(data))),
                           _rfc3339_to_unix(meta.get("updated", "")),
                           meta.get("etag", ""))

    def get_object(self, name: str, offset: int = 0, size: int = -1) -> bytes:
        headers = self._headers()
        if offset or size >= 0:
            end = "" if size < 0 else str(offset + size - 1)
            headers["Range"] = f"bytes={offset}-{end}"
        r = requests.get(self._obj_url(name) + "?alt=media", headers=headers,
                         timeout=300)
        if r.status_code >= 300:
            raise IOError(f"gcs get {name}: {r.status_code}")
        return r.content

    def list_objects(self, prefix: str = ""):
        token = ""
        while True:
            url = (f"{self.endpoint}/storage/v1/b/{self.bucket}/o"
                   f"?prefix={urllib.parse.quote(prefix, safe='')}")
            if token:
                url += "&pageToken=" + urllib.parse.quote(token, safe="")
            r = requests.get(url, headers=self._headers(), timeout=60)
            if r.status_code >= 300:
                raise IOError(f"gcs list: {r.status_code}")
            body = r.json()
            for item in body.get("items", []):
                yield CloudObject(item["name"], int(item.get("size", 0)),
                                  _rfc3339_to_unix(item.get("updated", "")),
                                  item.get("etag", ""))
            token = body.get("nextPageToken", "")
            if not token:
                return

    def delete_object(self, name: str) -> None:
        r = requests.delete(self._obj_url(name), headers=self._headers(),
                            timeout=60)
        if r.status_code >= 300 and r.status_code != 404:
            raise IOError(f"gcs delete {name}: {r.status_code}")

    # uniform verbs so sinks/remote-storage wrap any client generically
    put, get, remove, list = put_object, get_object, delete_object, \
        list_objects


def _rfc3339_to_unix(s: str) -> int:
    if not s:
        return 0
    try:
        return int(time.mktime(time.strptime(s[:19], "%Y-%m-%dT%H:%M:%S")))
    except ValueError:
        return 0


# ---------------------------------------------------------------------------
# Azure Blob


def azure_shared_key_signature(account: str, key_b64: str, method: str,
                               path: str, query: dict[str, list[str]],
                               headers: dict[str, str]) -> str:
    """Full SharedKey string-to-sign + HMAC (the 2015-02-21+ scheme:
    empty Content-Length when zero). `headers` is the request's header
    map, case-insensitive keys already lowered."""
    def h(name: str) -> str:
        return headers.get(name, "")

    length = h("content-length")
    if length == "0":
        length = ""
    canon_headers = "".join(
        f"{k}:{headers[k]}\n"
        for k in sorted(k for k in headers if k.startswith("x-ms-")))
    canon_res = f"/{account}{path}"
    for name in sorted(query):
        canon_res += f"\n{name}:{','.join(sorted(query[name]))}"
    sts = "\n".join([
        method.upper(), h("content-encoding"), h("content-language"),
        length, h("content-md5"), h("content-type"), h("date"),
        h("if-modified-since"), h("if-match"), h("if-none-match"),
        h("if-unmodified-since"), h("range"),
    ]) + "\n" + canon_headers + canon_res
    mac = hmac.new(base64.b64decode(key_b64), sts.encode("utf-8"),
                   hashlib.sha256).digest()
    return base64.b64encode(mac).decode()


class AzureBlobClient:
    """Azure Blob REST subset with SharedKey auth."""

    API_VERSION = "2020-10-02"

    def __init__(self, container: str, *, account: str, key: str,
                 endpoint: str = ""):
        self.container = container
        self.account = account
        self.key = key
        self.endpoint = (endpoint.rstrip("/") if endpoint else
                         f"https://{account}.blob.core.windows.net")

    def _request(self, method: str, path: str, *, params: dict | None = None,
                 data: bytes = b"", extra: dict | None = None):
        params = params or {}
        headers = {
            "x-ms-date": time.strftime("%a, %d %b %Y %H:%M:%S GMT",
                                       time.gmtime()),
            "x-ms-version": self.API_VERSION,
        }
        if data:
            headers["Content-Length"] = str(len(data))
        headers.update(extra or {})
        lowered = {k.lower(): v for k, v in headers.items()}
        qmap = {k: [str(v)] for k, v in params.items()}
        sig = azure_shared_key_signature(self.account, self.key, method,
                                         path, qmap, lowered)
        headers["Authorization"] = f"SharedKey {self.account}:{sig}"
        url = self.endpoint + path
        if params:
            url += "?" + urllib.parse.urlencode(params)
        return requests.request(method, url, data=data or None,
                                headers=headers, timeout=300)

    def _blob_path(self, name: str) -> str:
        return (f"/{self.container}/"
                f"{urllib.parse.quote(name.lstrip('/'), safe='/')}")

    def put_blob(self, name: str, data: bytes,
                 content_type: str = "application/octet-stream"
                 ) -> CloudObject:
        r = self._request("PUT", self._blob_path(name), data=data, extra={
            "x-ms-blob-type": "BlockBlob", "Content-Type": content_type})
        if r.status_code >= 300:
            raise IOError(f"azure put {name}: {r.status_code} {r.text[:200]}")
        return CloudObject(name, len(data), int(time.time()),
                           r.headers.get("ETag", "").strip('"'))

    def get_blob(self, name: str, offset: int = 0, size: int = -1) -> bytes:
        extra = {}
        if offset or size >= 0:
            end = "" if size < 0 else str(offset + size - 1)
            extra["Range"] = f"bytes={offset}-{end}"
        r = self._request("GET", self._blob_path(name), extra=extra)
        if r.status_code >= 300:
            raise IOError(f"azure get {name}: {r.status_code}")
        return r.content

    def delete_blob(self, name: str) -> None:
        r = self._request("DELETE", self._blob_path(name))
        if r.status_code >= 300 and r.status_code != 404:
            raise IOError(f"azure delete {name}: {r.status_code}")

    def list_blobs(self, prefix: str = ""):
        marker = ""
        while True:
            params = {"restype": "container", "comp": "list"}
            if prefix:
                params["prefix"] = prefix
            if marker:
                params["marker"] = marker
            r = self._request("GET", f"/{self.container}", params=params)
            if r.status_code >= 300:
                raise IOError(f"azure list: {r.status_code}")
            root = ET.fromstring(r.content)
            for blob in root.iter("Blob"):
                name = blob.findtext("Name") or ""
                props = blob.find("Properties")
                size = int(props.findtext("Content-Length") or 0) \
                    if props is not None else 0
                etag = (props.findtext("Etag") or "") \
                    if props is not None else ""
                yield CloudObject(name, size, 0, etag)
            marker = root.findtext("NextMarker") or ""
            if not marker:
                return

    put, get, remove, list = put_blob, get_blob, delete_blob, list_blobs


# ---------------------------------------------------------------------------
# Backblaze B2


class B2Client:
    """B2 native API v2 subset. Lazily authorizes; retries once on a 401
    (expired auth token), matching the SDK behavior the reference's
    b2sink relies on."""

    def __init__(self, bucket: str, *, key_id: str, application_key: str,
                 endpoint: str = "https://api.backblazeb2.com"):
        self.bucket = bucket
        self.key_id = key_id
        self.application_key = application_key
        self.endpoint = endpoint.rstrip("/")
        self._auth: dict | None = None
        self._bucket_id = ""

    # -- session plumbing

    def _authorize(self) -> dict:
        basic = base64.b64encode(
            f"{self.key_id}:{self.application_key}".encode()).decode()
        r = requests.get(
            f"{self.endpoint}/b2api/v2/b2_authorize_account",
            headers={"Authorization": f"Basic {basic}"}, timeout=60)
        if r.status_code >= 300:
            raise IOError(f"b2 authorize: {r.status_code} {r.text[:200]}")
        self._auth = r.json()
        return self._auth

    def _session(self) -> dict:
        return self._auth or self._authorize()

    def _api(self, op: str, body: dict) -> dict:
        for attempt in (0, 1):
            auth = self._session()
            r = requests.post(
                f"{auth['apiUrl']}/b2api/v2/{op}",
                headers={"Authorization": auth["authorizationToken"]},
                data=json.dumps(body), timeout=60)
            if r.status_code == 401 and attempt == 0:
                self._auth = None  # token expired — re-authorize once
                continue
            if r.status_code >= 300:
                raise IOError(f"b2 {op}: {r.status_code} {r.text[:200]}")
            return r.json()
        raise IOError(f"b2 {op}: unauthorized after re-auth")

    def _bucket(self) -> str:
        if not self._bucket_id:
            auth = self._session()
            resp = self._api("b2_list_buckets", {
                "accountId": auth.get("accountId", ""),
                "bucketName": self.bucket})
            for b in resp.get("buckets", []):
                if b.get("bucketName") == self.bucket:
                    self._bucket_id = b["bucketId"]
            if not self._bucket_id:
                raise IOError(f"b2: bucket {self.bucket!r} not found")
        return self._bucket_id

    # -- operations

    def upload(self, name: str, data: bytes,
               content_type: str = "b2/x-auto") -> CloudObject:
        up = self._api("b2_get_upload_url", {"bucketId": self._bucket()})
        r = requests.post(up["uploadUrl"], data=data, headers={
            "Authorization": up["authorizationToken"],
            "X-Bz-File-Name": urllib.parse.quote(name.lstrip("/"), safe="/"),
            "Content-Type": content_type,
            "X-Bz-Content-Sha1": hashlib.sha1(data).hexdigest(),
        }, timeout=300)
        if r.status_code >= 300:
            raise IOError(f"b2 upload {name}: {r.status_code} {r.text[:200]}")
        meta = r.json()
        return CloudObject(meta.get("fileName", name),
                           int(meta.get("contentLength", len(data))),
                           int(meta.get("uploadTimestamp", 0)) // 1000,
                           extra={"fileId": meta.get("fileId", "")})

    def download(self, name: str, offset: int = 0, size: int = -1) -> bytes:
        auth = self._session()
        headers = {"Authorization": auth["authorizationToken"]}
        if offset or size >= 0:
            end = "" if size < 0 else str(offset + size - 1)
            headers["Range"] = f"bytes={offset}-{end}"
        url = (f"{auth['downloadUrl']}/file/{self.bucket}/"
               f"{urllib.parse.quote(name.lstrip('/'), safe='/')}")
        r = requests.get(url, headers=headers, timeout=300)
        if r.status_code == 401:
            self._auth = None
            return self.download(name, offset, size)
        if r.status_code >= 300:
            raise IOError(f"b2 download {name}: {r.status_code}")
        return r.content

    def list_files(self, prefix: str = ""):
        start = ""
        while True:
            body = {"bucketId": self._bucket(), "maxFileCount": 1000}
            if prefix:
                body["prefix"] = prefix
            if start:
                body["startFileName"] = start
            resp = self._api("b2_list_file_names", body)
            for f in resp.get("files", []):
                yield CloudObject(
                    f["fileName"], int(f.get("contentLength", 0)),
                    int(f.get("uploadTimestamp", 0)) // 1000,
                    extra={"fileId": f.get("fileId", "")})
            start = resp.get("nextFileName") or ""
            if not start:
                return

    def delete(self, name: str) -> None:
        """Delete every version of `name` (the sink's semantic).
        b2_list_file_names surfaces only the newest version per name, so
        loop: each pass deletes the then-newest version until none hide
        beneath."""
        name = name.lstrip("/")
        while True:
            victims = [o for o in self.list_files(prefix=name)
                       if o.name == name]
            if not victims:
                return
            for o in victims:
                self._api("b2_delete_file_version",
                          {"fileName": o.name, "fileId": o.extra["fileId"]})

    put, get, remove, list = upload, download, delete, list_files
