"""collection.* commands (reference: weed/shell/command_collection_*.go)."""

from __future__ import annotations

import argparse

from ...pb import master_pb2
from ..registry import command


@command("collection.list", "list collections")
def collection_list(env, args, out):
    resp = env.master_stub().CollectionList(
        master_pb2.CollectionListRequest(
            include_normal_volumes=True, include_ec_volumes=True), timeout=10)
    for c in resp.collections:
        print(f"collection: {c.name!r}", file=out)
    print(f"total {len(resp.collections)} collections", file=out)


@command("collection.delete", "delete a whole collection (destructive)")
def collection_delete(env, args, out):
    p = argparse.ArgumentParser(prog="collection.delete")
    p.add_argument("-collection", required=True)
    p.add_argument("-force", action="store_true")
    opts = p.parse_args(args)
    env.confirm_is_locked()
    if not opts.force:
        print("add -force to actually delete", file=out)
        return
    env.master_stub().CollectionDelete(
        master_pb2.CollectionDeleteRequest(name=opts.collection), timeout=120)
    print(f"collection {opts.collection!r} deleted", file=out)
