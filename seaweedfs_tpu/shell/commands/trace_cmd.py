"""`trace.dump` — gather one trace's spans from every server it touched.

The tracing plane (utils/trace.py, ISSUE 7) keeps each span in the
PROCESS that produced it; a request that crossed s3 -> filer -> three
volume servers left pieces of its tree on each. This command walks the
cluster — the master, every registered volume server, and the shell's
filer if one is configured — asking each `/debug/traces?trace=<id>`,
merges the spans (deduped by span id: in-process test clusters share
one store), and prints them as a time-ordered tree with per-span
attributes, so one X-Trace-Id from a slow response turns into a full
per-plane latency breakdown at the terminal.
"""

from __future__ import annotations

import json

import requests

from ...utils.http import requests_verify, url_for
from ..registry import command


def _fetch(addr: str, trace_id: str) -> list[dict]:
    try:
        r = requests.get(url_for(addr, "/debug/traces"),
                         params={"trace": trace_id}, timeout=10,
                         verify=requests_verify())
        if r.status_code != 200:
            return []
        return r.json().get("spans", [])
    except (requests.RequestException, ValueError):
        return []


def gather_trace(env, trace_id: str,
                 extra: list[str] | None = None) -> tuple[list[dict],
                                                          list[str]]:
    """-> (spans deduped+sorted, servers queried). Queries the master,
    every data node from the topology, the shell's filer, and any
    `extra` addresses."""
    targets = [env.master]
    try:
        for dn in env.collect_data_nodes():
            if dn.id not in targets:
                targets.append(dn.id)
    except Exception:  # noqa: BLE001 — a dead master still leaves extras
        pass
    if env.filer and env.filer not in targets:
        targets.append(env.filer)
    for addr in extra or []:
        if addr and addr not in targets:
            targets.append(addr)
    spans: list[dict] = []
    seen: set[str] = set()
    for addr in targets:
        for s in _fetch(addr, trace_id):
            if s.get("spanId") in seen:
                continue
            seen.add(s.get("spanId"))
            spans.append(s)
    spans.sort(key=lambda s: s.get("startUnix", 0))
    return spans, targets


def _render(spans: list[dict], out) -> None:
    if not spans:
        print("no spans found (expired from every ring, or wrong id?)",
              file=out)
        return
    t0 = spans[0].get("startUnix", 0)
    by_id = {s["spanId"]: s for s in spans}

    def depth(s, hop=0):
        if hop > 32:  # cycles can't happen, but never loop on bad data
            return hop
        p = by_id.get(s.get("parentId", ""))
        return 0 if p is None else depth(p, hop + 1) + 1

    for s in spans:
        off_ms = (s.get("startUnix", 0) - t0) * 1000.0
        attrs = " ".join(f"{k}={v}" for k, v in
                         sorted(s.get("attrs", {}).items()))
        err = f" ERROR={s['error']}" if s.get("error") else ""
        indent = "  " * depth(s)
        print(f"  {off_ms:9.2f}ms {s.get('durationMs', -1):9.2f}ms "
              f"{(s.get('component') or '-'):7s} "
              f"{(s.get('server') or '-'):21s} "
              f"{indent}{s.get('name', '?')}"
              + (f" [{attrs}]" if attrs else "") + err, file=out)


@command("trace.dump",
         "gather a trace's spans from every server it touched "
         "(-trace=<id> [-server=addr,addr] [-json])")
def trace_dump(env, args, out):
    trace_id = ""
    extra: list[str] = []
    as_json = False
    for a in args:
        if a.startswith("-trace="):
            trace_id = a.split("=", 1)[1]
        elif a.startswith("-server="):
            extra.extend(x for x in a.split("=", 1)[1].split(",") if x)
        elif a == "-json":
            as_json = True
        elif not a.startswith("-") and not trace_id:
            trace_id = a  # bare positional id
    if not trace_id:
        raise RuntimeError("usage: trace.dump -trace=<trace id> "
                           "[-server=host:port,...] [-json]")
    spans, targets = gather_trace(env, trace_id, extra)
    if as_json:
        print(json.dumps({"traceId": trace_id, "spans": spans}, indent=2),
              file=out)
        return
    servers = sorted({s.get("server") or "?" for s in spans})
    print(f"trace {trace_id}: {len(spans)} span(s) from "
          f"{len(servers)} server(s) (queried {len(targets)})", file=out)
    print(f"  servers: {', '.join(servers)}", file=out)
    print("   startOff   duration comp    server                span",
          file=out)
    _render(spans, out)
