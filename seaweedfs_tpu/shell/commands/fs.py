"""fs.* shell commands: filer namespace browsing and metadata tools.

Rebuild of /root/reference/weed/shell/command_fs_*.go (fs.ls, fs.cd,
fs.pwd, fs.cat, fs.du, fs.mkdir, fs.rm, fs.mv, fs.meta.save,
fs.meta.load, fs.meta.cat).
"""

from __future__ import annotations

import struct

from ...pb import filer_pb2, rpc
from ..registry import command


def _stub(env):
    return rpc.filer_stub(rpc.grpc_address(env.require_filer()))


def _resolve(env, arg: str | None) -> str:
    p = arg if arg else env.cwd
    if not p.startswith("/"):
        p = env.cwd.rstrip("/") + "/" + p
    while "//" in p:
        p = p.replace("//", "/")
    return p.rstrip("/") or "/"


def _list(env, directory: str):
    for resp in _stub(env).ListEntries(filer_pb2.ListEntriesRequest(
            directory=directory, limit=1 << 20)):
        yield resp.entry


def _find(env, path: str) -> filer_pb2.Entry | None:
    if path == "/":
        return filer_pb2.Entry(name="", is_directory=True)
    d, name = path.rsplit("/", 1)
    try:
        e = _stub(env).LookupDirectoryEntry(
            filer_pb2.LookupDirectoryEntryRequest(
                directory=d or "/", name=name), timeout=10).entry
    except Exception:
        return None
    return e if (e.name or e.is_directory) else None


@command("fs.pwd", "print current filer directory")
def fs_pwd(env, args, out):
    print(env.cwd, file=out)


@command("fs.cd", "fs.cd <dir>")
def fs_cd(env, args, out):
    path = _resolve(env, args[0] if args else "/")
    e = _find(env, path)
    if e is None or not e.is_directory:
        raise RuntimeError(f"{path}: not a directory")
    env.cwd = path


@command("fs.ls", "fs.ls [-l] [dir]")
def fs_ls(env, args, out):
    long_ = "-l" in args
    args = [a for a in args if not a.startswith("-")]
    path = _resolve(env, args[0] if args else None)
    for e in _list(env, path):
        if long_:
            kind = "d" if e.is_directory else "-"
            size = e.attributes.file_size or \
                max((c.offset + c.size for c in e.chunks), default=0)
            print(f"{kind} {e.attributes.file_mode & 0o7777:04o} "
                  f"{size:>12d} {e.name}", file=out)
        else:
            print(e.name + ("/" if e.is_directory else ""), file=out)


@command("fs.du", "fs.du [dir] — directory usage (bytes, files)")
def fs_du(env, args, out):
    path = _resolve(env, args[0] if args else None)

    def walk(d):
        files = size = 0
        for e in _list(env, d):
            if e.is_directory:
                f2, s2 = walk(d.rstrip("/") + "/" + e.name)
                files += f2
                size += s2
            else:
                files += 1
                size += e.attributes.file_size or \
                    max((c.offset + c.size for c in e.chunks), default=0)
        return files, size

    files, size = walk(path)
    print(f"{size:>14d} bytes  {files:>8d} files  {path}", file=out)


@command("fs.cat", "fs.cat <file>")
def fs_cat(env, args, out):
    import requests

    path = _resolve(env, args[0])
    from ...utils.http import requests_verify, url_for

    r = requests.get(url_for(env.require_filer(), path), timeout=60,
                     verify=requests_verify())
    if r.status_code != 200:
        raise RuntimeError(f"{path}: {r.status_code}")
    out.write(r.content.decode(errors="replace"))


@command("fs.mkdir", "fs.mkdir <dir>")
def fs_mkdir(env, args, out):
    path = _resolve(env, args[0])
    d, name = path.rsplit("/", 1)
    entry = filer_pb2.Entry(name=name, is_directory=True)
    entry.attributes.file_mode = 0o40775
    _stub(env).CreateEntry(filer_pb2.CreateEntryRequest(
        directory=d or "/", entry=entry), timeout=10)
    print(f"created {path}", file=out)


@command("fs.rm", "fs.rm [-r] <path>")
def fs_rm(env, args, out):
    recursive = "-r" in args or "-rf" in args
    args = [a for a in args if not a.startswith("-")]
    path = _resolve(env, args[0])
    d, name = path.rsplit("/", 1)
    resp = _stub(env).DeleteEntry(filer_pb2.DeleteEntryRequest(
        directory=d or "/", name=name, is_delete_data=True,
        is_recursive=recursive), timeout=60)
    if resp.error:
        raise RuntimeError(resp.error)
    print(f"removed {path}", file=out)


@command("fs.mv", "fs.mv <src> <dst>")
def fs_mv(env, args, out):
    src = _resolve(env, args[0])
    dst = _resolve(env, args[1])
    if _find(env, dst) is not None and _find(env, dst).is_directory:
        dst = dst.rstrip("/") + "/" + src.rsplit("/", 1)[-1]
    od, on = src.rsplit("/", 1)
    nd, nn = dst.rsplit("/", 1)
    _stub(env).AtomicRenameEntry(filer_pb2.AtomicRenameEntryRequest(
        old_directory=od or "/", old_name=on,
        new_directory=nd or "/", new_name=nn), timeout=60)
    print(f"moved {src} -> {dst}", file=out)


# -- metadata save/load (command_fs_meta_save.go) --------------------------
# File format: repeated [4-byte big-endian length][FullEntry proto] records,
# the same framing the reference writes.

@command("fs.meta.save", "fs.meta.save -o=meta.bin [dir]")
def fs_meta_save(env, args, out):
    output = "meta.bin"
    rest = []
    for a in args:
        if a.startswith("-o="):
            output = a[3:]
        else:
            rest.append(a)
    path = _resolve(env, rest[0] if rest else None)
    count = 0
    with open(output, "wb") as f:
        def walk(d):
            nonlocal count
            for e in _list(env, d):
                blob = filer_pb2.FullEntry(dir=d, entry=e) \
                    .SerializeToString()
                f.write(struct.pack(">I", len(blob)) + blob)
                count += 1
                if e.is_directory:
                    walk(d.rstrip("/") + "/" + e.name)

        walk(path)
    print(f"saved {count} entries from {path} to {output}", file=out)


@command("fs.meta.load", "fs.meta.load meta.bin")
def fs_meta_load(env, args, out):
    stub = _stub(env)
    count = 0
    with open(args[0], "rb") as f:
        while True:
            hdr = f.read(4)
            if len(hdr) < 4:
                break
            (n,) = struct.unpack(">I", hdr)
            fe = filer_pb2.FullEntry.FromString(f.read(n))
            stub.CreateEntry(filer_pb2.CreateEntryRequest(
                directory=fe.dir, entry=fe.entry), timeout=30)
            count += 1
    print(f"loaded {count} entries", file=out)


@command("fs.meta.cat", "fs.meta.cat <path> — print entry metadata")
def fs_meta_cat(env, args, out):
    path = _resolve(env, args[0])
    e = _find(env, path)
    if e is None:
        raise RuntimeError(f"{path}: not found")
    print(e, file=out)


@command("fs.meta.tail", "fs.meta.tail [-timeAgo=10s] [-pathPrefix=/]")
def fs_meta_tail(env, args, out):
    """Stream filer metadata events (command_fs_meta_tail.go); drains
    until the stream goes idle for 2s (non-interactive shells)."""
    import time as _time

    import grpc

    prefix = "/"
    ago_ns = 0
    for a in args:
        if a.startswith("-pathPrefix="):
            prefix = a.split("=", 1)[1]
        elif a.startswith("-timeAgo="):
            spec = a.split("=", 1)[1]
            mult = {"s": 1, "m": 60, "h": 3600}.get(spec[-1], 1)
            ago_ns = int(float(spec.rstrip("smh")) * mult * 1e9)
    stub = _stub(env)
    cursor = _time.time_ns() - ago_ns
    # timeout=2 is a per-stream deadline, not an idle timer: resume from
    # the cursor until a whole window passes with no new events
    while True:
        got_any = False
        try:
            for resp in stub.SubscribeMetadata(
                    filer_pb2.SubscribeMetadataRequest(
                        client_name="fs.meta.tail", path_prefix=prefix,
                        since_ns=cursor), timeout=2):
                got_any = True
                cursor = max(cursor, resp.ts_ns)
                ev = resp.event_notification
                kind = ("update" if ev.old_entry.name and ev.new_entry.name
                        else "create" if ev.new_entry.name else "delete")
                name = ev.new_entry.name or ev.old_entry.name
                print(f"{resp.ts_ns} {kind} {resp.directory}/{name}",
                      file=out)
        except grpc.RpcError as e:
            if e.code() != grpc.StatusCode.DEADLINE_EXCEEDED:
                raise
        if not got_any:
            return


@command("fs.configure",
         "fs.configure [-locationPrefix=/p -collection=c -replication=XYZ] "
         "[-apply]")
def fs_configure(env, args, out):
    """Per-path storage rules stored at /etc/seaweedfs/filer.conf
    (command_fs_configure.go + filer_conf.go)."""
    import json as _json
    import time as _time

    stub = _stub(env)
    conf = {"locations": []}
    try:
        resp = stub.LookupDirectoryEntry(
            filer_pb2.LookupDirectoryEntryRequest(
                directory="/etc/seaweedfs", name="filer.conf"), timeout=10)
        if resp.entry.content:
            conf = _json.loads(resp.entry.content)
    except Exception:
        pass
    opts = {}
    apply_ = "-apply" in args
    for a in args:
        if a.startswith("-") and "=" in a:
            k, _, v = a[1:].partition("=")
            opts[k] = v
    if "locationPrefix" in opts:
        loc = {"location_prefix": opts["locationPrefix"]}
        for k in ("collection", "replication", "ttl", "disk_type"):
            if opts.get(k):
                loc[k] = opts[k]
        conf["locations"] = [l for l in conf["locations"]
                             if l["location_prefix"] != loc["location_prefix"]]
        conf["locations"].append(loc)
        if apply_:
            entry = filer_pb2.Entry(
                name="filer.conf",
                content=_json.dumps(conf, indent=2).encode())
            entry.attributes.file_mode = 0o644
            entry.attributes.mtime = int(_time.time())
            stub.CreateEntry(filer_pb2.CreateEntryRequest(
                directory="/etc/seaweedfs", entry=entry), timeout=10)
        else:
            # reference semantics: dry run unless -apply
            print("(dry run; add -apply to persist)", file=out)
    print(_json.dumps(conf, indent=2), file=out)


@command("mount.configure", "mount.configure -dir=/p -quotaMB=n")
def mount_configure(env, args, out):
    """Mount quota config persisted in the filer
    (command_mount_configure.go)."""
    import json as _json
    import time as _time

    stub = _stub(env)
    opts = {}
    for a in args:
        if a.startswith("-") and "=" in a:
            k, _, v = a[1:].partition("=")
            opts[k] = v
    conf = {}
    try:
        resp = stub.LookupDirectoryEntry(
            filer_pb2.LookupDirectoryEntryRequest(
                directory="/etc/seaweedfs", name="mount.conf"), timeout=10)
        if resp.entry.content:
            conf = _json.loads(resp.entry.content)
    except Exception:
        pass
    if "dir" in opts:
        conf[opts["dir"]] = {"quotaMB": int(opts.get("quotaMB", 0))}
        entry = filer_pb2.Entry(name="mount.conf",
                                content=_json.dumps(conf, indent=2).encode())
        entry.attributes.file_mode = 0o644
        entry.attributes.mtime = int(_time.time())
        stub.CreateEntry(filer_pb2.CreateEntryRequest(
            directory="/etc/seaweedfs", entry=entry), timeout=10)
    print(_json.dumps(conf, indent=2), file=out)


@command("fs.tree", "fs.tree [dir] — recursively print the directory tree")
def fs_tree(env, args, out):
    """command_fs_tree.go."""
    root = _resolve(env, args[0] if args else None)
    dirs = files = 0

    def walk(d: str, indent: str) -> None:
        nonlocal dirs, files
        entries = sorted(_list(env, d), key=lambda e: e.name)
        for i, e in enumerate(entries):
            last = i == len(entries) - 1
            branch = "└──" if last else "├──"
            print(f"{indent}{branch} {e.name}"
                  + ("/" if e.is_directory else ""), file=out)
            if e.is_directory:
                dirs += 1
                walk(f"{d.rstrip('/')}/{e.name}",
                     indent + ("    " if last else "│   "))
            else:
                files += 1

    print(root, file=out)
    walk(root, "")
    print(f"{dirs} directories, {files} files", file=out)


@command("fs.verify",
         "fs.verify [-v] [dir] — check every chunk of every file is readable")
def fs_verify(env, args, out):
    """command_fs_verify.go: walk the tree and probe each referenced chunk
    on its volume server."""
    import requests

    flags = [a for a in args if a.startswith("-")]
    rest = [a for a in args if not a.startswith("-")]
    verbose = "-v" in flags
    root = _resolve(env, rest[0] if rest else None)
    total = bad = 0

    def check_file(path: str, entry) -> None:
        nonlocal total, bad
        for c in entry.chunks:
            fid = c.file_id or (
                f"{c.fid.volume_id},{c.fid.file_key:x}{c.fid.cookie:08x}")
            total += 1
            # a chunk is missing only if NO replica serves it — one down
            # replica of a healthy volume is not data loss
            ok = False
            try:
                urls = env.master_client.lookup_file_id(fid)
            except Exception:
                urls = []
            for url in urls:
                try:
                    if requests.head(url, timeout=10).status_code == 200:
                        ok = True
                        break
                except Exception:
                    continue
            if not ok:
                bad += 1
                print(f"  MISSING {path} chunk {fid}", file=out)
            elif verbose:
                print(f"  ok {path} chunk {fid}", file=out)

    def walk(d: str) -> None:
        for e in _list(env, d):
            full = f"{d.rstrip('/')}/{e.name}"
            if e.is_directory:
                walk(full)
            else:
                check_file(full, e)

    walk(root)
    print(f"verified {total} chunks, {bad} missing/corrupt", file=out)
    if bad:
        raise RuntimeError(f"{bad} of {total} chunks failed verification")


@command("fs.meta.changeVolumeId",
         "fs.meta.changeVolumeId -mapping=old1:new1,old2:new2 [dir] [-apply]")
def fs_meta_change_volume_id(env, args, out):
    """command_fs_meta_change_volume_id.go: rewrite chunk volume ids in
    file metadata after volumes were renumbered/migrated."""
    from ..registry import kv_flags

    opts = kv_flags(args)
    apply = "apply" in opts
    rest = [a for a in args if not a.startswith("-")]
    mapping = {}
    for pair in filter(None, opts.get("mapping", "").split(",")):
        old, _, new = pair.partition(":")
        mapping[int(old)] = int(new)
    if not mapping:
        raise RuntimeError("need -mapping=old:new[,old2:new2]")
    root = _resolve(env, rest[0] if rest else None)
    stub = _stub(env)
    changed = 0

    def rewrite(e) -> bool:
        touched = False
        for c in e.chunks:
            vid = c.fid.volume_id if c.fid.volume_id else (
                int(c.file_id.split(",")[0]) if c.file_id else 0)
            if vid in mapping:
                new = mapping[vid]
                if c.file_id:
                    c.file_id = f"{new},{c.file_id.split(',', 1)[1]}"
                if c.fid.volume_id:
                    c.fid.volume_id = new
                touched = True
        return touched

    def walk(d: str) -> None:
        nonlocal changed
        for e in _list(env, d):
            full = f"{d.rstrip('/')}/{e.name}"
            if e.is_directory:
                walk(full)
            elif rewrite(e):
                changed += 1
                print(f"  {'updated' if apply else 'would update'} {full}",
                      file=out)
                if apply:
                    stub.UpdateEntry(filer_pb2.UpdateEntryRequest(
                        directory=d, entry=e), timeout=10)

    walk(root)
    print(f"{changed} entries {'updated' if apply else 'to update'}"
          + ("" if apply else " (rerun with -apply)"), file=out)


@command("fs.meta.notify",
         "fs.meta.notify [dir] — re-publish create events for a tree")
def fs_meta_notify(env, args, out):
    """command_fs_meta_notify.go: resend metadata as notification events
    (e.g. to prime a freshly configured notification backend). The shell
    loads notification.toml itself, exactly like the reference command."""
    from ...notification import current_queue, load_configuration
    from ...utils.config import load_config

    q = load_configuration(load_config("notification")) or current_queue()
    if q is None:
        raise RuntimeError("no notification queue configured "
                           "(see notification.toml)")
    root = _resolve(env, args[0] if args else None)
    sent = 0

    def walk(d: str) -> None:
        nonlocal sent
        for e in _list(env, d):
            full = f"{d.rstrip('/')}/{e.name}"
            ev = filer_pb2.EventNotification()
            ev.new_entry.CopyFrom(e)
            q.send_message(full, ev)
            sent += 1
            if e.is_directory:
                walk(full)

    walk(root)
    print(f"notified {sent} entries under {root}", file=out)
