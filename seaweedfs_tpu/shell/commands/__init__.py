from . import cluster, collection, ec, fs, lock, remote, volume  # noqa: F401
