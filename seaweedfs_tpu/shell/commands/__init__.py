from . import cluster, collection, ec, lock, volume  # noqa: F401
