from . import (  # noqa: F401
    cluster,
    collection,
    ec,
    fs,
    lock,
    qos_cmd,
    remote,
    s3_mq,
    trace_cmd,
    volume,
)
