"""remote.* shell commands: cloud-tier mounts.

Rebuild of /root/reference/weed/shell/command_remote_*.go
(remote.configure, remote.mount, remote.unmount, remote.meta.sync,
remote.cache, remote.uncache).
"""

from __future__ import annotations

import json

from ...remote_storage import RemoteConf, RemoteGateway
from ..registry import command, kv_flags as _kv


@command("remote.configure",
         "remote.configure -name=x -type=local|s3|gcs|azure|b2 "
         "[-root=... | -endpoint=... -bucket=... -access_key=... "
         "-secret_key=... | -bucket=... -token=... | -container=... "
         "-account=... -key=... | -bucket=... -key_id=... "
         "-application_key=...]")
def remote_configure(env, args, out):
    opts = _kv(args)
    conf = RemoteConf(env.require_filer())
    if not opts:
        print(json.dumps(conf.load().get("storages", {}), indent=2),
              file=out)
        return
    name = opts.pop("name")
    storage = {"type": opts.pop("type", "local"), **opts}
    conf.configure_storage(name, storage)
    print(f"configured remote storage {name}", file=out)


@command("remote.mount",
         "remote.mount -dir=/buckets/x -remote=name/path")
def remote_mount(env, args, out):
    opts = _kv(args)
    conf = RemoteConf(env.require_filer())
    if not opts:
        print(json.dumps(conf.load().get("mounts", {}), indent=2), file=out)
        return
    directory = opts["dir"]
    storage, _, remote_path = opts["remote"].partition("/")
    conf.mount(directory, storage, remote_path or "/")
    synced = RemoteGateway(env.require_filer()).sync_dir(directory)
    print(f"mounted {directory} -> {opts['remote']} ({synced} entries)",
          file=out)


@command("remote.unmount", "remote.unmount -dir=/buckets/x")
def remote_unmount(env, args, out):
    opts = _kv(args)
    RemoteConf(env.require_filer()).unmount(opts["dir"])
    print(f"unmounted {opts['dir']}", file=out)


@command("remote.meta.sync", "remote.meta.sync -dir=/buckets/x")
def remote_meta_sync(env, args, out):
    opts = _kv(args)
    n = RemoteGateway(env.require_filer()).sync_dir(opts["dir"])
    print(f"synced {n} entries", file=out)


@command("remote.cache", "remote.cache -dir=/buckets/x/file")
def remote_cache(env, args, out):
    """command_remote_cache.go: the filer does the remote fetch; the
    shell speaks the same CacheRemoteObjectToLocalCluster gRPC a stock
    client would."""
    from ...pb import filer_pb2, rpc

    opts = _kv(args)
    d, _, name = opts["dir"].rpartition("/")
    stub = rpc.filer_stub(rpc.grpc_address(env.require_filer()))
    resp = stub.CacheRemoteObjectToLocalCluster(
        filer_pb2.CacheRemoteObjectToLocalClusterRequest(
            directory=d or "/", name=name), timeout=300)
    size = max((c.offset + c.size for c in resp.entry.chunks),
               default=resp.entry.attributes.file_size)
    print(f"cached {size} bytes", file=out)


@command("remote.uncache", "remote.uncache -dir=/buckets/x/file")
def remote_uncache(env, args, out):
    opts = _kv(args)
    RemoteGateway(env.require_filer()).uncache(opts["dir"])
    print(f"uncached {opts['dir']}", file=out)





@command("remote.mount.buckets",
         "remote.mount.buckets -remote=<storage> [-apply]")
def remote_mount_buckets(env, args, out):
    """command_remote_mount_buckets.go: discover the remote storage's
    top-level buckets and mount each under /buckets/<bucket>."""
    opts = _kv(args)
    storage = opts.get("remote", "")
    if not storage:
        raise RuntimeError("usage: remote.mount.buckets -remote=<storage>")
    apply = "apply" in opts
    conf = RemoteConf(env.require_filer())
    all_conf = conf.load()
    if storage not in all_conf.get("storages", {}):
        raise RuntimeError(f"unknown remote storage {storage!r}")
    from ...remote_storage import new_client

    client = new_client(all_conf["storages"][storage])
    buckets = sorted({e.path.lstrip("/").split("/", 1)[0]
                      for e in client.traverse("")})
    mounted = 0
    for b in buckets:
        directory = f"/buckets/{b}"
        if directory in all_conf.get("mounts", {}):
            continue
        if apply:
            conf.mount(directory, storage, b)
            synced = RemoteGateway(env.require_filer()).sync_dir(directory)
            print(f"mounted {directory} -> {storage}/{b} "
                  f"({synced} entries)", file=out)
        else:
            print(f"would mount {directory} -> {storage}/{b} "
                  f"(rerun with -apply)", file=out)
        mounted += 1
    if not mounted:
        print("no unmounted buckets found", file=out)
