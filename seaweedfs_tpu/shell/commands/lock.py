"""lock / unlock — exclusive admin lease (weed/shell/command_lock_unlock.go)."""

from __future__ import annotations

from ..registry import command


@command("lock", "acquire the exclusive cluster admin lock")
def lock(env, args, out):
    env.acquire_lock()
    print("acquired cluster admin lock", file=out)


@command("unlock", "release the exclusive cluster admin lock")
def unlock(env, args, out):
    env.release_lock()
    print("released cluster admin lock", file=out)
