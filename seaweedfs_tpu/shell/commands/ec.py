"""EC lifecycle commands: ec.encode / ec.rebuild / ec.balance / ec.decode.

Rebuild of /root/reference/weed/shell/command_ec_encode.go:57-188,
command_ec_rebuild.go:58-230, command_ec_balance.go, command_ec_decode.go.
The encode hot loop itself runs on the volume server's TPU coder; these
commands orchestrate the shard lifecycle over gRPC exactly like the
reference shell does.

Addition over the reference: `-dataShards/-parityShards` flags (geometry is
hard-coded to 10+4 in the reference, SURVEY.md §2.2).
"""

from __future__ import annotations

import argparse
import os
from collections import defaultdict
from concurrent.futures import ThreadPoolExecutor

from ...pb import (
    ec_geometry_pb2 as eg,
    ec_stream_pb2 as es,
    master_pb2,
    volume_server_pb2 as vs,
)
from ..registry import command


def _collect_ec_nodes(env, topo=None):
    """-> [(url, free_slots, shard_count)] sorted by free slots desc
    (collectEcNodes / sortEcNodesByFreeslotsDecending)."""
    nodes = []
    for dn in env.collect_data_nodes(topo):
        free = shards = 0
        for disk in dn.disk_infos.values():
            free += disk.free_volume_count
            for e in disk.ec_shard_infos:
                shards += bin(e.ec_index_bits).count("1")
        nodes.append([dn.id, free, shards])
    nodes.sort(key=lambda n: -n[1])
    return nodes


def _volume_locations(env, vid: int) -> list[str]:
    resp = env.master_stub().LookupVolume(
        master_pb2.LookupVolumeRequest(volume_or_file_ids=[str(vid)]), timeout=10)
    for e in resp.volume_id_locations:
        return [l.url for l in e.locations]
    return []


def _ec_shard_holders(env, vid: int) -> dict[int, list[str]]:
    resp = env.master_stub().LookupEcVolume(
        master_pb2.LookupEcVolumeRequest(volume_id=vid), timeout=10)
    return {sl.shard_id: [l.url for l in sl.locations]
            for sl in resp.shard_id_locations}


@command("ec.encode", "erasure-code one volume (or a whole collection)")
def ec_encode(env, args, out):
    p = argparse.ArgumentParser(prog="ec.encode")
    p.add_argument("-volumeId", type=int, default=0)
    p.add_argument("-collection", default="")
    p.add_argument("-fullPercent", type=float, default=95.0)
    p.add_argument("-dataShards", type=int, default=0)
    p.add_argument("-parityShards", type=int, default=0)
    p.add_argument("-geometry", default="",
                   help="code geometry name from the registry "
                        "(models/geometry.py), e.g. rs_10_4 (default) or "
                        "lrc_10_2_2 — locally-repairable: single-shard "
                        "repair reads 5 survivors instead of 10")
    p.add_argument("-parallelCopy", type=int, default=10)
    p.add_argument("-parallelEncode", type=int, default=4,
                   help="volumes erasure-coded concurrently; concurrent "
                        "VolumeEcShardsGenerate pipelines on one server "
                        "coalesce into stacked device dispatches "
                        "(ops/dispatch.py)")
    p.add_argument("-stream", type=int, default=None, choices=(0, 1),
                   help="pipelined encode+distribute (ISSUE 6): placement "
                        "is computed BEFORE encoding and each "
                        "destination's shards stream to it while the GF "
                        "matmul runs (default on; env escape hatch "
                        "SWFS_EC_STREAM=0). 0 = classic "
                        "generate-then-copy")
    opts = p.parse_args(args)
    env.confirm_is_locked()
    _validate_geometry_opt(opts, out)

    from ...utils import trace

    vids = ([opts.volumeId] if opts.volumeId
            else _collect_full_volume_ids(env, opts.collection, opts.fullPercent))
    if not vids:
        print("no volumes qualify for ec encoding", file=out)
        return
    if opts.parallelEncode <= 1 or len(vids) == 1:
        for vid in vids:
            # root a trace per conversion: the generate/stream RPCs and
            # every destination's sink work become one dumpable tree
            with trace.span("shell.ec.encode", component="shell",
                            vid=vid) as tsp:
                _do_ec_encode(env, vid, opts, out)
            if tsp.trace_id:
                print(f"trace {tsp.trace_id} "
                      f"(trace.dump -trace={tsp.trace_id})", file=out)
        return
    # encode volumes concurrently: the per-volume shard lifecycle is
    # independent, and overlapping the servers' encode pipelines is what
    # lets the EC dispatch scheduler amortize device round-trips across
    # volumes. Placement shares one in-flight load ledger — concurrent
    # encoders see the same pre-copy topology snapshot, so without it
    # every thread would crown the same emptiest node/rack and pile all
    # volumes' shards there. Failures surface after every volume had its
    # attempt.
    errors: list[tuple[int, Exception]] = []
    shared = _SharedPlacement()

    def one(vid):
        try:
            with trace.span("shell.ec.encode", component="shell",
                            vid=vid):
                _do_ec_encode(env, vid, opts, out, shared=shared)
        except Exception as e:  # KeyboardInterrupt/SystemExit still abort
            errors.append((vid, e))

    with ThreadPoolExecutor(max_workers=opts.parallelEncode) as ex:
        list(ex.map(one, vids))
    for vid, e in errors:
        print(f"volume {vid}: ec encode failed: {e}", file=out)
    if errors:
        raise errors[0][1]


def _collect_full_volume_ids(env, collection: str, full_percent: float) -> list[int]:
    """Full + quiet volumes (collectVolumeIdsForEcEncode,
    command_ec_encode.go:271)."""
    resp = env.volume_list()
    limit = resp.volume_size_limit_mb * 1024 * 1024
    vids = []
    for dn in env.collect_data_nodes():
        for disk in dn.disk_infos.values():
            for v in disk.volume_infos:
                if collection and v.collection != collection:
                    continue
                if limit and v.size >= limit * full_percent / 100.0:
                    vids.append(v.id)
    return sorted(set(vids))


class _SharedPlacement:
    """Cross-thread ledger of shard placements already decided by THIS
    ec.encode invocation but not yet visible in topology heartbeats:
    node/rack counts that concurrent volumes' placement loops fold into
    their sort keys so the load spreads instead of piling onto whichever
    node the shared stale snapshot ranks emptiest."""

    def __init__(self):
        import threading

        self.lock = threading.Lock()
        self.node_load: dict[str, int] = defaultdict(int)
        self.rack_load: dict[tuple[str, str], int] = defaultdict(int)


def _validate_geometry_opt(opts, out) -> None:
    """Registry-backed -geometry validation (ISSUE 11): fail fast in the
    shell, before any replica is frozen, with the registered names in
    the error."""
    if not getattr(opts, "geometry", ""):
        return
    from ...models import geometry as geom_mod

    try:
        cg = geom_mod.get(opts.geometry)
    except ValueError as e:
        print(str(e), file=out)
        raise
    if not cg.volume_capable:
        msg = (f"geometry {opts.geometry!r} is not volume-capable "
               f"(stripe-level codec only); volume-capable: "
               f"{[n for n in geom_mod.names() if geom_mod.get(n).volume_capable]}")
        print(msg, file=out)
        raise ValueError(msg)
    if (opts.dataShards and opts.dataShards != cg.data_shards) or \
            (opts.parityShards and opts.parityShards != cg.parity_shards):
        msg = (f"geometry {opts.geometry!r} is {cg.data_shards}+"
               f"{cg.parity_shards}; -dataShards/-parityShards disagree")
        print(msg, file=out)
        raise ValueError(msg)


def _geometry_total_shards(opts) -> int:
    if getattr(opts, "geometry", ""):
        from ...models import geometry as geom_mod

        return geom_mod.get(opts.geometry).total_shards
    return (opts.dataShards or 10) + (opts.parityShards or 4)


def _stream_enabled(opts) -> bool:
    """-stream flag wins; else SWFS_EC_STREAM env (default on)."""
    if getattr(opts, "stream", None) is not None:
        return bool(opts.stream)
    return os.environ.get("SWFS_EC_STREAM", "1").lower() not in (
        "0", "false", "off")


def _plan_placement(env, total_shards: int, shared) -> dict[str, list[int]]:
    """Spread shards across servers (balancedEcDistribution), rack-aware:
    losing one rack must cost as few shards of this volume as possible
    (the reference README's "rack-aware placement";
    pickRackToBalanceShardsInto in command_ec_balance.go). In streaming
    mode this runs BEFORE the encode so shard bytes go straight to their
    destinations."""
    topo = env.volume_list().topology_info  # one snapshot for both views
    nodes = _collect_ec_nodes(env, topo)
    if not nodes:
        raise ValueError("no ec-capable nodes")
    racks = env.node_racks(topo)
    alloc: dict[str, list[int]] = defaultdict(list)
    rack_load: dict[tuple[str, str], int] = defaultdict(int)
    with shared.lock:
        for sid in range(total_shards):
            nodes.sort(key=lambda n: (
                rack_load[racks.get(n[0], ("", n[0]))]
                + shared.rack_load[racks.get(n[0], ("", n[0]))],
                len(alloc[n[0]]) + shared.node_load[n[0]],
                -n[1]))
            chosen = nodes[0][0]
            alloc[chosen].append(sid)
            rack_load[racks.get(chosen, ("", chosen))] += 1
        for node, sids in alloc.items():
            shared.node_load[node] += len(sids)
            shared.rack_load[racks.get(node, ("", node))] += len(sids)
    return alloc


def _do_ec_encode(env, vid: int, opts, out, shared=None) -> None:
    locations = _volume_locations(env, vid)
    if not locations:
        raise ValueError(f"volume {vid} not found in topology")
    source = locations[0]
    collection = opts.collection or _find_collection(env, vid)
    total_shards = _geometry_total_shards(opts)
    if shared is None:
        shared = _SharedPlacement()  # serial path: ledger is a no-op
    stream = _stream_enabled(opts)

    # 1. freeze writes on every replica (markVolumeReplicasWritable false)
    frozen: list[str] = []
    for addr in locations:
        env.volume_stub(addr).VolumeMarkReadonly(
            vs.VolumeMarkReadonlyRequest(volume_id=vid), timeout=30)
        frozen.append(addr)

    # 2+3. generate + distribute + mount. Any failure BEFORE the plain
    # volume is deleted rolls the replicas back to writable — the
    # conversion never happened, so the volume must not stay frozen
    # (pre-ISSUE-6 bug: every failed encode left read-only replicas).
    try:
        if stream:
            alloc = _do_stream_encode(env, vid, collection, source,
                                      total_shards, opts, shared, out)
        else:
            alloc = _do_copy_encode(env, vid, collection, source,
                                    total_shards, opts, shared, out)
    except BaseException:
        for addr in frozen:
            try:
                env.volume_stub(addr).VolumeMarkWritable(
                    vs.VolumeMarkWritableRequest(volume_id=vid),
                    timeout=30)
            except Exception:  # noqa: BLE001 — best-effort rollback
                pass
        raise

    # 4. retire moved shards from source + delete the plain volume
    moved = [sid for t, sids in alloc.items() if t != source for sid in sids]
    if moved:
        env.volume_stub(source).VolumeEcShardsDelete(
            vs.VolumeEcShardsDeleteRequest(
                volume_id=vid, collection=collection, shard_ids=moved),
            timeout=60)
    for addr in locations:
        env.volume_stub(addr).VolumeDelete(
            vs.VolumeDeleteRequest(volume_id=vid), timeout=60)
    spread = {t: sids for t, sids in alloc.items() if sids}
    print(f"volume {vid}: shards spread {dict(spread)}", file=out)


def _cleanup_targets(env, vid, collection, targets) -> None:
    """Best-effort unwind of a failed distribute: unmount + delete this
    volume's shards at every target so no destination keeps serving (or
    advertising) EC shards of a volume whose conversion is being rolled
    back to plain replicas."""
    for target in targets:
        try:
            env.volume_stub(target).VolumeEcShardsUnmount(
                vs.VolumeEcShardsUnmountRequest(
                    volume_id=vid, shard_ids=list(range(32))), timeout=60)
        except Exception:  # noqa: BLE001 — nothing may be mounted yet
            pass
        try:
            env.volume_stub(target).VolumeEcShardsDelete(
                vs.VolumeEcShardsDeleteRequest(
                    volume_id=vid, collection=collection,
                    shard_ids=list(range(32))), timeout=60)
        except Exception:  # noqa: BLE001 — best-effort cleanup
            pass


def _do_copy_encode(env, vid, collection, source, total_shards, opts,
                    shared, out) -> dict[str, list[int]]:
    """Classic three-phase path: generate all shards on the source, THEN
    copy them to their destinations, then mount."""
    env.volume_stub(source).VolumeEcShardsGenerate(
        eg.EcGenerateRequest(
            volume_id=vid, collection=collection,
            data_shards=opts.dataShards, parity_shards=opts.parityShards,
            geometry=getattr(opts, "geometry", "")),
        timeout=24 * 3600)
    print(f"volume {vid}: generated {total_shards} shards on {source}"
          + (f" ({opts.geometry})" if getattr(opts, "geometry", "")
             else ""),
          file=out)
    alloc = _plan_placement(env, total_shards, shared)

    def copy_to(target_and_sids):
        target, sids = target_and_sids
        if target != source:
            env.volume_stub(target).VolumeEcShardsCopy(
                vs.VolumeEcShardsCopyRequest(
                    volume_id=vid, collection=collection, shard_ids=sids,
                    copy_ecx_file=True, copy_ecj_file=True, copy_vif_file=True,
                    source_data_node=source), timeout=3600)
        env.volume_stub(target).VolumeEcShardsMount(
            vs.VolumeEcShardsMountRequest(
                volume_id=vid, collection=collection, shard_ids=sids),
            timeout=60)

    try:
        with ThreadPoolExecutor(max_workers=max(1, opts.parallelCopy)) as ex:
            list(ex.map(copy_to, alloc.items()))
    except BaseException:
        # one target's copy/mount failed AFTER others may have mounted:
        # un-advertise everything before the caller restores the plain
        # replicas to writable, or stale EC locations would shadow them
        _cleanup_targets(env, vid, collection,
                         [t for t in alloc if t != source])
        raise
    return alloc


def _do_stream_encode(env, vid, collection, source, total_shards, opts,
                      shared, out) -> dict[str, list[int]]:
    """ISSUE-6 pipelined path: placement FIRST, then one
    VolumeEcShardsGenerateStreamed that encodes and pushes each remote
    destination's shards to it while the GF matmul is still running. A
    destination the stream could not finish (even after slab-range
    resume) falls back to the classic copy — the source holds all shard
    files either way, so the conversion still completes."""
    alloc = _plan_placement(env, total_shards, shared)
    req = es.VolumeEcShardsGenerateStreamedRequest(
        volume_id=vid, collection=collection,
        data_shards=opts.dataShards, parity_shards=opts.parityShards,
        geometry=getattr(opts, "geometry", ""))
    for target, sids in alloc.items():
        if target != source and sids:
            req.targets.add(address=target, shard_ids=sids)
    try:
        resp = env.volume_stub(source).VolumeEcShardsGenerateStreamed(
            req, timeout=24 * 3600)
    except BaseException:
        # destinations may hold partially streamed .ecXX files with no
        # .ecx — clean them best-effort so a failed encode leaks
        # neither disk nor a stale shard set (the outer handler still
        # restores replica writability)
        _cleanup_targets(env, vid, collection,
                         [t.address for t in req.targets])
        raise
    failed = {r.address for r in resp.targets if not r.ok}
    resumed = sum(r.resumes for r in resp.targets)
    print(f"volume {vid}: streamed {total_shards} shards from {source} "
          f"({resp.bytes_streamed} bytes overlapped, overlap ratio "
          f"{resp.overlap_ratio:.2f}"
          + (f", {resumed} resume(s)" if resumed else "")
          + (f", fallback copy for {sorted(failed)}" if failed else "")
          + ")", file=out)

    def finish_target(target_and_sids):
        target, sids = target_and_sids
        if target != source:
            # streamed destinations only need the index files; failed
            # ones pull their shard bytes too (generate-then-copy)
            env.volume_stub(target).VolumeEcShardsCopy(
                vs.VolumeEcShardsCopyRequest(
                    volume_id=vid, collection=collection,
                    shard_ids=sids if target in failed else [],
                    copy_ecx_file=True, copy_ecj_file=True,
                    copy_vif_file=True, source_data_node=source),
                timeout=3600)
        env.volume_stub(target).VolumeEcShardsMount(
            vs.VolumeEcShardsMountRequest(
                volume_id=vid, collection=collection, shard_ids=sids),
            timeout=60)

    try:
        with ThreadPoolExecutor(max_workers=max(1, opts.parallelCopy)) as ex:
            list(ex.map(finish_target, alloc.items()))
    except BaseException:
        # mirror of _do_copy_encode: a failed mount/index-copy must not
        # leave other destinations' already-mounted shards advertised
        # while the plain replicas come back writable
        _cleanup_targets(env, vid, collection,
                         [t for t in alloc if t != source])
        raise
    return alloc


def _find_collection(env, vid: int) -> str:
    for dn in env.collect_data_nodes():
        for disk in dn.disk_infos.values():
            for v in disk.volume_infos:
                if v.id == vid:
                    return v.collection
    return ""


@command("ec.rebuild", "rebuild missing EC shards from survivors")
def ec_rebuild(env, args, out):
    p = argparse.ArgumentParser(prog="ec.rebuild")
    p.add_argument("-collection", default="")
    p.add_argument("-volumeId", type=int, default=0)
    opts = p.parse_args(args)
    env.confirm_is_locked()

    from ...models import geometry as geom_mod

    vols = _all_ec_volumes(env, opts.collection)
    for vid, holders in sorted(vols.items()):
        if opts.volumeId and vid != opts.volumeId:
            continue
        collection = _find_ec_collection(env, vid)
        d, p, code = _ec_geometry(env, vid, holders, collection)
        if not code:
            # no holder's .vif was readable: planning blind would copy a
            # survivor set the rebuilder may not be able to solve from
            print(f"volume {vid}: cannot read volume geometry (.vif) "
                  f"from any shard holder — skipping rebuild", file=out)
            continue
        total = d + p
        present = set(holders)
        if len(present) >= total:
            continue
        geom = geom_mod.get(code)
        missing = tuple(i for i in range(total) if i not in present)
        try:
            plan = geom.repair_plan(missing, tuple(sorted(present)))
        except (geom_mod.UnsolvableError, ValueError):
            print(f"volume {vid} ({code}): only {len(present)} shards "
                  f"left, cannot rebuild {list(missing)}", file=out)
            continue
        _rebuild_one(env, vid, holders, missing, plan, code, collection,
                     out)


def _all_ec_volumes(env, collection: str = "",
                    topo=None) -> dict[int, dict[int, list[str]]]:
    """vid -> shard -> [holders] from topology (EcShardMap.registerEcNode)."""
    vols: dict[int, dict[int, list[str]]] = defaultdict(lambda: defaultdict(list))
    for dn in env.collect_data_nodes(topo):
        for disk in dn.disk_infos.values():
            for e in disk.ec_shard_infos:
                if collection and e.collection != collection:
                    continue
                for sid in range(32):
                    if e.ec_index_bits >> sid & 1:
                        vols[e.id][sid].append(dn.id)
    return {vid: dict(m) for vid, m in vols.items()}


def _ec_vif(env, vid: int, holders: dict[int, list[str]],
            collection: str) -> dict:
    """Read the volume's .vif sidecar from any shard holder over the
    CopyFile RPC — the shard-set metadata (shard counts AND code
    geometry, ISSUE 11) is readable without mounting anything."""
    import json

    addrs = sorted({a for hs in holders.values() for a in hs})
    for addr in addrs:
        buf = bytearray()
        try:
            for chunk in env.volume_stub(addr).CopyFile(
                    vs.CopyFileRequest(
                        volume_id=vid, ext=".vif", collection=collection,
                        is_ec_volume=True,
                        ignore_source_file_not_found=True), timeout=30):
                buf += chunk.file_content
        except Exception:  # noqa: BLE001 — try the next holder
            continue
        if buf:
            try:
                return json.loads(bytes(buf))
            except ValueError:
                continue
    return {}


def _ec_geometry(env, vid: int, holders=None, collection="") -> tuple:
    """(data, parity, code_name) from a holder's .vif.

    code_name is "" when NO holder's .vif could be read — callers that
    PLAN from the geometry (ec.rebuild) must treat that as an error
    rather than assume RS: mis-planning an lrc volume as rs copies a
    survivor set the rebuilder cannot solve from. (A .vif that parses
    but predates the geometry field is legitimately RS.)"""
    vif = _ec_vif(env, vid, holders or {}, collection) if holders else {}
    if not vif:
        return 10, 4, ""
    d = vif.get("dataShards", 10)
    p = vif.get("parityShards", 4)
    return d, p, vif.get("geometry", "") or f"rs_{d}_{p}"


def _rebuild_one(env, vid: int, holders: dict[int, list[str]],
                 missing: tuple[int, ...], plan, code: str,
                 collection: str, out) -> None:
    # rebuilder: node with most free slots (command_ec_rebuild.go:132)
    rebuilder = _collect_ec_nodes(env)[0][0]
    local = {sid for sid, hs in holders.items() if rebuilder in hs}
    # minimal-read copy set (ISSUE 11): only the survivors the repair
    # plan actually reads travel to the rebuilder — under lrc_10_2_2 a
    # single lost group shard moves 5 shards' bytes, not 10-13
    to_copy = [sid for sid in plan.reads
               if sid not in local and holders.get(sid)]
    copied = []
    for sid in to_copy:
        env.volume_stub(rebuilder).VolumeEcShardsCopy(
            vs.VolumeEcShardsCopyRequest(
                volume_id=vid, collection=collection, shard_ids=[sid],
                copy_ecx_file=not local and not copied,
                copy_ecj_file=not local and not copied,
                copy_vif_file=not local and not copied,
                source_data_node=holders[sid][0]), timeout=3600)
        copied.append(sid)
    resp = env.volume_stub(rebuilder).VolumeEcShardsRebuild(
        eg.EcRebuildRequest(volume_id=vid, collection=collection,
                            shard_ids=list(missing)),
        timeout=24 * 3600)
    rebuilt = list(resp.rebuilt_shard_ids)
    env.volume_stub(rebuilder).VolumeEcShardsMount(
        vs.VolumeEcShardsMountRequest(volume_id=vid, collection=collection,
                                      shard_ids=rebuilt), timeout=60)
    # drop the temporary survivor copies, keep what was rebuilt + already local
    drop = [sid for sid in copied if sid not in rebuilt]
    if drop:
        env.volume_stub(rebuilder).VolumeEcShardsDelete(
            vs.VolumeEcShardsDeleteRequest(volume_id=vid, collection=collection,
                                           shard_ids=drop), timeout=60)
    geom_used = getattr(resp, "geometry", "") or code
    print(f"volume {vid}: rebuilt shards {rebuilt} on {rebuilder} "
          f"(geometry {geom_used}, read {len(plan.reads)} survivors, "
          f"{resp.survivor_bytes_read} bytes)", file=out)


def _find_ec_collection(env, vid: int) -> str:
    for dn in env.collect_data_nodes():
        for disk in dn.disk_infos.values():
            for e in disk.ec_shard_infos:
                if e.id == vid:
                    return e.collection
    return ""


@command("ec.balance", "even out EC shard distribution across servers")
def ec_balance(env, args, out):
    p = argparse.ArgumentParser(prog="ec.balance")
    p.add_argument("-collection", default="")
    p.add_argument("-apply", action="store_true",
                   help="actually move shards (dry-run by default)")
    opts = p.parse_args(args)
    env.confirm_is_locked()

    topo = env.volume_list().topology_info  # one snapshot for all views
    vols = _all_ec_volumes(env, opts.collection, topo)
    shard_count: dict[str, int] = defaultdict(int)
    for vid, m in vols.items():
        for sid, hs in m.items():
            for h in hs:
                shard_count[h] += 1
    nodes = [n[0] for n in _collect_ec_nodes(env, topo)]
    for n in nodes:
        shard_count.setdefault(n, 0)
    if not shard_count:
        print("no ec shards in cluster", file=out)
        return
    avg = sum(shard_count.values()) / len(shard_count)
    racks = env.node_racks(topo)
    moves = []
    for vid, m in sorted(vols.items()):
        collection = _find_ec_collection(env, vid)
        # rack -> how many of THIS volume's shards it already holds
        vol_rack: dict[tuple[str, str], int] = defaultdict(int)
        for sid, hs in m.items():
            for h in hs:
                vol_rack[racks.get(h, ("", h))] += 1
        for sid, hs in sorted(m.items()):
            src = hs[0]
            if shard_count[src] <= avg + 1:
                continue
            # among nodes with headroom, prefer the emptiest rack for this
            # volume, then the emptiest node (pickRackToBalanceShardsInto);
            # filtering by headroom FIRST keeps the rack preference from
            # selecting a full node and skipping the move entirely
            cands = [n for n in shard_count
                     if n not in hs and shard_count[n] < avg]
            dst = min(cands,
                      key=lambda n: (vol_rack[racks.get(n, ("", n))],
                                     shard_count[n]),
                      default=None)
            if dst is None:
                continue
            moves.append((vid, collection, sid, src, dst))
            shard_count[src] -= 1
            shard_count[dst] += 1
            vol_rack[racks.get(dst, ("", dst))] += 1
            vol_rack[racks.get(src, ("", src))] -= 1
    for vid, collection, sid, src, dst in moves:
        print(f"move volume {vid} shard {sid}: {src} -> {dst}", file=out)
        if not opts.apply:
            continue
        env.volume_stub(dst).VolumeEcShardsCopy(
            vs.VolumeEcShardsCopyRequest(
                volume_id=vid, collection=collection, shard_ids=[sid],
                copy_ecx_file=True, copy_ecj_file=True, copy_vif_file=True,
                source_data_node=src), timeout=3600)
        env.volume_stub(dst).VolumeEcShardsMount(
            vs.VolumeEcShardsMountRequest(volume_id=vid, collection=collection,
                                          shard_ids=[sid]), timeout=60)
        env.volume_stub(src).VolumeEcShardsUnmount(
            vs.VolumeEcShardsUnmountRequest(volume_id=vid, shard_ids=[sid]),
            timeout=60)
        env.volume_stub(src).VolumeEcShardsDelete(
            vs.VolumeEcShardsDeleteRequest(volume_id=vid, collection=collection,
                                           shard_ids=[sid]), timeout=60)
    if not moves:
        print("ec shards already balanced", file=out)


@command("ec.decode", "decode an EC volume back into a normal volume")
def ec_decode(env, args, out):
    p = argparse.ArgumentParser(prog="ec.decode")
    p.add_argument("-volumeId", type=int, required=True)
    p.add_argument("-collection", default="")
    opts = p.parse_args(args)
    env.confirm_is_locked()
    vid = opts.volumeId

    holders = _ec_shard_holders(env, vid)
    if not holders:
        raise ValueError(f"ec volume {vid} not found")
    collection = opts.collection or _find_ec_collection(env, vid)
    # gather every shard onto the server already holding the most
    counts: dict[str, int] = defaultdict(int)
    for hs in holders.values():
        for h in hs:
            counts[h] += 1
    target = max(counts, key=counts.get)
    first_copy = True
    for sid, hs in sorted(holders.items()):
        if target in hs:
            continue
        env.volume_stub(target).VolumeEcShardsCopy(
            vs.VolumeEcShardsCopyRequest(
                volume_id=vid, collection=collection, shard_ids=[sid],
                copy_ecx_file=first_copy, copy_ecj_file=first_copy,
                copy_vif_file=first_copy, source_data_node=hs[0]),
            timeout=3600)
        first_copy = False
    env.volume_stub(target).VolumeEcShardsToVolume(
        vs.VolumeEcShardsToVolumeRequest(volume_id=vid, collection=collection),
        timeout=24 * 3600)
    # retire shards everywhere
    all_servers = {h for hs in holders.values() for h in hs} | {target}
    for addr in all_servers:
        env.volume_stub(addr).VolumeEcShardsUnmount(
            vs.VolumeEcShardsUnmountRequest(
                volume_id=vid, shard_ids=list(range(32))), timeout=60)
        env.volume_stub(addr).VolumeEcShardsDelete(
            vs.VolumeEcShardsDeleteRequest(
                volume_id=vid, collection=collection,
                shard_ids=list(range(32))), timeout=60)
    print(f"volume {vid}: decoded back to a normal volume on {target}", file=out)
