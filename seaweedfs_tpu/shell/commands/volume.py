"""volume.* admin commands (reference: weed/shell/command_volume_*.go)."""

from __future__ import annotations

import argparse
from collections import defaultdict

from ...pb import master_pb2, volume_server_pb2 as vs
from ..registry import command


@command("volume.list", "print the cluster volume topology")
def volume_list(env, args, out):
    resp = env.volume_list()
    topo = resp.topology_info
    for dc in topo.data_center_infos:
        print(f"DataCenter {dc.id}", file=out)
        for rack in dc.rack_infos:
            print(f"  Rack {rack.id}", file=out)
            for dn in rack.data_node_infos:
                vols = ecs = 0
                for disk in dn.disk_infos.values():
                    vols += len(disk.volume_infos)
                    ecs += len(disk.ec_shard_infos)
                print(f"    DataNode {dn.id} volumes:{vols} ecShards:{ecs}",
                      file=out)
                for disk in dn.disk_infos.values():
                    for v in disk.volume_infos:
                        print(f"      volume id:{v.id} size:{v.size} "
                              f"collection:{v.collection!r} "
                              f"file_count:{v.file_count} "
                              f"deleted:{v.delete_count} "
                              f"read_only:{v.read_only}", file=out)
                    for e in disk.ec_shard_infos:
                        sids = [i for i in range(32) if e.ec_index_bits >> i & 1]
                        print(f"      ec volume id:{e.id} "
                              f"collection:{e.collection!r} shards:{sids}",
                              file=out)


@command("volume.vacuum", "compact volumes above a garbage threshold")
def volume_vacuum(env, args, out):
    p = argparse.ArgumentParser(prog="volume.vacuum")
    p.add_argument("-garbageThreshold", type=float, default=0.3)
    p.add_argument("-volumeId", type=int, default=0)
    opts = p.parse_args(args)
    env.master_stub().VacuumVolume(
        master_pb2.VacuumVolumeRequest(
            garbage_threshold=opts.garbageThreshold,
            volume_id=opts.volumeId), timeout=3600)
    print("vacuum triggered", file=out)


@command("volume.mark", "mark a volume readonly/writable on a server")
def volume_mark(env, args, out):
    p = argparse.ArgumentParser(prog="volume.mark")
    p.add_argument("-node", required=True)
    p.add_argument("-volumeId", type=int, required=True)
    g = p.add_mutually_exclusive_group(required=True)
    g.add_argument("-readonly", action="store_true")
    g.add_argument("-writable", action="store_true")
    opts = p.parse_args(args)
    stub = env.volume_stub(opts.node)
    if opts.readonly:
        stub.VolumeMarkReadonly(
            vs.VolumeMarkReadonlyRequest(volume_id=opts.volumeId), timeout=30)
    else:
        stub.VolumeMarkWritable(
            vs.VolumeMarkWritableRequest(volume_id=opts.volumeId), timeout=30)
    print(f"volume {opts.volumeId} marked", file=out)


@command("volume.delete", "delete a volume from a server")
def volume_delete(env, args, out):
    p = argparse.ArgumentParser(prog="volume.delete")
    p.add_argument("-node", required=True)
    p.add_argument("-volumeId", type=int, required=True)
    opts = p.parse_args(args)
    env.confirm_is_locked()
    env.volume_stub(opts.node).VolumeDelete(
        vs.VolumeDeleteRequest(volume_id=opts.volumeId), timeout=60)
    print(f"volume {opts.volumeId} deleted from {opts.node}", file=out)


@command("volume.copy", "copy a volume from one server to another")
def volume_copy(env, args, out):
    p = argparse.ArgumentParser(prog="volume.copy")
    p.add_argument("-from", dest="src", required=True)
    p.add_argument("-to", dest="dst", required=True)
    p.add_argument("-volumeId", type=int, required=True)
    opts = p.parse_args(args)
    env.confirm_is_locked()
    for resp in env.volume_stub(opts.dst).VolumeCopy(
            vs.VolumeCopyRequest(volume_id=opts.volumeId,
                                 source_data_node=opts.src), timeout=24 * 3600):
        if resp.processed_bytes:
            print(f"  copied {resp.processed_bytes} bytes", file=out)
    print(f"volume {opts.volumeId}: {opts.src} -> {opts.dst}", file=out)


@command("volume.move", "move a volume between servers (copy + delete)")
def volume_move(env, args, out):
    p = argparse.ArgumentParser(prog="volume.move")
    p.add_argument("-from", dest="src", required=True)
    p.add_argument("-to", dest="dst", required=True)
    p.add_argument("-volumeId", type=int, required=True)
    opts = p.parse_args(args)
    env.confirm_is_locked()
    for _ in env.volume_stub(opts.dst).VolumeCopy(
            vs.VolumeCopyRequest(volume_id=opts.volumeId,
                                 source_data_node=opts.src), timeout=24 * 3600):
        pass
    env.volume_stub(opts.src).VolumeDelete(
        vs.VolumeDeleteRequest(volume_id=opts.volumeId), timeout=60)
    print(f"volume {opts.volumeId} moved {opts.src} -> {opts.dst}", file=out)


def _replica_index(env):
    """vid -> {server: VolumeInformationMessage} + replica placement."""
    index: dict[int, dict[str, master_pb2.VolumeInformationMessage]] = defaultdict(dict)
    for dn in env.collect_data_nodes():
        for disk in dn.disk_infos.values():
            for v in disk.volume_infos:
                index[v.id][dn.id] = v
    return index


@command("volume.fix.replication", "re-replicate under-replicated volumes")
def volume_fix_replication(env, args, out):
    p = argparse.ArgumentParser(prog="volume.fix.replication")
    p.add_argument("-apply", action="store_true")
    opts = p.parse_args(args)
    env.confirm_is_locked()
    index = _replica_index(env)
    all_nodes = [dn.id for dn in env.collect_data_nodes()]
    fixes = 0
    for vid, replicas in sorted(index.items()):
        any_info = next(iter(replicas.values()))
        want = _copy_count(any_info.replica_placement)
        have = len(replicas)
        if have >= want:
            continue
        candidates = [n for n in all_nodes if n not in replicas]
        if not candidates:
            print(f"volume {vid}: under-replicated ({have}/{want}) "
                  f"but no free server", file=out)
            continue
        src = next(iter(replicas))
        dst = candidates[0]
        print(f"volume {vid}: {have}/{want} replicas; copy {src} -> {dst}",
              file=out)
        fixes += 1
        if opts.apply:
            for _ in env.volume_stub(dst).VolumeCopy(
                    vs.VolumeCopyRequest(volume_id=vid, source_data_node=src),
                    timeout=24 * 3600):
                pass
    if not fixes:
        print("all volumes sufficiently replicated", file=out)


def _copy_count(rp_byte: int) -> int:
    return rp_byte // 100 + rp_byte // 10 % 10 + rp_byte % 10 + 1


@command("volume.balance", "even out volume counts across servers")
def volume_balance(env, args, out):
    p = argparse.ArgumentParser(prog="volume.balance")
    p.add_argument("-apply", action="store_true")
    opts = p.parse_args(args)
    env.confirm_is_locked()
    counts: dict[str, list[int]] = {}
    for dn in env.collect_data_nodes():
        vids = []
        for disk in dn.disk_infos.values():
            vids.extend(v.id for v in disk.volume_infos)
        counts[dn.id] = vids
    if not counts:
        return
    avg = sum(len(v) for v in counts.values()) / len(counts)
    moves = []
    replica_idx = _replica_index(env)
    for src, vids in sorted(counts.items(), key=lambda kv: -len(kv[1])):
        while len(vids) > avg + 0.5:
            dst = min(counts, key=lambda n: len(counts[n]))
            if len(counts[dst]) + 1 > avg + 0.5 or dst == src:
                break
            vid = next((v for v in vids if dst not in replica_idx[v]), None)
            if vid is None:
                break
            moves.append((vid, src, dst))
            vids.remove(vid)
            counts[dst].append(vid)
    for vid, src, dst in moves:
        print(f"move volume {vid}: {src} -> {dst}", file=out)
        if opts.apply:
            for _ in env.volume_stub(dst).VolumeCopy(
                    vs.VolumeCopyRequest(volume_id=vid, source_data_node=src),
                    timeout=24 * 3600):
                pass
            env.volume_stub(src).VolumeDelete(
                vs.VolumeDeleteRequest(volume_id=vid), timeout=60)
    if not moves:
        print("volumes already balanced", file=out)


@command("volume.check.disk", "cross-check replica contents of every volume")
def volume_check_disk(env, args, out):
    """Digest-manifest replica check (command_volume_check_disk.go — but
    where the reference ships file-id lists, this compares per-needle
    digest manifests via the VolumeDigest RPC: ~20 bytes per volume when
    replicas agree, ~16 bytes per needle only when they don't). Also
    covers EC volumes: per-shard whole-file CRCs are cross-checked for
    every shard id held by more than one server, and a holder with a
    full local shard set gets a syndrome verify (detect-only)."""
    from ...pb import scrub_pb2

    p = argparse.ArgumentParser(prog="volume.check.disk")
    p.add_argument("-volumeId", type=int, default=0)
    p.add_argument("-slow", action="store_true",
                   help="also syndrome-verify EC volumes on their holders")
    opts = p.parse_args(args)
    index = _replica_index(env)
    issues = 0
    for vid, replicas in sorted(index.items()):
        if opts.volumeId and vid != opts.volumeId:
            continue
        if len(replicas) < 2:
            continue
        digests = {}
        for server in replicas:
            d = env.volume_stub(server).VolumeDigest(
                scrub_pb2.VolumeDigestRequest(volume_id=vid), timeout=60)
            # rolling CRC covers live entries; tombstone_count is
            # informational only (deletion HISTORY may differ between
            # converged replicas — e.g. one vacuumed)
            digests[server] = (d.rolling_crc, d.needle_count)
        if len(set(digests.values())) > 1:
            issues += 1
            print(f"volume {vid} replicas diverge: {digests}", file=out)
            # name the diverging needles: entry lists ship only now
            entries = {}
            for server in replicas:
                d = env.volume_stub(server).VolumeDigest(
                    scrub_pb2.VolumeDigestRequest(
                        volume_id=vid, include_entries=True), timeout=120)
                entries[server] = {e.needle_id: (e.crc, e.size)
                                   for e in d.entries}
            all_ids = set()
            for m in entries.values():
                all_ids |= m.keys()

            def norm(nid):
                # tombstone ≈ absent: deletion history may legitimately
                # differ between converged replicas
                vals = set()
                for m in entries.values():
                    got = m.get(nid)
                    vals.add(None if got is not None and got[1] < 0
                             else got)
                return vals

            diverging = [nid for nid in sorted(all_ids)
                         if len(norm(nid)) > 1]
            for nid in diverging[:20]:
                print(f"  needle {nid:x}: "
                      + ", ".join(f"{s}={entries[s].get(nid)}"
                                  for s in sorted(entries)), file=out)
            if len(diverging) > 20:
                print(f"  ... and {len(diverging) - 20} more", file=out)
    # EC volumes: shard-integrity coverage (the old check skipped them)
    ec_holders: dict[int, dict[str, dict[int, tuple[int, int]]]] = {}
    ec_cols: dict[int, str] = {}
    for dn in env.collect_data_nodes():
        for disk in dn.disk_infos.values():
            for e in disk.ec_shard_infos:
                if opts.volumeId and e.id != opts.volumeId:
                    continue
                ec_cols.setdefault(e.id, e.collection)
                try:
                    d = env.volume_stub(dn.id).VolumeDigest(
                        scrub_pb2.VolumeDigestRequest(volume_id=e.id),
                        timeout=120)
                except Exception as ex:  # noqa: BLE001 — keep checking
                    print(f"ec volume {e.id} on {dn.id}: digest failed: "
                          f"{ex}", file=out)
                    continue
                ec_holders.setdefault(e.id, {})[dn.id] = {
                    s.shard_id: (s.crc, s.size) for s in d.shard_digests}
    for vid, holders in sorted(ec_holders.items()):
        by_shard: dict[int, dict[str, tuple[int, int]]] = {}
        for server, shards in holders.items():
            for sid, cs in shards.items():
                by_shard.setdefault(sid, {})[server] = cs
        # report the code geometry the check operates on (ISSUE 11):
        # readable from any holder's .vif — mixed-geometry clusters name
        # each volume's layout explicitly
        from .ec import _ec_geometry

        hmap = {sid: sorted(copies) for sid, copies in by_shard.items()}
        d, pshards, code = _ec_geometry(env, vid, hmap,
                                        ec_cols.get(vid, ""))
        print(f"ec volume {vid}: geometry "
              f"{code or 'unknown (.vif unreadable)'} "
              f"({d}+{pshards}), {len(by_shard)} shard ids on "
              f"{len(holders)} holder(s)", file=out)
        for sid, copies in sorted(by_shard.items()):
            if len(copies) > 1 and len(set(copies.values())) > 1:
                issues += 1
                print(f"ec volume {vid} shard {sid} copies diverge: "
                      f"{copies}", file=out)
        if opts.slow:
            # the holder with the most shards runs the syndrome verify;
            # when no holder has a full local set, the scrub plane's
            # cross-server gather (ISSUE 13) fetches a repair-plan's
            # worth of survivor ranges from peers — a split volume is
            # VERIFIED, never skipped (the pre-ISSUE-13 gap)
            best = max(holders, key=lambda s: len(holders[s]))
            split = len(holders[best]) < len(by_shard)
            r = env.volume_stub(best).VolumeScrub(
                scrub_pb2.VolumeScrubRequest(volume_id=vid, full=True),
                timeout=3600)
            bad = [f for f in r.findings if f.kind == "ec_parity"]
            if bad:
                issues += len(bad)
                for f in bad:
                    print(f"ec volume {vid}: {f.detail} "
                          f"(shard {f.shard_id}, {f.state})", file=out)
            elif r.bytes_verified:
                print(f"ec volume {vid}: syndrome verified clean via "
                      f"{best}"
                      + (" (cross-server gather)" if split else ""),
                      file=out)
            else:
                issues += 1
                print(f"ec volume {vid}: syndrome verify could not "
                      f"cover the volume from {best}", file=out)
    print(f"{issues} integrity issue(s) found", file=out)


@command("volume.scrub",
         "volume.scrub -node=<server> [-volumeId=n] [-full] [-detectOnly] "
         "| -status")
def volume_scrub(env, args, out):
    """On-demand integrity pass (and status view) of one volume server's
    scrub plane: needle CRC sweep + EC syndrome verify + anti-entropy,
    with findings escalated into self-healing repair unless -detectOnly."""
    from ...pb import scrub_pb2

    p = argparse.ArgumentParser(prog="volume.scrub")
    p.add_argument("-node", required=True)
    p.add_argument("-volumeId", type=int, default=0)
    p.add_argument("-full", action="store_true",
                   help="ignore the sweep cursor, verify from offset 0")
    p.add_argument("-detectOnly", action="store_true",
                   help="report findings without repairing")
    p.add_argument("-status", action="store_true",
                   help="show cursors/findings instead of scrubbing")
    opts = p.parse_args(args)
    stub = env.volume_stub(opts.node)
    if opts.status:
        st = stub.ScrubStatus(scrub_pb2.ScrubStatusRequest(), timeout=30)
        print(f"running:{st.running} sweeps:{st.sweeps_completed} "
              f"suspectBacklog:{st.suspect_backlog}", file=out)
        for c in st.cursors:
            print(f"  cursor vol {c.volume_id}: offset {c.offset} "
                  f"(sweeps {c.sweeps})", file=out)
        for f in st.findings:
            print(f"  finding vol {f.volume_id} {f.kind} "
                  f"needle={f.needle_id:x} shard={f.shard_id} "
                  f"[{f.state}] {f.detail}", file=out)
        return
    r = stub.VolumeScrub(scrub_pb2.VolumeScrubRequest(
        volume_id=opts.volumeId, full=opts.full,
        repair=not opts.detectOnly), timeout=3600)
    print(f"scrubbed {r.volumes_scrubbed} volume(s): "
          f"{r.needles_checked} needles, {r.bytes_verified} bytes, "
          f"{len(r.findings)} finding(s), {r.repaired} repaired"
          + (f", {r.skipped_pairs} peer pair(s) skipped"
             if r.skipped_pairs else ""), file=out)
    for f in r.findings:
        print(f"  vol {f.volume_id} {f.kind} needle={f.needle_id:x} "
              f"shard={f.shard_id} [{f.state}] {f.detail}", file=out)


@command("volumeServer.evacuate", "move everything off one volume server")
def volume_server_evacuate(env, args, out):
    p = argparse.ArgumentParser(prog="volumeServer.evacuate")
    p.add_argument("-node", required=True)
    p.add_argument("-apply", action="store_true")
    opts = p.parse_args(args)
    env.confirm_is_locked()
    targets = [dn.id for dn in env.collect_data_nodes() if dn.id != opts.node]
    if not targets:
        raise ValueError("no other servers to evacuate to")
    index = _replica_index(env)
    i = 0
    for vid, replicas in sorted(index.items()):
        if opts.node not in replicas:
            continue
        dst = next((t for t in targets[i:] + targets[:i]
                    if t not in replicas), None)
        i = (i + 1) % len(targets)
        if dst is None:
            print(f"volume {vid}: no destination without a replica", file=out)
            continue
        print(f"move volume {vid}: {opts.node} -> {dst}", file=out)
        if opts.apply:
            for _ in env.volume_stub(dst).VolumeCopy(
                    vs.VolumeCopyRequest(volume_id=vid,
                                         source_data_node=opts.node),
                    timeout=24 * 3600):
                pass
            env.volume_stub(opts.node).VolumeDelete(
                vs.VolumeDeleteRequest(volume_id=vid), timeout=60)


@command("volumeServer.leave", "ask a volume server to stop heartbeating")
def volume_server_leave(env, args, out):
    p = argparse.ArgumentParser(prog="volumeServer.leave")
    p.add_argument("-node", required=True)
    opts = p.parse_args(args)
    env.volume_stub(opts.node).VolumeServerLeave(
        vs.VolumeServerLeaveRequest(), timeout=30)
    print(f"{opts.node} asked to leave", file=out)


@command("volume.tier.upload", "move a sealed volume's .dat to a tier backend")
def volume_tier_upload(env, args, out):
    """command_volume_tier_upload.go: .dat -> remote backend, reads
    range-fetch afterward."""
    p = argparse.ArgumentParser(prog="volume.tier.upload")
    p.add_argument("-node", required=True)
    p.add_argument("-volumeId", type=int, required=True)
    p.add_argument("-dest", required=True, help="tier backend name")
    p.add_argument("-keepLocalDatFile", action="store_true")
    opts = p.parse_args(args)
    env.confirm_is_locked()
    stub = env.volume_stub(opts.node)
    for resp in stub.VolumeTierMoveDatToRemote(
            vs.VolumeTierMoveDatToRemoteRequest(
                volume_id=opts.volumeId,
                destination_backend_name=opts.dest,
                keep_local_dat_file=opts.keepLocalDatFile), timeout=3600):
        print(f"moved {resp.processed} bytes "
              f"({resp.processed_percentage:.0f}%)", file=out)


@command("volume.tier.download", "bring a tiered volume's .dat back to disk")
def volume_tier_download(env, args, out):
    """command_volume_tier_download.go."""
    p = argparse.ArgumentParser(prog="volume.tier.download")
    p.add_argument("-node", required=True)
    p.add_argument("-volumeId", type=int, required=True)
    p.add_argument("-keepRemoteDatFile", action="store_true")
    opts = p.parse_args(args)
    env.confirm_is_locked()
    stub = env.volume_stub(opts.node)
    for resp in stub.VolumeTierMoveDatFromRemote(
            vs.VolumeTierMoveDatFromRemoteRequest(
                volume_id=opts.volumeId,
                keep_remote_dat_file=opts.keepRemoteDatFile), timeout=3600):
        print(f"downloaded {resp.processed} bytes "
              f"({resp.processed_percentage:.0f}%)", file=out)


@command("volume.mount", "volume.mount -node=<server> -volumeId=<n>")
def volume_mount(env, args, out):
    p = argparse.ArgumentParser(prog="volume.mount")
    p.add_argument("-node", required=True)
    p.add_argument("-volumeId", type=int, required=True)
    opts = p.parse_args(args)
    env.volume_stub(opts.node).VolumeMount(
        vs.VolumeMountRequest(volume_id=opts.volumeId), timeout=30)
    print(f"mounted volume {opts.volumeId} on {opts.node}", file=out)


@command("volume.unmount", "volume.unmount -node=<server> -volumeId=<n>")
def volume_unmount(env, args, out):
    p = argparse.ArgumentParser(prog="volume.unmount")
    p.add_argument("-node", required=True)
    p.add_argument("-volumeId", type=int, required=True)
    opts = p.parse_args(args)
    env.volume_stub(opts.node).VolumeUnmount(
        vs.VolumeUnmountRequest(volume_id=opts.volumeId), timeout=30)
    print(f"unmounted volume {opts.volumeId} on {opts.node}", file=out)


@command("volume.configure.replication",
         "volume.configure.replication -volumeId=<n> -replication=XYZ")
def volume_configure_replication(env, args, out):
    """command_volume_configure_replication.go: rewrite a volume's replica
    placement on every server holding it."""
    p = argparse.ArgumentParser(prog="volume.configure.replication")
    p.add_argument("-volumeId", type=int, required=True)
    p.add_argument("-replication", required=True)
    opts = p.parse_args(args)
    env.confirm_is_locked()
    changed = 0
    for dn in env.collect_data_nodes():
        for disk in dn.disk_infos.values():
            for v in disk.volume_infos:
                if v.id == opts.volumeId:
                    env.volume_stub(dn.id).VolumeConfigure(
                        vs.VolumeConfigureRequest(
                            volume_id=opts.volumeId,
                            replication=opts.replication), timeout=30)
                    changed += 1
    if not changed:
        raise RuntimeError(f"volume {opts.volumeId} not found")
    print(f"configured replication={opts.replication} on {changed} replicas",
          file=out)


@command("volume.grow",
         "volume.grow [-collection=c] [-replication=XYZ] [-count=n]")
def volume_grow(env, args, out):
    """command_volume_grow semantics via the master's grow endpoint."""
    import requests

    from ...utils.http import requests_verify, url_for

    p = argparse.ArgumentParser(prog="volume.grow")
    p.add_argument("-collection", default="")
    p.add_argument("-replication", default="")
    p.add_argument("-count", type=int, default=1)
    opts = p.parse_args(args)
    r = requests.get(
        url_for(env.master, "/vol/grow"),
        params={"collection": opts.collection,
                "replication": opts.replication,
                "count": opts.count}, timeout=60,
        verify=requests_verify()).json()
    if "error" in r:
        raise RuntimeError(r["error"])
    print(f"grew {r.get('count', 0)} volumes", file=out)


@command("volume.fsck",
         "volume.fsck [-verbose] — cross-check filer chunks vs volumes")
def volume_fsck(env, args, out):
    """command_volume_fsck.go (simplified): walk the filer namespace,
    verify every referenced chunk's volume exists in the topology and the
    needle is readable; report dangling references."""
    import requests

    from ...pb import filer_pb2
    from ...pb import rpc as _rpc

    verbose = "-verbose" in args
    stub = _rpc.filer_stub(_rpc.grpc_address(env.require_filer()))
    topo = env.volume_list().topology_info
    known_vids = set()
    for dc in topo.data_center_infos:
        for rack in dc.rack_infos:
            for dn in rack.data_node_infos:
                for disk in dn.disk_infos.values():
                    known_vids.update(v.id for v in disk.volume_infos)
                    known_vids.update(
                        ec.id for ec in disk.ec_shard_infos)
    checked = missing_vol = unreadable = 0

    def walk(d):
        nonlocal checked, missing_vol, unreadable
        for resp in stub.ListEntries(filer_pb2.ListEntriesRequest(
                directory=d, limit=1 << 20)):
            e = resp.entry
            path = d.rstrip("/") + "/" + e.name
            if e.is_directory:
                walk(path)
                continue
            for c in e.chunks:
                checked += 1
                vid = int(c.file_id.split(",")[0])
                if vid not in known_vids:
                    missing_vol += 1
                    print(f"  {path}: chunk {c.file_id}: volume {vid} "
                          f"not in topology", file=out)
                    continue
                if verbose:
                    urls = env.master_client.lookup_file_id(c.file_id)
                    r = requests.head(urls[0], timeout=10)
                    if r.status_code != 200:
                        unreadable += 1
                        print(f"  {path}: chunk {c.file_id}: HTTP "
                              f"{r.status_code}", file=out)

    walk("/")
    print(f"checked {checked} chunks: {missing_vol} dangling volume refs, "
          f"{unreadable} unreadable", file=out)


@command("volume.delete.empty",
         "volume.delete.empty [-quietFor=24h] [-force]  (drop 0-file volumes)")
def volume_delete_empty(env, args, out):
    """command_volume_delete_empty.go: delete volumes that hold no live
    files and have been quiet long enough."""
    import time as _time

    p = argparse.ArgumentParser(prog="volume.delete.empty")
    p.add_argument("-quietFor", default="24h")
    p.add_argument("-force", action="store_true")
    opts = p.parse_args(args)
    env.confirm_is_locked()
    from ..registry import parse_duration

    cutoff = _time.time() - parse_duration(opts.quietFor, flag="-quietFor")
    deleted = 0
    for dn in env.collect_data_nodes():
        for disk in dn.disk_infos.values():
            for v in disk.volume_infos:
                if v.file_count - v.delete_count > 0:
                    continue
                if v.modified_at_second and v.modified_at_second > cutoff:
                    continue
                if opts.force:
                    env.volume_stub(dn.id).VolumeDelete(
                        vs.VolumeDeleteRequest(volume_id=v.id), timeout=60)
                    print(f"deleted empty volume {v.id} on {dn.id}", file=out)
                else:
                    print(f"would delete empty volume {v.id} on {dn.id} "
                          f"(rerun with -force)", file=out)
                deleted += 1
    if not deleted:
        print("no empty volumes", file=out)


@command("volume.tier.move",
         "volume.tier.move -fromDiskType=hdd -toDiskType=ssd "
         "[-collection=x] [-apply]")
def volume_tier_move(env, args, out):
    """command_volume_tier_move.go: migrate volumes onto servers that have
    the target disk type (copy there, delete at the source)."""
    p = argparse.ArgumentParser(prog="volume.tier.move")
    p.add_argument("-fromDiskType", default="")
    p.add_argument("-toDiskType", required=True)
    p.add_argument("-collection", default="")
    p.add_argument("-apply", action="store_true")
    opts = p.parse_args(args)
    env.confirm_is_locked()

    def norm(dt: str) -> str:
        return "" if dt in ("", "hdd") else dt

    # destination servers offering the target disk type, with room
    dests = []
    sources = []  # (vid, server)
    replicas = _replica_index(env)
    for dn in env.collect_data_nodes():
        for dtype, disk in dn.disk_infos.items():
            if norm(dtype) == norm(opts.toDiskType):
                free = disk.max_volume_count - disk.volume_count
                if free > 0:
                    dests.append([dn.id, free])
            if norm(dtype) == norm(opts.fromDiskType):
                for v in disk.volume_infos:
                    if opts.collection and v.collection != opts.collection:
                        continue
                    sources.append((v.id, dn.id))
    if not dests:
        print(f"no server offers disk type {opts.toDiskType!r} with room",
              file=out)
        return
    moved = 0
    for vid, src in sources:
        # a destination must not already hold this volume (ALREADY_EXISTS)
        holders = set(replicas.get(vid, {}))
        dest = next((d for d in dests
                     if d[0] != src and d[1] > 0 and d[0] not in holders),
                    None)
        if dest is None:
            print(f"volume {vid}: no destination with room", file=out)
            continue
        print(f"volume {vid}: {src} -> {dest[0]} ({opts.toDiskType})",
              file=out)
        if opts.apply:
            try:
                for _ in env.volume_stub(dest[0]).VolumeCopy(
                        vs.VolumeCopyRequest(volume_id=vid,
                                             source_data_node=src,
                                             disk_type=opts.toDiskType),
                        timeout=24 * 3600):
                    pass
                env.volume_stub(src).VolumeDelete(
                    vs.VolumeDeleteRequest(volume_id=vid), timeout=60)
            except Exception as e:  # keep moving the rest
                print(f"  volume {vid} move failed: {e}", file=out)
                continue
        moved += 1
        dest[1] -= 1
        replicas.setdefault(vid, {})[dest[0]] = None
    if not moved:
        print("nothing to move", file=out)


@command("volume.scrub.disable", "pause the master's fleet scrub driver")
def volume_scrub_disable(env, args, out):
    """Incident knob: stops the master from nudging servers to scrub
    (per-server daemons keep their own schedule; on-demand volume.scrub
    still works)."""
    from ...pb import scrub_pb2

    env.master_stub().DisableScrub(
        scrub_pb2.DisableScrubRequest(), timeout=10)
    print("disabled", file=out)


@command("volume.scrub.enable", "resume the master's fleet scrub driver")
def volume_scrub_enable(env, args, out):
    from ...pb import scrub_pb2

    env.master_stub().EnableScrub(
        scrub_pb2.EnableScrubRequest(), timeout=10)
    print("enabled", file=out)


@command("volume.vacuum.disable", "pause the master's periodic vacuum")
def volume_vacuum_disable(env, args, out):
    """command_volume_vacuum_disable.go via master DisableVacuum."""
    env.master_stub().DisableVacuum(
        master_pb2.DisableVacuumRequest(), timeout=10)
    print("disabled", file=out)


@command("volume.vacuum.enable", "resume the master's periodic vacuum")
def volume_vacuum_enable(env, args, out):
    """command_volume_vacuum_enable.go via master EnableVacuum."""
    env.master_stub().EnableVacuum(
        master_pb2.EnableVacuumRequest(), timeout=10)
    print("enabled", file=out)
