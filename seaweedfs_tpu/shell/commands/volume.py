"""volume.* admin commands (reference: weed/shell/command_volume_*.go)."""

from __future__ import annotations

import argparse
from collections import defaultdict

from ...pb import master_pb2, volume_server_pb2 as vs
from ..registry import command


@command("volume.list", "print the cluster volume topology")
def volume_list(env, args, out):
    resp = env.volume_list()
    topo = resp.topology_info
    for dc in topo.data_center_infos:
        print(f"DataCenter {dc.id}", file=out)
        for rack in dc.rack_infos:
            print(f"  Rack {rack.id}", file=out)
            for dn in rack.data_node_infos:
                vols = ecs = 0
                for disk in dn.disk_infos.values():
                    vols += len(disk.volume_infos)
                    ecs += len(disk.ec_shard_infos)
                print(f"    DataNode {dn.id} volumes:{vols} ecShards:{ecs}",
                      file=out)
                for disk in dn.disk_infos.values():
                    for v in disk.volume_infos:
                        print(f"      volume id:{v.id} size:{v.size} "
                              f"collection:{v.collection!r} "
                              f"file_count:{v.file_count} "
                              f"deleted:{v.delete_count} "
                              f"read_only:{v.read_only}", file=out)
                    for e in disk.ec_shard_infos:
                        sids = [i for i in range(32) if e.ec_index_bits >> i & 1]
                        print(f"      ec volume id:{e.id} "
                              f"collection:{e.collection!r} shards:{sids}",
                              file=out)


@command("volume.vacuum", "compact volumes above a garbage threshold")
def volume_vacuum(env, args, out):
    p = argparse.ArgumentParser(prog="volume.vacuum")
    p.add_argument("-garbageThreshold", type=float, default=0.3)
    p.add_argument("-volumeId", type=int, default=0)
    opts = p.parse_args(args)
    env.master_stub().VacuumVolume(
        master_pb2.VacuumVolumeRequest(
            garbage_threshold=opts.garbageThreshold,
            volume_id=opts.volumeId), timeout=3600)
    print("vacuum triggered", file=out)


@command("volume.mark", "mark a volume readonly/writable on a server")
def volume_mark(env, args, out):
    p = argparse.ArgumentParser(prog="volume.mark")
    p.add_argument("-node", required=True)
    p.add_argument("-volumeId", type=int, required=True)
    g = p.add_mutually_exclusive_group(required=True)
    g.add_argument("-readonly", action="store_true")
    g.add_argument("-writable", action="store_true")
    opts = p.parse_args(args)
    stub = env.volume_stub(opts.node)
    if opts.readonly:
        stub.VolumeMarkReadonly(
            vs.VolumeMarkReadonlyRequest(volume_id=opts.volumeId), timeout=30)
    else:
        stub.VolumeMarkWritable(
            vs.VolumeMarkWritableRequest(volume_id=opts.volumeId), timeout=30)
    print(f"volume {opts.volumeId} marked", file=out)


@command("volume.delete", "delete a volume from a server")
def volume_delete(env, args, out):
    p = argparse.ArgumentParser(prog="volume.delete")
    p.add_argument("-node", required=True)
    p.add_argument("-volumeId", type=int, required=True)
    opts = p.parse_args(args)
    env.confirm_is_locked()
    env.volume_stub(opts.node).VolumeDelete(
        vs.VolumeDeleteRequest(volume_id=opts.volumeId), timeout=60)
    print(f"volume {opts.volumeId} deleted from {opts.node}", file=out)


@command("volume.copy", "copy a volume from one server to another")
def volume_copy(env, args, out):
    p = argparse.ArgumentParser(prog="volume.copy")
    p.add_argument("-from", dest="src", required=True)
    p.add_argument("-to", dest="dst", required=True)
    p.add_argument("-volumeId", type=int, required=True)
    opts = p.parse_args(args)
    env.confirm_is_locked()
    for resp in env.volume_stub(opts.dst).VolumeCopy(
            vs.VolumeCopyRequest(volume_id=opts.volumeId,
                                 source_data_node=opts.src), timeout=24 * 3600):
        if resp.processed_bytes:
            print(f"  copied {resp.processed_bytes} bytes", file=out)
    print(f"volume {opts.volumeId}: {opts.src} -> {opts.dst}", file=out)


@command("volume.move", "move a volume between servers (copy + delete)")
def volume_move(env, args, out):
    p = argparse.ArgumentParser(prog="volume.move")
    p.add_argument("-from", dest="src", required=True)
    p.add_argument("-to", dest="dst", required=True)
    p.add_argument("-volumeId", type=int, required=True)
    opts = p.parse_args(args)
    env.confirm_is_locked()
    for _ in env.volume_stub(opts.dst).VolumeCopy(
            vs.VolumeCopyRequest(volume_id=opts.volumeId,
                                 source_data_node=opts.src), timeout=24 * 3600):
        pass
    env.volume_stub(opts.src).VolumeDelete(
        vs.VolumeDeleteRequest(volume_id=opts.volumeId), timeout=60)
    print(f"volume {opts.volumeId} moved {opts.src} -> {opts.dst}", file=out)


def _replica_index(env):
    """vid -> {server: VolumeInformationMessage} + replica placement."""
    index: dict[int, dict[str, master_pb2.VolumeInformationMessage]] = defaultdict(dict)
    for dn in env.collect_data_nodes():
        for disk in dn.disk_infos.values():
            for v in disk.volume_infos:
                index[v.id][dn.id] = v
    return index


@command("volume.fix.replication", "re-replicate under-replicated volumes")
def volume_fix_replication(env, args, out):
    p = argparse.ArgumentParser(prog="volume.fix.replication")
    p.add_argument("-apply", action="store_true")
    opts = p.parse_args(args)
    env.confirm_is_locked()
    index = _replica_index(env)
    all_nodes = [dn.id for dn in env.collect_data_nodes()]
    fixes = 0
    for vid, replicas in sorted(index.items()):
        any_info = next(iter(replicas.values()))
        want = _copy_count(any_info.replica_placement)
        have = len(replicas)
        if have >= want:
            continue
        candidates = [n for n in all_nodes if n not in replicas]
        if not candidates:
            print(f"volume {vid}: under-replicated ({have}/{want}) "
                  f"but no free server", file=out)
            continue
        src = next(iter(replicas))
        dst = candidates[0]
        print(f"volume {vid}: {have}/{want} replicas; copy {src} -> {dst}",
              file=out)
        fixes += 1
        if opts.apply:
            for _ in env.volume_stub(dst).VolumeCopy(
                    vs.VolumeCopyRequest(volume_id=vid, source_data_node=src),
                    timeout=24 * 3600):
                pass
    if not fixes:
        print("all volumes sufficiently replicated", file=out)


def _copy_count(rp_byte: int) -> int:
    return rp_byte // 100 + rp_byte // 10 % 10 + rp_byte % 10 + 1


@command("volume.balance", "even out volume counts across servers")
def volume_balance(env, args, out):
    p = argparse.ArgumentParser(prog="volume.balance")
    p.add_argument("-apply", action="store_true")
    opts = p.parse_args(args)
    env.confirm_is_locked()
    counts: dict[str, list[int]] = {}
    for dn in env.collect_data_nodes():
        vids = []
        for disk in dn.disk_infos.values():
            vids.extend(v.id for v in disk.volume_infos)
        counts[dn.id] = vids
    if not counts:
        return
    avg = sum(len(v) for v in counts.values()) / len(counts)
    moves = []
    replica_idx = _replica_index(env)
    for src, vids in sorted(counts.items(), key=lambda kv: -len(kv[1])):
        while len(vids) > avg + 0.5:
            dst = min(counts, key=lambda n: len(counts[n]))
            if len(counts[dst]) + 1 > avg + 0.5 or dst == src:
                break
            vid = next((v for v in vids if dst not in replica_idx[v]), None)
            if vid is None:
                break
            moves.append((vid, src, dst))
            vids.remove(vid)
            counts[dst].append(vid)
    for vid, src, dst in moves:
        print(f"move volume {vid}: {src} -> {dst}", file=out)
        if opts.apply:
            for _ in env.volume_stub(dst).VolumeCopy(
                    vs.VolumeCopyRequest(volume_id=vid, source_data_node=src),
                    timeout=24 * 3600):
                pass
            env.volume_stub(src).VolumeDelete(
                vs.VolumeDeleteRequest(volume_id=vid), timeout=60)
    if not moves:
        print("volumes already balanced", file=out)


@command("volume.check.disk", "cross-check replica contents of every volume")
def volume_check_disk(env, args, out):
    """Compare file counts + sizes across replicas
    (command_volume_check_disk.go, simplified to status-level checks)."""
    index = _replica_index(env)
    issues = 0
    for vid, replicas in sorted(index.items()):
        if len(replicas) < 2:
            continue
        statuses = {}
        for server in replicas:
            st = env.volume_stub(server).VolumeStatus(
                vs.VolumeStatusRequest(volume_id=vid), timeout=30)
            statuses[server] = (st.file_count, st.volume_size)
        if len(set(statuses.values())) > 1:
            issues += 1
            print(f"volume {vid} replicas diverge: {statuses}", file=out)
    print(f"{issues} volume(s) with diverging replicas", file=out)


@command("volumeServer.evacuate", "move everything off one volume server")
def volume_server_evacuate(env, args, out):
    p = argparse.ArgumentParser(prog="volumeServer.evacuate")
    p.add_argument("-node", required=True)
    p.add_argument("-apply", action="store_true")
    opts = p.parse_args(args)
    env.confirm_is_locked()
    targets = [dn.id for dn in env.collect_data_nodes() if dn.id != opts.node]
    if not targets:
        raise ValueError("no other servers to evacuate to")
    index = _replica_index(env)
    i = 0
    for vid, replicas in sorted(index.items()):
        if opts.node not in replicas:
            continue
        dst = next((t for t in targets[i:] + targets[:i]
                    if t not in replicas), None)
        i = (i + 1) % len(targets)
        if dst is None:
            print(f"volume {vid}: no destination without a replica", file=out)
            continue
        print(f"move volume {vid}: {opts.node} -> {dst}", file=out)
        if opts.apply:
            for _ in env.volume_stub(dst).VolumeCopy(
                    vs.VolumeCopyRequest(volume_id=vid,
                                         source_data_node=opts.node),
                    timeout=24 * 3600):
                pass
            env.volume_stub(opts.node).VolumeDelete(
                vs.VolumeDeleteRequest(volume_id=vid), timeout=60)


@command("volumeServer.leave", "ask a volume server to stop heartbeating")
def volume_server_leave(env, args, out):
    p = argparse.ArgumentParser(prog="volumeServer.leave")
    p.add_argument("-node", required=True)
    opts = p.parse_args(args)
    env.volume_stub(opts.node).VolumeServerLeave(
        vs.VolumeServerLeaveRequest(), timeout=30)
    print(f"{opts.node} asked to leave", file=out)


@command("volume.tier.upload", "move a sealed volume's .dat to a tier backend")
def volume_tier_upload(env, args, out):
    """command_volume_tier_upload.go: .dat -> remote backend, reads
    range-fetch afterward."""
    p = argparse.ArgumentParser(prog="volume.tier.upload")
    p.add_argument("-node", required=True)
    p.add_argument("-volumeId", type=int, required=True)
    p.add_argument("-dest", required=True, help="tier backend name")
    p.add_argument("-keepLocalDatFile", action="store_true")
    opts = p.parse_args(args)
    env.confirm_is_locked()
    stub = env.volume_stub(opts.node)
    for resp in stub.VolumeTierMoveDatToRemote(
            vs.VolumeTierMoveDatToRemoteRequest(
                volume_id=opts.volumeId,
                destination_backend_name=opts.dest,
                keep_local_dat_file=opts.keepLocalDatFile), timeout=3600):
        print(f"moved {resp.processed} bytes "
              f"({resp.processed_percentage:.0f}%)", file=out)


@command("volume.tier.download", "bring a tiered volume's .dat back to disk")
def volume_tier_download(env, args, out):
    """command_volume_tier_download.go."""
    p = argparse.ArgumentParser(prog="volume.tier.download")
    p.add_argument("-node", required=True)
    p.add_argument("-volumeId", type=int, required=True)
    p.add_argument("-keepRemoteDatFile", action="store_true")
    opts = p.parse_args(args)
    env.confirm_is_locked()
    stub = env.volume_stub(opts.node)
    for resp in stub.VolumeTierMoveDatFromRemote(
            vs.VolumeTierMoveDatFromRemoteRequest(
                volume_id=opts.volumeId,
                keep_remote_dat_file=opts.keepRemoteDatFile), timeout=3600):
        print(f"downloaded {resp.processed} bytes "
              f"({resp.processed_percentage:.0f}%)", file=out)
