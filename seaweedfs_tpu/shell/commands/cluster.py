"""cluster.* commands (reference: weed/shell/command_cluster_*.go)."""

from __future__ import annotations

import time

from ...pb import master_pb2, volume_server_pb2 as vs
from ..registry import command


@command("cluster.ps", "list cluster processes (masters, volume servers, filers, brokers)")
def cluster_ps(env, args, out):
    """command_cluster_ps.go: volume servers from topology, filers/brokers
    from the master's cluster membership (weed/cluster)."""
    filer_group = args[0] if args else ""
    print(f"master: {env.master}", file=out)
    for dn in env.collect_data_nodes():
        print(f"  volume server: {dn.id} (grpc :{dn.grpc_port})", file=out)
    for node_type in ("filer", "broker"):
        try:
            resp = env.master_stub().ListClusterNodes(
                master_pb2.ListClusterNodesRequest(
                    client_type=node_type, filer_group=filer_group),
                timeout=10)
        except Exception:  # older master without the RPC
            continue
        for n in resp.cluster_nodes:
            star = " *leader*" if n.is_leader else ""
            group = f" group={filer_group}" if filer_group else ""
            print(f"  {node_type}: {n.address}{group}{star}", file=out)


@command("cluster.check", "ping every node and report health")
def cluster_check(env, args, out):
    t0 = time.time_ns()
    env.master_stub().Ping(master_pb2.PingRequest(), timeout=10)
    print(f"master {env.master}: ok "
          f"({(time.time_ns() - t0) / 1e6:.1f} ms)", file=out)
    for dn in env.collect_data_nodes():
        t0 = time.time_ns()
        try:
            env.volume_stub(dn.id).Ping(vs.PingRequest(), timeout=10)
            print(f"volume server {dn.id}: ok "
                  f"({(time.time_ns() - t0) / 1e6:.1f} ms)", file=out)
        except Exception as e:  # noqa: BLE001
            print(f"volume server {dn.id}: UNREACHABLE ({e})", file=out)


@command("cluster.status", "overall capacity and usage")
def cluster_status(env, args, out):
    stats = env.master_stub().Statistics(
        master_pb2.StatisticsRequest(), timeout=10)
    print(f"capacity: {stats.total_size}", file=out)
    print(f"used:     {stats.used_size}", file=out)
    print(f"files:    {stats.file_count}", file=out)


def _raft_servers(env):
    return env.master_stub().RaftListClusterServers(
        master_pb2.RaftListClusterServersRequest(), timeout=10
    ).cluster_servers


@command("cluster.raft.ps", "show Raft membership and roles")
def cluster_raft_ps(env, args, out):
    """command_cluster_raft_ps.go via master RaftListClusterServers —
    the same gRPC a stock `weed shell` issues."""
    for s in _raft_servers(env):
        star = " *leader*" if s.isLeader else ""
        print(f"  {s.id} {s.suffrage}{star}", file=out)


@command("cluster.raft.leader", "print the current Raft leader")
def cluster_raft_leader(env, args, out):
    for s in _raft_servers(env):
        if s.isLeader:
            print(s.address, file=out)
            return
    print(env.master, file=out)


def _raft_leader_addr(env) -> str:
    for s in _raft_servers(env):
        if s.isLeader:
            return s.address
    return env.master


def _raft_member_op(env, args, out, op: str) -> None:
    from ...pb import rpc
    from ..registry import kv_flags

    env.confirm_is_locked()  # membership changes mutate cluster topology
    opts = kv_flags(args)
    if not opts.get("id"):
        raise RuntimeError(f"usage: cluster.raft.{op} -id=<master-address>")
    # membership ops must land on the leader (followers reject them)
    stub = rpc.master_stub(rpc.grpc_address(_raft_leader_addr(env)))
    if op == "add":
        stub.RaftAddServer(master_pb2.RaftAddServerRequest(
            id=opts["id"], address=opts["id"], voter=True), timeout=10)
    else:
        stub.RaftRemoveServer(master_pb2.RaftRemoveServerRequest(
            id=opts["id"]), timeout=10)
    verb = "added" if op == "add" else "removed"
    members = sorted(s.id for s in _raft_servers(env))
    print(f"{verb} {opts['id']}; members: {members}", file=out)


@command("cluster.raft.add", "cluster.raft.add -id=<master-address>")
def cluster_raft_add(env, args, out):
    """command_cluster_raft_add.go: add a voter to the master Raft group
    (the new master should be started with matching -peers)."""
    _raft_member_op(env, args, out, "add")


@command("cluster.raft.remove", "cluster.raft.remove -id=<master-address>")
def cluster_raft_remove(env, args, out):
    """command_cluster_raft_remove.go: remove a server from the master
    Raft group."""
    _raft_member_op(env, args, out, "remove")
