"""cluster.* commands (reference: weed/shell/command_cluster_*.go)."""

from __future__ import annotations

import time

from ...pb import master_pb2, volume_server_pb2 as vs
from ..registry import command


@command("cluster.ps", "list cluster processes (masters, volume servers, filers, brokers)")
def cluster_ps(env, args, out):
    """command_cluster_ps.go: volume servers from topology, filers/brokers
    from the master's cluster membership (weed/cluster)."""
    filer_group = args[0] if args else ""
    print(f"master: {env.master}", file=out)
    for dn in env.collect_data_nodes():
        print(f"  volume server: {dn.id} (grpc :{dn.grpc_port})", file=out)
    for node_type in ("filer", "broker"):
        try:
            resp = env.master_stub().ListClusterNodes(
                master_pb2.ListClusterNodesRequest(
                    client_type=node_type, filer_group=filer_group),
                timeout=10)
        except Exception:  # older master without the RPC
            continue
        for n in resp.cluster_nodes:
            star = " *leader*" if n.is_leader else ""
            group = f" group={filer_group}" if filer_group else ""
            print(f"  {node_type}: {n.address}{group}{star}", file=out)


@command("cluster.check", "ping every node and report health")
def cluster_check(env, args, out):
    t0 = time.time_ns()
    env.master_stub().Ping(master_pb2.PingRequest(), timeout=10)
    print(f"master {env.master}: ok "
          f"({(time.time_ns() - t0) / 1e6:.1f} ms)", file=out)
    for dn in env.collect_data_nodes():
        t0 = time.time_ns()
        try:
            env.volume_stub(dn.id).Ping(vs.PingRequest(), timeout=10)
            print(f"volume server {dn.id}: ok "
                  f"({(time.time_ns() - t0) / 1e6:.1f} ms)", file=out)
        except Exception as e:  # noqa: BLE001
            print(f"volume server {dn.id}: UNREACHABLE ({e})", file=out)


@command("cluster.status", "overall capacity and usage")
def cluster_status(env, args, out):
    stats = env.master_stub().Statistics(
        master_pb2.StatisticsRequest(), timeout=10)
    print(f"capacity: {stats.total_size}", file=out)
    print(f"used:     {stats.used_size}", file=out)
    print(f"files:    {stats.file_count}", file=out)


@command("cluster.raft.ps", "show Raft membership and roles")
def cluster_raft_ps(env, args, out):
    """command_cluster_raft_ps.go: query each master's raft status."""
    import requests

    seen = set()
    frontier = [env.master]
    while frontier:
        m = frontier.pop()
        if m in seen:
            continue
        seen.add(m)
        try:
            st = requests.get(f"http://{m}/cluster/raft/status",
                              timeout=5).json()
        except requests.RequestException as e:
            print(f"  {m}: unreachable ({e})", file=out)
            continue
        if st.get("mode") == "single-master":
            print(f"  {m}: single-master (leader)", file=out)
            continue
        print(f"  {m}: {st['role']} term={st['term']} "
              f"commit={st['commit_index']} leader={st['leader']}",
              file=out)
        frontier.extend(p for p in st.get("peers", []) if p not in seen)


@command("cluster.raft.leader", "print the current Raft leader")
def cluster_raft_leader(env, args, out):
    import requests

    st = requests.get(f"http://{env.master}/cluster/raft/status",
                      timeout=5).json()
    print(st.get("leader", env.master), file=out)


def _raft_leader_addr(env) -> str:
    import requests

    st = requests.get(f"http://{env.master}/cluster/raft/status",
                      timeout=5).json()
    return st.get("leader") or env.master


def _raft_member_op(env, args, out, op: str) -> None:
    import requests

    from ..registry import kv_flags

    env.confirm_is_locked()  # membership changes mutate cluster topology
    opts = kv_flags(args)
    if not opts.get("id"):
        raise RuntimeError(f"usage: cluster.raft.{op} -id=<master-address>")
    leader = _raft_leader_addr(env)
    r = requests.get(f"http://{leader}/cluster/raft/{op}",
                     params={"id": opts["id"]}, timeout=10).json()
    if "error" in r:
        raise RuntimeError(r["error"])
    verb = "added" if op == "add" else "removed"
    print(f"{verb} {opts['id']}; members: "
          f"{sorted([r['id'], *r.get('peers', [])])}", file=out)


@command("cluster.raft.add", "cluster.raft.add -id=<master-address>")
def cluster_raft_add(env, args, out):
    """command_cluster_raft_add.go: add a voter to the master Raft group
    (the new master should be started with matching -peers)."""
    _raft_member_op(env, args, out, "add")


@command("cluster.raft.remove", "cluster.raft.remove -id=<master-address>")
def cluster_raft_remove(env, args, out):
    """command_cluster_raft_remove.go: remove a server from the master
    Raft group."""
    _raft_member_op(env, args, out, "remove")
