"""s3.* and mq.* shell commands.

Rebuild of /root/reference/weed/shell/command_s3_bucket_*.go,
command_s3_configure.go, and command_mq_topic_list.go. Buckets are filer
directories under /buckets (s3api/server.py BUCKETS_DIR); identities live
at /etc/iam/identity.json shared with the IAM API.
"""

from __future__ import annotations

import json
import time

from ...pb import filer_pb2, rpc
from ..registry import command, kv_flags as _kv

BUCKETS_DIR = "/buckets"


def _stub(env):
    return rpc.filer_stub(rpc.grpc_address(env.require_filer()))


@command("s3.bucket.list", "list S3 buckets")
def s3_bucket_list(env, args, out):
    for resp in _stub(env).ListEntries(filer_pb2.ListEntriesRequest(
            directory=BUCKETS_DIR, limit=10000)):
        e = resp.entry
        if e.is_directory and not e.name.startswith("."):
            print(e.name, file=out)


@command("s3.bucket.create", "s3.bucket.create -name=<bucket>")
def s3_bucket_create(env, args, out):
    opts = _kv(args)
    name = opts["name"]
    entry = filer_pb2.Entry(name=name, is_directory=True)
    entry.attributes.file_mode = 0o40777
    entry.attributes.mtime = int(time.time())
    resp = _stub(env).CreateEntry(filer_pb2.CreateEntryRequest(
        directory=BUCKETS_DIR, entry=entry), timeout=10)
    if resp.error:
        raise RuntimeError(resp.error)
    print(f"created bucket {name}", file=out)


@command("s3.bucket.delete", "s3.bucket.delete -name=<bucket>")
def s3_bucket_delete(env, args, out):
    opts = _kv(args)
    name = opts["name"]
    resp = _stub(env).DeleteEntry(filer_pb2.DeleteEntryRequest(
        directory=BUCKETS_DIR, name=name, is_delete_data=True,
        is_recursive=True), timeout=60)
    if resp.error:
        raise RuntimeError(resp.error)
    print(f"deleted bucket {name}", file=out)


@command("s3.configure",
         "s3.configure [-user=x -access_key=k -secret_key=s "
         "-actions=Read:bucket,Write] [-delete]")
def s3_configure(env, args, out):
    """Manage the S3 identity list (command_s3_configure.go), stored in
    the filer where the IAM API and S3 gateway read it."""
    from ...iamapi import IamConfigStore
    from ...s3api.auth import Identity

    store = IamConfigStore(env.require_filer())
    identities = store.load()
    opts = _kv(args)
    if not opts:
        print(json.dumps(
            [{"name": i.name, "access_key": i.access_key,
              "actions": i.actions} for i in identities], indent=2),
            file=out)
        return
    user = opts.get("user", "")
    existing = next((i for i in identities if i.name == user), None)
    if "delete" in opts:
        if existing:
            identities.remove(existing)
    else:
        if existing is None:
            existing = Identity(name=user, access_key="", secret_key="",
                                actions=[])
            identities.append(existing)
        if opts.get("access_key"):
            existing.access_key = opts["access_key"]
        if opts.get("secret_key"):
            existing.secret_key = opts["secret_key"]
        if opts.get("actions"):
            existing.actions = opts["actions"].split(",")
    store.save(identities)
    print(f"configured {len(identities)} identities", file=out)


@command("s3.bucket.quota",
         "s3.bucket.quota -name=<bucket> [-sizeMB=N | -disable]")
def s3_bucket_quota(env, args, out):
    """Set/clear a bucket quota (command_s3_bucket_quota.go): stored as the
    bucket entry's quota field; s3.bucket.quota.check enforces it."""
    opts = _kv(args)
    name = opts["name"]
    stub = _stub(env)
    resp = stub.LookupDirectoryEntry(filer_pb2.LookupDirectoryEntryRequest(
        directory=BUCKETS_DIR, name=name), timeout=10)
    entry = resp.entry
    if not entry.name:
        raise RuntimeError(f"no such bucket {name}")
    if "disable" in opts:
        entry.quota = -abs(entry.quota) if entry.quota else 0
    else:
        entry.quota = int(opts.get("sizeMB", "0")) << 20
    stub.UpdateEntry(filer_pb2.UpdateEntryRequest(
        directory=BUCKETS_DIR, entry=entry), timeout=10)
    print(f"bucket {name} quota = {entry.quota} bytes", file=out)


@command("s3.bucket.quota.check",
         "s3.bucket.quota.check [-apply]  (toggle read-only on over-quota)")
def s3_bucket_quota_check(env, args, out):
    """Enforce quotas (command_s3_bucket_quota_check.go): walk each bucket,
    compare usage to quota, and with -apply flip the bucket's read-only
    marker that the S3 gateway checks on writes."""
    from ...s3api.server import READONLY_KEY

    opts = _kv(args)
    apply = "apply" in opts
    stub = _stub(env)

    def tree_size(d: str) -> int:
        total = 0
        for r in stub.ListEntries(filer_pb2.ListEntriesRequest(
                directory=d, limit=100000)):
            e = r.entry
            if e.is_directory:
                total += tree_size(f"{d}/{e.name}")
            else:
                total += max(e.attributes.file_size,
                             sum(c.size for c in e.chunks), len(e.content))
        return total

    for r in stub.ListEntries(filer_pb2.ListEntriesRequest(
            directory=BUCKETS_DIR, limit=10000)):
        entry = r.entry
        if not entry.is_directory or entry.name.startswith("."):
            continue
        readonly = entry.extended.get(READONLY_KEY) == b"true"
        if entry.quota <= 0:
            want_ro = False
        else:
            used = tree_size(f"{BUCKETS_DIR}/{entry.name}")
            want_ro = used > entry.quota
            pct = 100.0 * used / entry.quota
            print(f"  {entry.name}\tused={used}\tquota={entry.quota}"
                  f"\t{pct:.1f}%", file=out)
        if want_ro != readonly:
            state = "read-only" if want_ro else "writable"
            if apply:
                if want_ro:
                    entry.extended[READONLY_KEY] = b"true"
                else:
                    entry.extended.pop(READONLY_KEY, None)
                stub.UpdateEntry(filer_pb2.UpdateEntryRequest(
                    directory=BUCKETS_DIR, entry=entry), timeout=10)
                print(f"    bucket {entry.name} -> {state}", file=out)
            else:
                print(f"    would set bucket {entry.name} -> {state} "
                      f"(rerun with -apply)", file=out)


@command("s3.circuitbreaker",
         "s3.circuitbreaker [-global|-buckets=b1,b2] [-enable|-disable] "
         "[-actions=Read:Count=100,Write:MB=64] [-delete] [-apply]")
def s3_circuitbreaker(env, args, out):
    """Edit /etc/s3/circuit_breaker.json (command_s3_circuitbreaker.go);
    the gateway hot-reloads it within its poll interval."""
    from ...s3api.circuit_breaker import CB_CONFIG_DIR, CB_CONFIG_FILE

    opts = _kv(args)
    stub = _stub(env)
    conf = {"global": {"enabled": False, "actions": {}}, "buckets": {}}
    try:
        resp = stub.LookupDirectoryEntry(filer_pb2.LookupDirectoryEntryRequest(
            directory=CB_CONFIG_DIR, name=CB_CONFIG_FILE), timeout=10)
        if resp.entry.content:
            conf = json.loads(resp.entry.content)
    except Exception:
        pass

    if "delete" in opts:
        conf = {"global": {"enabled": False, "actions": {}}, "buckets": {}}
    else:
        actions = {}
        for pair in filter(None, opts.get("actions", "").split(",")):
            k, _, v = pair.partition("=")
            actions[k] = int(v)
        targets = []
        if "buckets" in opts:
            for b in filter(None, opts["buckets"].split(",")):
                node = conf.setdefault("buckets", {}).setdefault(
                    b, {"enabled": True, "actions": {}})
                targets.append(node)
        else:
            targets.append(conf.setdefault("global",
                                           {"enabled": False, "actions": {}}))
        for node in targets:
            if "enable" in opts:
                node["enabled"] = True
            if "disable" in opts:
                node["enabled"] = False
            if actions:
                node.setdefault("actions", {}).update(actions)

    if "apply" in opts:
        entry = filer_pb2.Entry(name=CB_CONFIG_FILE,
                                content=json.dumps(conf, indent=2).encode())
        entry.attributes.file_mode = 0o600
        entry.attributes.mtime = int(time.time())
        stub.CreateEntry(filer_pb2.CreateEntryRequest(
            directory=CB_CONFIG_DIR, entry=entry), timeout=10)
        print("applied:", file=out)
    print(json.dumps(conf, indent=2), file=out)


@command("s3.clean.uploads",
         "s3.clean.uploads [-timeAgo=24h]  (abort stale multipart uploads)")
def s3_clean_uploads(env, args, out):
    """Drop multipart upload scratch dirs older than the cutoff
    (command_s3_clean_uploads.go)."""
    from ..registry import parse_duration

    opts = _kv(args)
    cutoff = time.time() - parse_duration(opts.get("timeAgo", "24h") or "24h",
                                          flag="-timeAgo")
    stub = _stub(env)
    uploads_dir = f"{BUCKETS_DIR}/.uploads"
    import grpc

    removed = 0
    try:
        entries = list(stub.ListEntries(filer_pb2.ListEntriesRequest(
            directory=uploads_dir, limit=10000)))
    except grpc.RpcError as e:
        if e.code() == grpc.StatusCode.NOT_FOUND:
            entries = []
        else:
            raise
    for r in entries:
        e = r.entry
        ts = e.attributes.crtime or e.attributes.mtime
        if ts and ts < cutoff:
            stub.DeleteEntry(filer_pb2.DeleteEntryRequest(
                directory=uploads_dir, name=e.name, is_delete_data=True,
                is_recursive=True), timeout=30)
            print(f"aborted upload {e.name}", file=out)
            removed += 1
    print(f"removed {removed} stale uploads", file=out)


@command("mq.topic.list", "list message-queue topics persisted in the filer")
def mq_topic_list(env, args, out):
    stub = _stub(env)

    def listdir(d):
        import grpc

        try:
            return [r.entry for r in stub.ListEntries(
                filer_pb2.ListEntriesRequest(directory=d, limit=10000))]
        except grpc.RpcError as e:
            if e.code() == grpc.StatusCode.NOT_FOUND:
                return []  # /topics doesn't exist yet
            raise  # connectivity failures must surface, not read as empty

    found = 0
    for ns in listdir("/topics"):
        if not ns.is_directory or ns.name.startswith("."):
            continue
        for tp in listdir(f"/topics/{ns.name}"):
            if not tp.is_directory:
                continue
            parts = [p for p in listdir(f"/topics/{ns.name}/{tp.name}")
                     if p.is_directory]
            print(f"{ns.name}.{tp.name} partitions={len(parts)}", file=out)
            found += 1
    if not found:
        print("no topics", file=out)



