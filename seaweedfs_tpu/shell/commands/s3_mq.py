"""s3.* and mq.* shell commands.

Rebuild of /root/reference/weed/shell/command_s3_bucket_*.go,
command_s3_configure.go, and command_mq_topic_list.go. Buckets are filer
directories under /buckets (s3api/server.py BUCKETS_DIR); identities live
at /etc/iam/identity.json shared with the IAM API.
"""

from __future__ import annotations

import json
import time

from ...pb import filer_pb2, rpc
from ..registry import command

BUCKETS_DIR = "/buckets"


def _stub(env):
    return rpc.filer_stub(rpc.grpc_address(env.require_filer()))


@command("s3.bucket.list", "list S3 buckets")
def s3_bucket_list(env, args, out):
    for resp in _stub(env).ListEntries(filer_pb2.ListEntriesRequest(
            directory=BUCKETS_DIR, limit=10000)):
        e = resp.entry
        if e.is_directory and not e.name.startswith("."):
            print(e.name, file=out)


@command("s3.bucket.create", "s3.bucket.create -name=<bucket>")
def s3_bucket_create(env, args, out):
    opts = _kv(args)
    name = opts["name"]
    entry = filer_pb2.Entry(name=name, is_directory=True)
    entry.attributes.file_mode = 0o40777
    entry.attributes.mtime = int(time.time())
    resp = _stub(env).CreateEntry(filer_pb2.CreateEntryRequest(
        directory=BUCKETS_DIR, entry=entry), timeout=10)
    if resp.error:
        raise RuntimeError(resp.error)
    print(f"created bucket {name}", file=out)


@command("s3.bucket.delete", "s3.bucket.delete -name=<bucket>")
def s3_bucket_delete(env, args, out):
    opts = _kv(args)
    name = opts["name"]
    resp = _stub(env).DeleteEntry(filer_pb2.DeleteEntryRequest(
        directory=BUCKETS_DIR, name=name, is_delete_data=True,
        is_recursive=True), timeout=60)
    if resp.error:
        raise RuntimeError(resp.error)
    print(f"deleted bucket {name}", file=out)


@command("s3.configure",
         "s3.configure [-user=x -access_key=k -secret_key=s "
         "-actions=Read:bucket,Write] [-delete]")
def s3_configure(env, args, out):
    """Manage the S3 identity list (command_s3_configure.go), stored in
    the filer where the IAM API and S3 gateway read it."""
    from ...iamapi import IamConfigStore
    from ...s3api.auth import Identity

    store = IamConfigStore(env.require_filer())
    identities = store.load()
    opts = _kv(args)
    if not opts:
        print(json.dumps(
            [{"name": i.name, "access_key": i.access_key,
              "actions": i.actions} for i in identities], indent=2),
            file=out)
        return
    user = opts.get("user", "")
    existing = next((i for i in identities if i.name == user), None)
    if "delete" in opts:
        if existing:
            identities.remove(existing)
    else:
        if existing is None:
            existing = Identity(name=user, access_key="", secret_key="",
                                actions=[])
            identities.append(existing)
        if opts.get("access_key"):
            existing.access_key = opts["access_key"]
        if opts.get("secret_key"):
            existing.secret_key = opts["secret_key"]
        if opts.get("actions"):
            existing.actions = opts["actions"].split(",")
    store.save(identities)
    print(f"configured {len(identities)} identities", file=out)


@command("mq.topic.list", "list message-queue topics persisted in the filer")
def mq_topic_list(env, args, out):
    stub = _stub(env)

    def listdir(d):
        import grpc

        try:
            return [r.entry for r in stub.ListEntries(
                filer_pb2.ListEntriesRequest(directory=d, limit=10000))]
        except grpc.RpcError as e:
            if e.code() == grpc.StatusCode.NOT_FOUND:
                return []  # /topics doesn't exist yet
            raise  # connectivity failures must surface, not read as empty

    found = 0
    for ns in listdir("/topics"):
        if not ns.is_directory or ns.name.startswith("."):
            continue
        for tp in listdir(f"/topics/{ns.name}"):
            if not tp.is_directory:
                continue
            parts = [p for p in listdir(f"/topics/{ns.name}/{tp.name}")
                     if p.is_directory]
            print(f"{ns.name}.{tp.name} partitions={len(parts)}", file=out)
            found += 1
    if not found:
        print("no topics", file=out)


def _kv(args) -> dict:
    out = {}
    for a in args:
        if a.startswith("-"):
            k, _, v = a[1:].partition("=")
            out[k] = v
    return out
