"""`qos.status` — one view of the QoS/admission plane across the fleet.

Walks the cluster (master, every registered volume server, the shell's
filer and any `-server=` extras) asking each `/status` for its `Qos`
section and prints: the master's grant ledger (cluster budget, per-class
granted/denied, per-server pressure), each volume server's live pressure
score + governor lease state, and each ingress plane's tenant buckets
with recent rejections (tenant, retry-after, trace id — the handle
`trace.dump` turns into a per-plane breakdown).
"""

from __future__ import annotations

import json

import requests

from ...utils.http import requests_verify, url_for
from ..registry import command


def _status(addr: str) -> dict:
    try:
        r = requests.get(url_for(addr, "/status"), timeout=10,
                         verify=requests_verify())
        if r.status_code != 200:
            return {}
        return r.json()
    except (requests.RequestException, ValueError):
        return {}


def _fmt_admission(adm: dict, out) -> None:
    print(f"    admitted={adm.get('admitted', 0)} "
          f"rejected={adm.get('rejected', 0)} "
          f"defaultRps={adm.get('defaultRps', 0)}", file=out)
    for tenant, b in sorted(adm.get("tenants", {}).items()):
        print(f"      tenant {tenant:24s} rate={b.get('rate', 0):g} "
              f"burst={b.get('burst', 0):g} tokens={b.get('tokens', 0)}",
              file=out)
    for rej in adm.get("recentRejections", [])[-5:]:
        print(f"      rejected {rej.get('tenant', '?'):24s} "
              f"retryAfter={rej.get('retryAfterS', 0)}s "
              f"trace={rej.get('traceId', '') or '-'}", file=out)


@command("qos.status",
         "QoS/admission plane across the fleet: grant ledger, pressure, "
         "tenant buckets ([-server=addr,addr] [-json])")
def qos_status(env, args, out):
    extra: list[str] = []
    as_json = False
    for a in args:
        if a.startswith("-server="):
            extra.extend(x for x in a.split("=", 1)[1].split(",") if x)
        elif a == "-json":
            as_json = True
    targets = [("master", env.master)]
    try:
        for dn in env.collect_data_nodes():
            targets.append(("volume", dn.id))
    except Exception:  # noqa: BLE001 — a dead master still leaves extras
        pass
    if env.filer:
        targets.append(("filer", env.filer))
    for addr in extra:
        if addr and all(addr != t[1] for t in targets):
            targets.append(("server", addr))

    gathered = {}
    for kind, addr in targets:
        st = _status(addr)
        qos = st.get("Qos")
        if qos is not None:
            gathered[addr] = {"kind": kind, "qos": qos}
    if as_json:
        print(json.dumps(gathered, indent=2), file=out)
        return
    if not gathered:
        print("no Qos sections found (servers down, or pre-QoS builds?)",
              file=out)
        return
    for addr, entry in gathered.items():
        kind, qos = entry["kind"], entry["qos"]
        print(f"{kind} {addr}:", file=out)
        ledger = qos.get("ledger")
        if ledger is not None:
            print(f"  ledger: clusterBudgetMBps="
                  f"{ledger.get('clusterBudgetMBps', 0)} "
                  f"granted={ledger.get('grantedBytes', {})} "
                  f"denied={ledger.get('deniedGrants', {})}", file=out)
            for saddr, s in sorted(ledger.get("servers", {}).items()):
                print(f"    server {saddr:21s} "
                      f"pressure={s.get('pressure', 0):.3f} "
                      f"age={s.get('ageSeconds', 0)}s", file=out)
        if "pressure" in qos and "governor" in qos:
            gov = qos["governor"]
            print(f"  pressure={qos['pressure']:.3f} "
                  f"(gcDepth={qos.get('groupCommitDepth', 0)} "
                  f"dispatchDepth={qos.get('dispatchDepth', 0)})",
                  file=out)
            print(f"  governor: enabled={gov.get('enabled')} "
                  f"tokens={gov.get('tokens', {})} "
                  f"waits={gov.get('waitSeconds', {})} "
                  f"denials={gov.get('denials', 0)}", file=out)
        adm = qos.get("tenantAdmission")
        if adm is not None:
            print(f"  admission ({adm.get('plane', '?')}):", file=out)
            _fmt_admission(adm, out)
        grants = qos.get("grants")
        if grants and kind in ("master", "volume"):
            for klass, g in sorted(grants.items()):
                if any(g.values()):
                    print(f"  class {klass}: {g}", file=out)
