"""Shell command registry + REPL runner (weed/shell/commands.go analogue)."""

from __future__ import annotations

import shlex
import sys

COMMANDS: dict[str, tuple] = {}  # name -> (fn, help)


def command(name: str, help_: str = ""):
    def deco(fn):
        COMMANDS[name] = (fn, help_)
        return fn
    return deco


def run_command(env, line: str, out=None) -> int:
    out = out or sys.stdout
    parts = shlex.split(line.strip())
    if not parts:
        return 0
    name, args = parts[0], parts[1:]
    if name in ("help", "?"):
        for n in sorted(COMMANDS):
            print(f"  {n:32s} {COMMANDS[n][1]}", file=out)
        return 0
    entry = COMMANDS.get(name)
    if entry is None:
        print(f"unknown command: {name} (try `help`)", file=out)
        return 1
    try:
        entry[0](env, args, out)
        return 0
    except Exception as e:  # noqa: BLE001 - REPL surfaces, doesn't crash
        print(f"error: {e}", file=out)
        return 1


def repl(env) -> None:
    """Interactive admin shell (`weed shell`)."""
    from . import commands  # noqa: F401 - ensure registration

    print("seaweedfs-tpu shell; `help` lists commands, `exit` quits")
    while True:
        try:
            line = input("> ")
        except (EOFError, KeyboardInterrupt):
            print()
            break
        if line.strip() in ("exit", "quit"):
            break
        run_command(env, line)


# importing the command modules registers them
from . import commands  # noqa: E402,F401
