"""Shell command registry + REPL runner (weed/shell/commands.go analogue)."""

from __future__ import annotations

import shlex
import sys

COMMANDS: dict[str, tuple] = {}  # name -> (fn, help)


def command(name: str, help_: str = ""):
    def deco(fn):
        COMMANDS[name] = (fn, help_)
        return fn
    return deco


def run_command(env, line: str, out=None) -> int:
    out = out or sys.stdout
    parts = shlex.split(line.strip())
    if not parts:
        return 0
    name, args = parts[0], parts[1:]
    if name in ("help", "?"):
        for n in sorted(COMMANDS):
            print(f"  {n:32s} {COMMANDS[n][1]}", file=out)
        return 0
    entry = COMMANDS.get(name)
    if entry is None:
        print(f"unknown command: {name} (try `help`)", file=out)
        return 1
    try:
        entry[0](env, args, out)
        return 0
    except Exception as e:  # noqa: BLE001 - REPL surfaces, doesn't crash
        print(f"error: {e}", file=out)
        return 1


def repl(env) -> None:
    """Interactive admin shell (`weed shell`)."""
    from . import commands  # noqa: F401 - ensure registration

    print("seaweedfs-tpu shell; `help` lists commands, `exit` quits")
    while True:
        try:
            line = input("> ")
        except (EOFError, KeyboardInterrupt):
            print()
            break
        if line.strip() in ("exit", "quit"):
            break
        run_command(env, line)


def kv_flags(args) -> dict:
    """Shared '-k=v' / bare '-flag' parser for simple commands (the same
    shape remote.py's commands use; richer commands use argparse)."""
    out = {}
    for a in args:
        if a.startswith("-"):
            k, _, v = a[1:].partition("=")
            out[k] = v
    return out


_DURATION_UNITS = {"s": 1, "m": 60, "h": 3600, "d": 86400}


def parse_duration(spec: str, *, flag: str = "duration") -> float:
    """'90s' / '15m' / '24h' / '7d' -> seconds; a bare number or anything
    unparsable is an error (silent unit guessing misreads operator intent)."""
    spec = (spec or "").strip()
    if len(spec) >= 2 and spec[-1] in _DURATION_UNITS:
        try:
            return float(spec[:-1]) * _DURATION_UNITS[spec[-1]]
        except ValueError:
            pass
    raise RuntimeError(
        f"bad {flag} {spec!r}: use <number><unit> with unit one of s/m/h/d")


# importing the command modules registers them
from . import commands  # noqa: E402,F401
