from .env import CommandEnv
from .registry import COMMANDS, run_command

__all__ = ["CommandEnv", "COMMANDS", "run_command"]
