"""Shell command environment: master connection + exclusive cluster lock.

Rebuild of the reference's shell CommandEnv (weed/shell/commands.go,
command_lock_unlock.go, wdclient/exclusive_locks/): commands that mutate
cluster topology must hold the admin lease obtained via LeaseAdminToken.
"""

from __future__ import annotations

import time

from ..pb import master_pb2, rpc
from ..wdclient import MasterClient

LOCK_NAME = "admin"


class CommandEnv:
    def __init__(self, masters: str | list[str], *, filer: str = ""):
        self.master_client = MasterClient(masters)
        self.filer = filer  # filer address for fs.*/remote.* commands
        self.cwd = "/"      # fs.cd state
        self._lock_token = 0
        self._lock_ts = 0

    def require_filer(self) -> str:
        if not self.filer:
            raise RuntimeError(
                "this command needs a filer; start the shell with -filer")
        return self.filer

    @property
    def master(self) -> str:
        return self.master_client.current_master

    def master_stub(self):
        return rpc.master_stub(rpc.grpc_address(self.master))

    def volume_stub(self, server_http_addr: str):
        return rpc.volume_stub(rpc.grpc_address(server_http_addr))

    # -- exclusive lock (command_lock_unlock.go) ---------------------------

    def acquire_lock(self, client_name: str = "shell") -> None:
        resp = self.master_stub().LeaseAdminToken(
            master_pb2.LeaseAdminTokenRequest(
                previous_token=self._lock_token,
                previous_lock_time=self._lock_ts,
                lock_name=LOCK_NAME, client_name=client_name,
            ), timeout=10)
        self._lock_token, self._lock_ts = resp.token, resp.lock_ts_ns

    def release_lock(self) -> None:
        if not self._lock_token:
            return
        self.master_stub().ReleaseAdminToken(
            master_pb2.ReleaseAdminTokenRequest(
                previous_token=self._lock_token,
                previous_lock_time=self._lock_ts, lock_name=LOCK_NAME,
            ), timeout=10)
        self._lock_token = self._lock_ts = 0

    @property
    def is_locked(self) -> bool:
        return bool(self._lock_token)

    def confirm_is_locked(self) -> None:
        if not self.is_locked:
            raise RuntimeError(
                "need to run `lock` before this command; `unlock` when done")

    # -- topology helpers --------------------------------------------------

    def volume_list(self) -> master_pb2.VolumeListResponse:
        return self.master_stub().VolumeList(
            master_pb2.VolumeListRequest(), timeout=30)

    def collect_data_nodes(self, topo=None) -> list[master_pb2.DataNodeInfo]:
        """Pass a prefetched topology_info to keep node and rack views on
        one consistent snapshot."""
        out = []
        topo = topo if topo is not None else self.volume_list().topology_info
        for dc in topo.data_center_infos:
            for rack in dc.rack_infos:
                out.extend(rack.data_node_infos)
        return out

    def node_racks(self, topo=None) -> dict[str, tuple[str, str]]:
        """node url -> (data_center, rack) from the master topology."""
        out = {}
        topo = topo if topo is not None else self.volume_list().topology_info
        for dc in topo.data_center_infos:
            for rack in dc.rack_infos:
                for dn in rack.data_node_infos:
                    out[dn.id] = (dc.id, rack.id)
        return out

    def wait_heartbeat(self, seconds: float = 1.2) -> None:
        """Give volume servers a pulse to re-report after a mutation."""
        time.sleep(seconds)
