"""Filer entry model: path -> attributes + chunk list.

Rebuild of /root/reference/weed/filer/entry.go + filechunks.go's FileChunk
model. An Entry is either a directory or a file whose bytes live as chunks
(fid extents) on volume servers; small files may inline `content`.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field

from ..pb import filer_pb2


@dataclass
class Attr:
    mtime: int = 0           # unix seconds
    crtime: int = 0
    mode: int = 0o660
    uid: int = 0
    gid: int = 0
    mime: str = ""
    ttl_sec: int = 0
    user_name: str = ""
    symlink_target: str = ""
    md5: bytes = b""
    disk_type: str = ""
    file_size: int = 0       # declared size for chunk-less entries
    #                          (remote-mounted mirrors carry no chunks)

    @property
    def is_directory(self) -> bool:
        return bool(self.mode & 0o40000 == 0o40000) or bool(self.mode & (1 << 31))


@dataclass
class Entry:
    full_path: str = "/"
    attr: Attr = field(default_factory=Attr)
    chunks: list[filer_pb2.FileChunk] = field(default_factory=list)
    extended: dict[str, bytes] = field(default_factory=dict)
    content: bytes = b""
    hard_link_id: bytes = b""
    hard_link_counter: int = 0
    is_directory: bool = False
    quota: int = 0  # bucket dirs only (filer.proto Entry.quota)

    @property
    def name(self) -> str:
        return self.full_path.rstrip("/").rsplit("/", 1)[-1] if self.full_path != "/" else ""

    @property
    def parent(self) -> str:
        if self.full_path == "/":
            return "/"
        p = self.full_path.rstrip("/").rsplit("/", 1)[0]
        return p or "/"

    def size(self) -> int:
        if self.content:
            return len(self.content)
        return max((c.offset + c.size for c in self.chunks),
                   default=self.attr.file_size)

    # -- protobuf conversion ----------------------------------------------

    def to_pb(self) -> filer_pb2.Entry:
        e = filer_pb2.Entry(
            name=self.name, is_directory=self.is_directory,
            content=self.content, hard_link_id=self.hard_link_id,
            hard_link_counter=self.hard_link_counter, quota=self.quota,
        )
        e.chunks.extend(self.chunks)
        a = self.attr
        e.attributes.CopyFrom(filer_pb2.FuseAttributes(
            file_size=self.size(), mtime=a.mtime, file_mode=a.mode,
            uid=a.uid, gid=a.gid, crtime=a.crtime, mime=a.mime,
            ttl_sec=a.ttl_sec, user_name=a.user_name,
            symlink_target=a.symlink_target, md5=a.md5, disk_type=a.disk_type,
        ))
        for k, v in self.extended.items():
            e.extended[k] = v
        return e

    @classmethod
    def from_pb(cls, directory: str, e: filer_pb2.Entry) -> "Entry":
        a = e.attributes
        full = directory.rstrip("/") + "/" + e.name if e.name else directory
        return cls(
            full_path=full or "/",
            attr=Attr(mtime=a.mtime, crtime=a.crtime, mode=a.file_mode,
                      uid=a.uid, gid=a.gid, mime=a.mime, ttl_sec=a.ttl_sec,
                      user_name=a.user_name, symlink_target=a.symlink_target,
                      md5=a.md5, disk_type=a.disk_type,
                      file_size=a.file_size),
            chunks=list(e.chunks),
            extended=dict(e.extended),
            content=e.content,
            hard_link_id=e.hard_link_id,
            hard_link_counter=e.hard_link_counter,
            is_directory=e.is_directory,
            quota=e.quota,
        )


def new_directory_entry(path: str, mode: int = 0o770) -> Entry:
    now = int(time.time())
    return Entry(full_path=path, is_directory=True,
                 attr=Attr(mtime=now, crtime=now, mode=mode | 0o40000))
