from .entry import Attr, Entry
from .filer import Filer
from .filerstore import FilerStore, get_store, register_store

__all__ = ["Attr", "Entry", "Filer", "FilerStore", "get_store", "register_store"]
