"""Filer peer metadata aggregation.

Rebuild of /root/reference/weed/filer/meta_aggregator.go: in a multi-filer
deployment every filer subscribes to its peers' local metadata streams and
folds those events into its own event log, so any single filer can serve a
cluster-wide SubscribeMetadata. Events are tagged with the originating
filer's signature; a filer skips events carrying its own signature to
avoid loops (MaybeBootstrapFromPeers handles initial catch-up via the
persisted log — here the peer stream replays from since_ns=0 on first
connect, which covers bootstrap for in-memory logs).
"""

from __future__ import annotations

import threading

from ..pb import filer_pb2, rpc
from ..utils import glog
from ..utils.retry import Backoff
from ..utils.stats import META_AGGREGATOR_RECONNECTS


class MetaAggregator:
    def __init__(self, filer, self_signature: int, *,
                 client_name: str = "filer-peer"):
        self.filer = filer
        self.signature = self_signature
        self.client_name = client_name
        self._stop = threading.Event()
        self._threads: list[threading.Thread] = []
        self.peer_counts: dict[str, int] = {}

    def subscribe_to_peer(self, peer_grpc_address: str,
                          since_ns: int = 0) -> None:
        self.peer_counts[peer_grpc_address] = 0

        def run():
            cursor = since_ns
            # a down peer answers every dial attempt instantly with
            # UNAVAILABLE, so a fixed 0.5s pause was a 2 Hz reconnect
            # hammer per peer; exponential backoff with jitter (the
            # utils/retry discipline every other reconnect loop rides)
            # caps the retry rate while the counted metric keeps the
            # flapping visible
            bo = Backoff()
            while not self._stop.is_set():
                try:
                    stub = rpc.filer_stub(peer_grpc_address)
                    req = filer_pb2.SubscribeMetadataRequest(
                        client_name=self.client_name,
                        path_prefix="/", since_ns=cursor,
                        signature=self.signature)
                    for resp in stub.SubscribeLocalMetadata(req):
                        if self._stop.is_set():
                            return
                        cursor = max(cursor, resp.ts_ns)
                        bo = Backoff()  # events flowing = peer healthy
                        if self.signature in \
                                resp.event_notification.signatures:
                            continue  # our own event echoed back
                        self._fold(resp)
                        self.peer_counts[peer_grpc_address] += 1
                except Exception as e:
                    glog.v(2, f"meta aggregator {peer_grpc_address}: {e}")
                    META_AGGREGATOR_RECONNECTS.inc(peer=peer_grpc_address)
                    if self._stop.wait(bo.next_wait()):
                        return

        t = threading.Thread(target=run, daemon=True)
        t.start()
        self._threads.append(t)

    def _fold(self, resp: filer_pb2.SubscribeMetadataResponse) -> None:
        """Append a peer event to the local log (and only the log — the
        peer owns the store mutation) so local subscribers see it."""
        import time

        copied = filer_pb2.SubscribeMetadataResponse()
        copied.CopyFrom(resp)
        if self.signature not in copied.event_notification.signatures:
            copied.event_notification.signatures.append(self.signature)
        # re-stamp with LOCAL arrival time: subscribers cursor this log by
        # max ts, so keeping the peer's (older) ts would let an event that
        # propagated slowly land behind an already-consumed cursor
        copied.ts_ns = time.time_ns()
        with self.filer._log_cond:
            self.filer._log.append(copied)
            self.filer._log_cond.notify_all()

    def close(self) -> None:
        self._stop.set()
