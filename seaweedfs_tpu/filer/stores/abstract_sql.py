"""Shared SQL filer-store layer + dialects.

Rebuild of /root/reference/weed/filer/abstract_sql/abstract_sql_store.go:
one generic store speaking DB-API, with per-dialect SQL generation (the
reference's SqlGenerator interface: GetSqlInsert/Find/Delete/List/... that
mysql/postgres/sqlite and five more stores all reuse). A dialect supplies:

  * the SQL statements (paramstyle differences: ?, %s, $N)
  * a connect() factory returning DB-API connections
  * upsert syntax (ON CONFLICT / ON DUPLICATE KEY)

The sqlite dialect is fully live; mysql/postgres generate their exact SQL
and are import-gated on their client libraries (pymysql / psycopg2), which
this environment doesn't ship — construction raises with instructions,
matching the repo's convention for cloud-gated backends.
"""

from __future__ import annotations

import threading
from typing import Iterator

from ...pb import filer_pb2
from ...utils import locks
from ..entry import Entry
from ..filerstore import register_store


def like_escape(s: str) -> str:
    """Escape LIKE wildcards in a fixed prefix with '!' (the ESCAPE
    char every dialect statement declares): '/data_1' must not also
    match '/dataX1'."""
    return s.replace("!", "!!").replace("%", "!%").replace("_", "!_")


class SqlDialect:
    """SqlGenerator equivalent (abstract_sql_store.go:15-26)."""

    name = "abstract"
    param = "?"  # DB-API paramstyle placeholder

    def _p(self, n: int) -> list[str]:
        return [self.param] * n

    def qi(self, ident: str) -> str:
        """Quote an identifier. Bucket tables carry user-chosen names
        ('my-bucket', 'a.b'); every statement must quote, not just DDL."""
        return '"' + ident.replace('"', '""') + '"'

    def kv_table(self, table: str) -> str:
        return f"{table}_kv"

    def create_table(self, table: str) -> str:
        return (f"CREATE TABLE IF NOT EXISTS {self.qi(table)} ("
                f"directory TEXT NOT NULL, name TEXT NOT NULL, meta BLOB, "
                f"PRIMARY KEY (directory, name))")

    def create_kv_table(self, table: str) -> str:
        return (f"CREATE TABLE IF NOT EXISTS {self.qi(self.kv_table(table))} "
                f"(k BLOB PRIMARY KEY, v BLOB)")

    def drop_table(self, table: str) -> str:
        return f"DROP TABLE IF EXISTS {self.qi(table)}"

    def list_bucket_tables(self) -> str:
        """Enumerate per-bucket tables (sqlite flavor; postgres overrides).
        Used when a recursive delete covers the whole /buckets tree."""
        return ("SELECT name FROM sqlite_master WHERE type='table' "
                "AND name LIKE 'bucket\\_%' ESCAPE '\\'")

    def upsert(self, table: str) -> str:
        a, b, c = self._p(3)
        return (f"INSERT INTO {self.qi(table)}(directory,name,meta) "
                f"VALUES({a},{b},{c}) "
                f"ON CONFLICT(directory,name) DO UPDATE SET meta=excluded.meta")

    def find(self, table: str) -> str:
        a, b = self._p(2)
        return (f"SELECT meta FROM {self.qi(table)} "
                f"WHERE directory={a} AND name={b}")

    def delete(self, table: str) -> str:
        a, b = self._p(2)
        return f"DELETE FROM {self.qi(table)} WHERE directory={a} AND name={b}"

    def delete_folder_children(self, table: str) -> str:
        # ESCAPE '!': directory names may contain SQL wildcards ('_',
        # '%'); callers escape the fixed prefix with like_escape() so
        # '/data_1/%' can't also match '/dataX1/...'. '!' is portable
        # across sqlite/mysql/postgres (backslash is not: mysql string
        # syntax vs pg standard_conforming_strings).
        a, b = self._p(2)
        return (f"DELETE FROM {self.qi(table)} WHERE directory={a} "
                f"OR directory LIKE {b} ESCAPE '!'")

    def list_entries(self, table: str, inclusive: bool) -> str:
        op = ">=" if inclusive else ">"
        a, b, c, d = self._p(4)
        return (f"SELECT name, meta FROM {self.qi(table)} WHERE directory={a} "
                f"AND name {op} {b} AND name LIKE {c} ESCAPE '!' "
                f"ORDER BY name LIMIT {d}")

    def kv_upsert(self, table: str) -> str:
        a, b = self._p(2)
        return (f"INSERT INTO {self.qi(self.kv_table(table))}(k,v) "
                f"VALUES({a},{b}) "
                f"ON CONFLICT(k) DO UPDATE SET v=excluded.v")

    def kv_get(self, table: str) -> str:
        return (f"SELECT v FROM {self.qi(self.kv_table(table))} "
                f"WHERE k={self.param}")

    def connect(self):
        raise NotImplementedError


class SqliteDialect(SqlDialect):
    name = "sqlite"
    param = "?"

    def kv_table(self, table: str) -> str:
        # round-1 sqlite databases named this table plain "kv" — keep
        # reading/writing it so existing stores survive the upgrade
        return "kv"

    _mem_seq = 0
    _mem_lock = threading.Lock()

    def __init__(self, db_path: str = ":memory:"):
        self.uri = False
        if db_path == ":memory:":
            # per-connection private :memory: DBs won't do — every server
            # thread must see one namespace. Use a named shared-cache DB.
            with SqliteDialect._mem_lock:
                SqliteDialect._mem_seq += 1
                db_path = (f"file:filer_mem_{id(self)}_"
                           f"{SqliteDialect._mem_seq}?mode=memory&cache=shared")
            self.uri = True
        self.db_path = db_path

    def connect(self):
        import sqlite3

        c = sqlite3.connect(self.db_path, uri=self.uri,
                            check_same_thread=False)
        if not self.uri:
            c.execute("PRAGMA journal_mode=WAL")
            c.execute("PRAGMA synchronous=NORMAL")
        c.execute("PRAGMA busy_timeout=5000")
        return c


class MySqlDialect(SqlDialect):
    """mysql/mysql_store.go + mysql_sql_gen.go SQL shapes."""

    name = "mysql"
    param = "%s"

    def qi(self, ident: str) -> str:
        # mysql default sql_mode rejects double-quoted identifiers
        return "`" + ident.replace("`", "``") + "`"

    def list_bucket_tables(self) -> str:
        return ("SELECT table_name FROM information_schema.tables "
                "WHERE table_name LIKE 'bucket\\_%'")

    def __init__(self, *, host="localhost", port=3306, user="root",
                 password="", database="seaweedfs", **_):
        self.kwargs = dict(host=host, port=port, user=user,
                           password=password, database=database)

    def create_table(self, table: str) -> str:
        # 2 x VARCHAR(383) x 4 bytes/char (utf8mb4) = 3064 bytes, inside
        # InnoDB's 3072-byte composite index limit
        return (f"CREATE TABLE IF NOT EXISTS `{table}` ("
                f"`directory` VARCHAR(383) NOT NULL, "
                f"`name` VARCHAR(383) NOT NULL, `meta` LONGBLOB, "
                f"PRIMARY KEY (`directory`, `name`)) CHARACTER SET utf8mb4")

    def create_kv_table(self, table: str) -> str:
        # BLOB cannot be a MySQL key; bounded VARBINARY can
        return (f"CREATE TABLE IF NOT EXISTS `{self.kv_table(table)}` "
                f"(k VARBINARY(255) PRIMARY KEY, v LONGBLOB)")

    def upsert(self, table: str) -> str:
        return (f"INSERT INTO `{table}`(directory,name,meta) "
                f"VALUES(%s,%s,%s) "
                f"ON DUPLICATE KEY UPDATE meta=VALUES(meta)")

    def kv_upsert(self, table: str) -> str:
        return (f"INSERT INTO `{self.kv_table(table)}`(k,v) VALUES(%s,%s) "
                f"ON DUPLICATE KEY UPDATE v=VALUES(v)")

    def connect(self):
        # no pymysql in this image: speak the client/server protocol
        # directly (mysql_wire.MySqlConnection)
        from .mysql_wire import MySqlConnection

        return MySqlConnection(**self.kwargs)


class PostgresDialect(SqlDialect):
    """postgres/postgres_store.go + postgres_sql_gen.go SQL shapes."""

    name = "postgres"
    param = "%s"

    def __init__(self, *, host="localhost", port=5432, user="postgres",
                 password="", database="seaweedfs", sslmode="disable", **_):
        self.kwargs = dict(host=host, port=port, user=user,
                           password=password, dbname=database,
                           sslmode=sslmode)

    def create_table(self, table: str) -> str:
        return (f'CREATE TABLE IF NOT EXISTS "{table}" ('
                f"directory VARCHAR(65535) NOT NULL, "
                f"name VARCHAR(65535) NOT NULL, meta BYTEA, "
                f"PRIMARY KEY (directory, name))")

    def create_kv_table(self, table: str) -> str:
        # Postgres has no BLOB type — BYTEA throughout
        return (f'CREATE TABLE IF NOT EXISTS "{self.kv_table(table)}" '
                f"(k BYTEA PRIMARY KEY, v BYTEA)")

    def list_bucket_tables(self) -> str:
        return ("SELECT tablename FROM pg_tables "
                "WHERE tablename LIKE 'bucket\\_%' ESCAPE '\\'")

    def upsert(self, table: str) -> str:
        return (f'INSERT INTO "{table}"(directory,name,meta) '
                f"VALUES(%s,%s,%s) ON CONFLICT(directory,name) "
                f"DO UPDATE SET meta=EXCLUDED.meta")

    def kv_upsert(self, table: str) -> str:
        return (f'INSERT INTO "{self.kv_table(table)}"(k,v) VALUES(%s,%s) '
                f"ON CONFLICT(k) DO UPDATE SET v=EXCLUDED.v")

    def connect(self):
        # no psycopg2 in this image: speak the v3 wire protocol directly
        # (pg_wire.PgConnection — same protocol a real server expects)
        from .pg_wire import PgConnection

        return PgConnection(**self.kwargs)


class AbstractSqlStore:
    """FilerStore over any SqlDialect (AbstractSqlStore,
    abstract_sql_store.go:28).

    ``support_bucket_table`` mirrors the reference's "2"-generation
    stores (postgres2/mysql2: SupportBucketTable=true,
    postgres2_store.go:53): objects under ``/buckets/<name>/...`` live
    in a per-bucket table created on first write and dropped whole on
    bucket deletion — O(1) bucket deletes instead of a LIKE-scan.
    """

    TABLE = "filemeta"

    def __init__(self, dialect: SqlDialect,
                 support_bucket_table: bool = False):
        self.dialect = dialect
        self.name = dialect.name
        self.support_bucket_table = support_bucket_table
        self._bucket_tables: set[str] = set()
        self._local = threading.local()
        self._lock = locks.wlock("filer.store.mu", rank=500)
        # anchor connection: creates the schema and, for shared-cache
        # in-memory sqlite, pins the database alive
        self._anchor = dialect.connect()
        cur = self._anchor.cursor()
        cur.execute(self.dialect.create_table(self.TABLE))
        cur.execute(self.dialect.create_kv_table(self.TABLE))
        self._anchor.commit()

    def _conn(self):
        c = getattr(self._local, "conn", None)
        if c is None:
            c = self.dialect.connect()
            self._local.conn = c
        return c

    @staticmethod
    def _split(full_path: str) -> tuple[str, str]:
        if full_path == "/":
            return "", "/"
        d, _, n = full_path.rstrip("/").rpartition("/")
        return d or "/", n

    # -- bucket tables (abstract_sql_store.go getTxOrDB bucket routing) ---

    @staticmethod
    def _bucket_of(directory: str) -> str | None:
        if not directory.startswith("/buckets/"):
            return None
        bucket = directory[len("/buckets/"):].split("/", 1)[0]
        # identifier-safe only; anything exotic stays in the main table
        if bucket and all(c.isalnum() or c in "-_." for c in bucket):
            return bucket
        return None

    def _table_for(self, directory: str, create: bool = False) -> str:
        if not self.support_bucket_table:
            return self.TABLE
        bucket = self._bucket_of(directory)
        if bucket is None:
            return self.TABLE
        table = f"bucket_{bucket}"
        # only writes materialize the table — a read must never resurrect
        # a dropped bucket (reads on a missing table read as empty)
        if create and table not in self._bucket_tables:
            c = self._conn()
            with self._lock:
                c.cursor().execute(self.dialect.create_table(table))
                c.commit()
                self._bucket_tables.add(table)
        return table

    def on_bucket_creation(self, bucket: str) -> None:
        if self.support_bucket_table:
            self._table_for(f"/buckets/{bucket}/", create=True)

    def on_bucket_deletion(self, bucket: str) -> None:
        if not self.support_bucket_table:
            return
        table = f"bucket_{bucket}"
        c = self._conn()
        with self._lock:
            c.cursor().execute(self.dialect.drop_table(table))
            c.commit()
            self._bucket_tables.discard(table)

    def insert_entry(self, entry: Entry) -> None:
        d, n = self._split(entry.full_path)
        blob = entry.to_pb().SerializeToString()
        table = self._table_for(d, create=True)
        c = self._conn()
        with self._lock:
            try:
                c.cursor().execute(self.dialect.upsert(table), (d, n, blob))
            except Exception as e:
                # another client may have dropped the bucket table since we
                # cached it — recreate once and retry
                if table == self.TABLE or not self._is_missing_table(e):
                    raise
                self._bucket_tables.discard(table)
                c.cursor().execute(self.dialect.create_table(table))
                self._bucket_tables.add(table)
                c.cursor().execute(self.dialect.upsert(table), (d, n, blob))
            c.commit()

    update_entry = insert_entry

    @staticmethod
    def _is_missing_table(exc: Exception) -> bool:
        """Only 'relation/table does not exist' errors may be swallowed —
        connection drops and genuine SQL failures must propagate."""
        sqlstate = getattr(exc, "sqlstate", "")
        if sqlstate == "42P01":          # postgres undefined_table
            return True
        msg = str(exc).lower()
        return ("no such table" in msg          # sqlite
                or "doesn't exist" in msg        # mysql 1146
                or "does not exist" in msg)      # postgres text

    def _bucket_read(self, table: str, fn):
        """Run a read/mutation against a possibly-absent bucket table:
        a dropped bucket's table reads as empty instead of erroring."""
        try:
            return fn()
        except Exception as e:
            if table != self.TABLE and self._is_missing_table(e):
                return None
            raise

    def find_entry(self, full_path: str) -> Entry | None:
        d, n = self._split(full_path)
        table = self._table_for(d)
        cur = self._conn().cursor()

        def go():
            cur.execute(self.dialect.find(table), (d, n))
            return cur.fetchone()

        row = self._bucket_read(table, go)
        if row is None:
            return None
        pb = filer_pb2.Entry.FromString(bytes(row[0]))
        return Entry.from_pb(d, pb)

    def delete_entry(self, full_path: str) -> None:
        d, n = self._split(full_path)
        table = self._table_for(d)
        c = self._conn()
        with self._lock:
            self._bucket_read(table, lambda: (
                c.cursor().execute(self.dialect.delete(table), (d, n)),
                c.commit()))

    def delete_folder_children(self, full_path: str) -> None:
        base = full_path.rstrip("/") or "/"
        bucket = self._bucket_of(base + "/") if self.support_bucket_table \
            else None
        if bucket is not None and base == f"/buckets/{bucket}":
            # whole-bucket delete: drop the bucket table (O(1))
            self.on_bucket_deletion(bucket)
            return
        if self.support_bucket_table and base in ("/", "/buckets"):
            # the delete covers every bucket: drop all bucket tables, not
            # just the main-table rows (enumerated server-side so tables
            # created by other clients/processes are included)
            c = self._conn()
            cur = c.cursor()
            cur.execute(self.dialect.list_bucket_tables())
            tables = [row[0] for row in cur.fetchall()]
            with self._lock:
                for t in tables:
                    c.cursor().execute(self.dialect.drop_table(t))
                    self._bucket_tables.discard(t)
                c.commit()
        table = self._table_for(base)
        c = self._conn()
        # '/' + '/%' would build pattern '//%', which matches no real
        # directory and leaves every deeper descendant row behind on a
        # root-wide wipe; root's descendants all match '/%'
        like = "/%" if base == "/" else like_escape(base) + "/%"
        with self._lock:
            self._bucket_read(table, lambda: (
                c.cursor().execute(
                    self.dialect.delete_folder_children(table),
                    (base, like)),
                c.commit()))

    def list_directory_entries(self, dir_path: str, start_file_name: str = "",
                               include_start: bool = False,
                               limit: int = 1024,
                               prefix: str = "") -> Iterator[Entry]:
        base = dir_path.rstrip("/") or "/"
        table = self._table_for(base)
        cur = self._conn().cursor()

        def go():
            cur.execute(self.dialect.list_entries(table, include_start),
                        (base, start_file_name,
                         like_escape(prefix or "") + "%", limit))
            return cur.fetchall()

        for _name, blob in self._bucket_read(table, go) or []:
            pb = filer_pb2.Entry.FromString(bytes(blob))
            yield Entry.from_pb(base, pb)

    def kv_get(self, key: bytes) -> bytes | None:
        cur = self._conn().cursor()
        cur.execute(self.dialect.kv_get(self.TABLE), (key,))
        row = cur.fetchone()
        return bytes(row[0]) if row else None

    def kv_put(self, key: bytes, value: bytes) -> None:
        c = self._conn()
        with self._lock:
            c.cursor().execute(self.dialect.kv_upsert(self.TABLE),
                               (key, value))
            c.commit()

    def close(self) -> None:
        c = getattr(self._local, "conn", None)
        if c is not None:
            c.close()
            self._local.conn = None
        self._anchor.close()


def _mysql_store(**kwargs) -> AbstractSqlStore:
    return AbstractSqlStore(MySqlDialect(**kwargs))


def _postgres_store(**kwargs) -> AbstractSqlStore:
    return AbstractSqlStore(PostgresDialect(**kwargs))


def _postgres2_store(**kwargs) -> AbstractSqlStore:
    store = AbstractSqlStore(PostgresDialect(**kwargs),
                             support_bucket_table=True)
    store.name = "postgres2"
    return store


def _mysql2_store(**kwargs) -> AbstractSqlStore:
    store = AbstractSqlStore(MySqlDialect(**kwargs),
                             support_bucket_table=True)
    store.name = "mysql2"
    return store


register_store("mysql", _mysql_store)
register_store("mysql2", _mysql2_store)
register_store("postgres", _postgres_store)
register_store("postgres2", _postgres2_store)
