"""Shared SQL filer-store layer + dialects.

Rebuild of /root/reference/weed/filer/abstract_sql/abstract_sql_store.go:
one generic store speaking DB-API, with per-dialect SQL generation (the
reference's SqlGenerator interface: GetSqlInsert/Find/Delete/List/... that
mysql/postgres/sqlite and five more stores all reuse). A dialect supplies:

  * the SQL statements (paramstyle differences: ?, %s, $N)
  * a connect() factory returning DB-API connections
  * upsert syntax (ON CONFLICT / ON DUPLICATE KEY)

The sqlite dialect is fully live; mysql/postgres generate their exact SQL
and are import-gated on their client libraries (pymysql / psycopg2), which
this environment doesn't ship — construction raises with instructions,
matching the repo's convention for cloud-gated backends.
"""

from __future__ import annotations

import threading
from typing import Iterator

from ...pb import filer_pb2
from ..entry import Entry
from ..filerstore import register_store


class SqlDialect:
    """SqlGenerator equivalent (abstract_sql_store.go:15-26)."""

    name = "abstract"
    param = "?"  # DB-API paramstyle placeholder

    def _p(self, n: int) -> list[str]:
        return [self.param] * n

    def kv_table(self, table: str) -> str:
        return f"{table}_kv"

    def create_table(self, table: str) -> str:
        return (f"CREATE TABLE IF NOT EXISTS {table} ("
                f"directory TEXT NOT NULL, name TEXT NOT NULL, meta BLOB, "
                f"PRIMARY KEY (directory, name))")

    def create_kv_table(self, table: str) -> str:
        return (f"CREATE TABLE IF NOT EXISTS {self.kv_table(table)} "
                f"(k BLOB PRIMARY KEY, v BLOB)")

    def drop_table(self, table: str) -> str:
        return f"DROP TABLE IF EXISTS {table}"

    def upsert(self, table: str) -> str:
        a, b, c = self._p(3)
        return (f"INSERT INTO {table}(directory,name,meta) VALUES({a},{b},{c}) "
                f"ON CONFLICT(directory,name) DO UPDATE SET meta=excluded.meta")

    def find(self, table: str) -> str:
        a, b = self._p(2)
        return (f"SELECT meta FROM {table} WHERE directory={a} AND name={b}")

    def delete(self, table: str) -> str:
        a, b = self._p(2)
        return f"DELETE FROM {table} WHERE directory={a} AND name={b}"

    def delete_folder_children(self, table: str) -> str:
        a, b = self._p(2)
        return (f"DELETE FROM {table} WHERE directory={a} "
                f"OR directory LIKE {b}")

    def list_entries(self, table: str, inclusive: bool) -> str:
        op = ">=" if inclusive else ">"
        a, b, c, d = self._p(4)
        return (f"SELECT name, meta FROM {table} WHERE directory={a} "
                f"AND name {op} {b} AND name LIKE {c} "
                f"ORDER BY name LIMIT {d}")

    def kv_upsert(self, table: str) -> str:
        a, b = self._p(2)
        return (f"INSERT INTO {self.kv_table(table)}(k,v) VALUES({a},{b}) "
                f"ON CONFLICT(k) DO UPDATE SET v=excluded.v")

    def kv_get(self, table: str) -> str:
        return f"SELECT v FROM {self.kv_table(table)} WHERE k={self.param}"

    def connect(self):
        raise NotImplementedError


class SqliteDialect(SqlDialect):
    name = "sqlite"
    param = "?"

    def kv_table(self, table: str) -> str:
        # round-1 sqlite databases named this table plain "kv" — keep
        # reading/writing it so existing stores survive the upgrade
        return "kv"

    _mem_seq = 0
    _mem_lock = threading.Lock()

    def __init__(self, db_path: str = ":memory:"):
        self.uri = False
        if db_path == ":memory:":
            # per-connection private :memory: DBs won't do — every server
            # thread must see one namespace. Use a named shared-cache DB.
            with SqliteDialect._mem_lock:
                SqliteDialect._mem_seq += 1
                db_path = (f"file:filer_mem_{id(self)}_"
                           f"{SqliteDialect._mem_seq}?mode=memory&cache=shared")
            self.uri = True
        self.db_path = db_path

    def connect(self):
        import sqlite3

        c = sqlite3.connect(self.db_path, uri=self.uri,
                            check_same_thread=False)
        if not self.uri:
            c.execute("PRAGMA journal_mode=WAL")
            c.execute("PRAGMA synchronous=NORMAL")
        c.execute("PRAGMA busy_timeout=5000")
        return c


class MySqlDialect(SqlDialect):
    """mysql/mysql_store.go + mysql_sql_gen.go SQL shapes."""

    name = "mysql"
    param = "%s"

    def __init__(self, *, host="localhost", port=3306, user="root",
                 password="", database="seaweedfs", **_):
        self.kwargs = dict(host=host, port=port, user=user,
                           password=password, database=database)

    def create_table(self, table: str) -> str:
        # 2 x VARCHAR(383) x 4 bytes/char (utf8mb4) = 3064 bytes, inside
        # InnoDB's 3072-byte composite index limit
        return (f"CREATE TABLE IF NOT EXISTS `{table}` ("
                f"`directory` VARCHAR(383) NOT NULL, "
                f"`name` VARCHAR(383) NOT NULL, `meta` LONGBLOB, "
                f"PRIMARY KEY (`directory`, `name`)) CHARACTER SET utf8mb4")

    def create_kv_table(self, table: str) -> str:
        # BLOB cannot be a MySQL key; bounded VARBINARY can
        return (f"CREATE TABLE IF NOT EXISTS `{self.kv_table(table)}` "
                f"(k VARBINARY(255) PRIMARY KEY, v LONGBLOB)")

    def upsert(self, table: str) -> str:
        return (f"INSERT INTO `{table}`(directory,name,meta) "
                f"VALUES(%s,%s,%s) "
                f"ON DUPLICATE KEY UPDATE meta=VALUES(meta)")

    def kv_upsert(self, table: str) -> str:
        return (f"INSERT INTO `{self.kv_table(table)}`(k,v) VALUES(%s,%s) "
                f"ON DUPLICATE KEY UPDATE v=VALUES(v)")

    def connect(self):
        try:
            import pymysql
        except ImportError:
            raise RuntimeError(
                "the mysql filer store needs pymysql, which is not "
                "installed in this environment")
        return pymysql.connect(**self.kwargs)


class PostgresDialect(SqlDialect):
    """postgres/postgres_store.go + postgres_sql_gen.go SQL shapes."""

    name = "postgres"
    param = "%s"

    def __init__(self, *, host="localhost", port=5432, user="postgres",
                 password="", database="seaweedfs", sslmode="disable", **_):
        self.kwargs = dict(host=host, port=port, user=user,
                           password=password, dbname=database,
                           sslmode=sslmode)

    def create_table(self, table: str) -> str:
        return (f'CREATE TABLE IF NOT EXISTS "{table}" ('
                f"directory VARCHAR(65535) NOT NULL, "
                f"name VARCHAR(65535) NOT NULL, meta BYTEA, "
                f"PRIMARY KEY (directory, name))")

    def create_kv_table(self, table: str) -> str:
        # Postgres has no BLOB type — BYTEA throughout
        return (f'CREATE TABLE IF NOT EXISTS "{self.kv_table(table)}" '
                f"(k BYTEA PRIMARY KEY, v BYTEA)")

    def upsert(self, table: str) -> str:
        return (f'INSERT INTO "{table}"(directory,name,meta) '
                f"VALUES(%s,%s,%s) ON CONFLICT(directory,name) "
                f"DO UPDATE SET meta=EXCLUDED.meta")

    def kv_upsert(self, table: str) -> str:
        return (f'INSERT INTO "{self.kv_table(table)}"(k,v) VALUES(%s,%s) '
                f"ON CONFLICT(k) DO UPDATE SET v=EXCLUDED.v")

    def connect(self):
        try:
            import psycopg2
        except ImportError:
            raise RuntimeError(
                "the postgres filer store needs psycopg2, which is not "
                "installed in this environment")
        return psycopg2.connect(**self.kwargs)


class AbstractSqlStore:
    """FilerStore over any SqlDialect (AbstractSqlStore,
    abstract_sql_store.go:28)."""

    TABLE = "filemeta"

    def __init__(self, dialect: SqlDialect):
        self.dialect = dialect
        self.name = dialect.name
        self._local = threading.local()
        self._lock = threading.Lock()
        # anchor connection: creates the schema and, for shared-cache
        # in-memory sqlite, pins the database alive
        self._anchor = dialect.connect()
        cur = self._anchor.cursor()
        cur.execute(self.dialect.create_table(self.TABLE))
        cur.execute(self.dialect.create_kv_table(self.TABLE))
        self._anchor.commit()

    def _conn(self):
        c = getattr(self._local, "conn", None)
        if c is None:
            c = self.dialect.connect()
            self._local.conn = c
        return c

    @staticmethod
    def _split(full_path: str) -> tuple[str, str]:
        if full_path == "/":
            return "", "/"
        d, _, n = full_path.rstrip("/").rpartition("/")
        return d or "/", n

    def insert_entry(self, entry: Entry) -> None:
        d, n = self._split(entry.full_path)
        blob = entry.to_pb().SerializeToString()
        c = self._conn()
        with self._lock:
            c.cursor().execute(self.dialect.upsert(self.TABLE), (d, n, blob))
            c.commit()

    update_entry = insert_entry

    def find_entry(self, full_path: str) -> Entry | None:
        d, n = self._split(full_path)
        cur = self._conn().cursor()
        cur.execute(self.dialect.find(self.TABLE), (d, n))
        row = cur.fetchone()
        if row is None:
            return None
        pb = filer_pb2.Entry.FromString(bytes(row[0]))
        return Entry.from_pb(d, pb)

    def delete_entry(self, full_path: str) -> None:
        d, n = self._split(full_path)
        c = self._conn()
        with self._lock:
            c.cursor().execute(self.dialect.delete(self.TABLE), (d, n))
            c.commit()

    def delete_folder_children(self, full_path: str) -> None:
        base = full_path.rstrip("/") or "/"
        c = self._conn()
        with self._lock:
            c.cursor().execute(
                self.dialect.delete_folder_children(self.TABLE),
                (base, base + "/%"))
            c.commit()

    def list_directory_entries(self, dir_path: str, start_file_name: str = "",
                               include_start: bool = False,
                               limit: int = 1024,
                               prefix: str = "") -> Iterator[Entry]:
        base = dir_path.rstrip("/") or "/"
        cur = self._conn().cursor()
        cur.execute(self.dialect.list_entries(self.TABLE, include_start),
                    (base, start_file_name, (prefix or "") + "%", limit))
        for _name, blob in cur.fetchall():
            pb = filer_pb2.Entry.FromString(bytes(blob))
            yield Entry.from_pb(base, pb)

    def kv_get(self, key: bytes) -> bytes | None:
        cur = self._conn().cursor()
        cur.execute(self.dialect.kv_get(self.TABLE), (key,))
        row = cur.fetchone()
        return bytes(row[0]) if row else None

    def kv_put(self, key: bytes, value: bytes) -> None:
        c = self._conn()
        with self._lock:
            c.cursor().execute(self.dialect.kv_upsert(self.TABLE),
                               (key, value))
            c.commit()

    def close(self) -> None:
        c = getattr(self._local, "conn", None)
        if c is not None:
            c.close()
            self._local.conn = None
        self._anchor.close()


def _mysql_store(**kwargs) -> AbstractSqlStore:
    return AbstractSqlStore(MySqlDialect(**kwargs))


def _postgres_store(**kwargs) -> AbstractSqlStore:
    return AbstractSqlStore(PostgresDialect(**kwargs))


register_store("mysql", _mysql_store)
register_store("postgres", _postgres_store)
