"""In-memory filer store (maps; the moral equivalent of the reference's
leveldb default for tests — weed/filer/leveldb/leveldb_store.go shape)."""

from __future__ import annotations

from typing import Iterator

from ...utils import locks
from ..entry import Entry
from ..filerstore import register_store


class MemoryStore:
    name = "memory"

    def __init__(self, **_):
        self._entries: dict[str, Entry] = {}
        self._children: dict[str, set[str]] = {}
        self._kv: dict[bytes, bytes] = {}
        # leaf rank 500: a filer store never calls back out under its
        # mutate lock (all stores share the name — same order contract)
        self._lock = locks.wrlock("filer.store.mu", rank=500)

    def insert_entry(self, entry: Entry) -> None:
        with self._lock:
            self._entries[entry.full_path] = entry
            if entry.full_path != "/":
                self._children.setdefault(entry.parent, set()).add(entry.name)

    update_entry = insert_entry

    def find_entry(self, full_path: str) -> Entry | None:
        with self._lock:
            return self._entries.get(full_path)

    def delete_entry(self, full_path: str) -> None:
        with self._lock:
            e = self._entries.pop(full_path, None)
            if e is not None and full_path != "/":
                kids = self._children.get(e.parent)
                if kids:
                    kids.discard(e.name)

    def delete_folder_children(self, full_path: str) -> None:
        with self._lock:
            base = full_path.rstrip("/")
            for name in list(self._children.get(base or "/", ())):
                child = f"{base}/{name}"
                self.delete_folder_children(child)
                self.delete_entry(child)

    def list_directory_entries(self, dir_path: str, start_file_name: str = "",
                               include_start: bool = False, limit: int = 1024,
                               prefix: str = "") -> Iterator[Entry]:
        with self._lock:
            names = sorted(self._children.get(dir_path.rstrip("/") or "/", ()))
        base = dir_path.rstrip("/")
        n = 0
        for name in names:
            if prefix and not name.startswith(prefix):
                continue
            if start_file_name:
                if name < start_file_name:
                    continue
                if name == start_file_name and not include_start:
                    continue
            e = self.find_entry(f"{base}/{name}")
            if e is None:
                continue
            yield e
            n += 1
            if n >= limit:
                return

    def kv_get(self, key: bytes) -> bytes | None:
        return self._kv.get(key)

    def kv_put(self, key: bytes, value: bytes) -> None:
        self._kv[key] = value

    def close(self) -> None:
        pass


register_store("memory", MemoryStore)
