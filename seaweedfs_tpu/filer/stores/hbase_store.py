"""HBase filer store over the Thrift2 gateway wire protocol.

Rebuild of /root/reference/weed/filer/hbase/hbase_store.go (backed by
tsuna/gohbase, the native RegionServer RPC): no hbase client library in
this image, so this store drives HBase's OTHER first-class wire surface
— the Thrift2 gateway's ``THBaseService`` (hbase.thrift, shipped with
every HBase) — through the stdlib Thrift binary-protocol client in
thrift_wire.py. Layout matches the reference exactly:

  * one table, two column families: ``meta`` for entries, ``kv`` for
    the kv API, single qualifier ``a`` (hbase_store.go:42-44,
    hbase_store_kv.go:11 COLUMN_NAME)
  * row key = the full path bytes; entries carry the pb blob in
    meta:a (InsertEntry, hbase_store.go:73)
  * FindEntry -> get (doGet, hbase_store_kv.go:47)
  * DeleteEntry -> deleteSingle (doDelete)
  * ListDirectoryEntries -> getScannerResults from ``dir/<start>``,
    keeping only rows whose parent IS dir (the row keyspace mixes the
    whole subtree, hbase_store.go:152-200)
  * DeleteFolderChildren -> scan the ``dir/`` prefix and delete every
    row under it (hbase_store.go:113 — extended to the whole subtree
    like the other stores in this package, which the flat row keyspace
    gives us in one scan)
  * kv_* -> same ops against the ``kv`` family (hbase_store_kv.go)

Deviation, documented: table creation is admin-plane (the reference
uses gohbase's AdminClient; Thrift2 exposes no DDL), so the table must
exist — the in-repo fake auto-creates it, a real deployment runs
``create 't', 'meta', 'kv'`` once in hbase shell.
"""

from __future__ import annotations

from typing import Iterator

from ...pb import filer_pb2
from ..entry import Entry
from ..filerstore import register_store
from .thrift_wire import I32, LIST, STRING, STRUCT, ThriftClient
from .wire_common import prefix_end, split_dir_name

COLUMN = b"a"
CF_META = b"meta"
CF_KV = b"kv"
SCAN_PAGE = 1024


def _tcolumn(family: bytes) -> list:
    # TColumn {1: family, 2: qualifier}
    return [(1, STRING, family), (2, STRING, COLUMN)]


def _tcolumn_value(family: bytes, value: bytes) -> list:
    # TColumnValue {1: family, 2: qualifier, 3: value}
    return [(1, STRING, family), (2, STRING, COLUMN), (3, STRING, value)]


class HbaseStore:
    """FilerStore over THBaseService (HbaseStore, hbase_store.go:21)."""

    name = "hbase"

    def __init__(self, *, zkquorum: str = "localhost:9090",
                 table: str = "seaweedfs", timeout: int = 30, **_kwargs):
        # the reference's filer.toml key is `zkquorum`; Thrift2 needs
        # the gateway address, so that's what the value means here
        host, _, port = zkquorum.split(",")[0].partition(":")
        self.client = ThriftClient(host, int(port or 9090),
                                   timeout=timeout)
        self.table = table.encode()
        # fail fast (and detect a missing table) like initialize()'s
        # probe get (hbase_store.go:47-55)
        try:
            self._get(CF_META, b"\x00probe")
        except Exception:
            self.client.close()  # don't strand the socket on a bad table
            raise

    # -- thrift2 ops -------------------------------------------------------

    def _get(self, family: bytes, row: bytes) -> bytes | None:
        # get(1: table, 2: TGet{1: row, 2: [TColumn]}) -> TResult
        reply = self.client.call("get", [
            (1, STRING, self.table),
            (2, STRUCT, [(1, STRING, row),
                         (2, LIST, (STRUCT, [_tcolumn(family)]))]),
        ])
        result = reply.get(0) or {}
        for cv in result.get(2) or []:
            return cv.get(3)
        return None

    def _put(self, family: bytes, row: bytes, value: bytes) -> None:
        # put(1: table, 2: TPut{1: row, 2: [TColumnValue]})
        self.client.call("put", [
            (1, STRING, self.table),
            (2, STRUCT, [(1, STRING, row),
                         (2, LIST, (STRUCT,
                                    [_tcolumn_value(family, value)]))]),
        ])

    def _delete(self, family: bytes, row: bytes) -> None:
        # deleteSingle(1: table, 2: TDelete{1: row, 2: [TColumn]})
        self.client.call("deleteSingle", [
            (1, STRING, self.table),
            (2, STRUCT, [(1, STRING, row),
                         (2, LIST, (STRUCT, [_tcolumn(family)]))]),
        ])

    def _scan(self, start: bytes, stop: bytes
              ) -> Iterator[tuple[bytes, bytes]]:
        """(row, meta:a value) ascending over [start, stop), paging
        through getScannerResults like a caching scanner would."""
        cur = start
        while True:
            # getScannerResults(1: table, 2: TScan, 3: i32 numRows)
            reply = self.client.call("getScannerResults", [
                (1, STRING, self.table),
                (2, STRUCT, [(1, STRING, cur), (2, STRING, stop),
                             (3, LIST, (STRUCT, [_tcolumn(CF_META)]))]),
                (3, I32, SCAN_PAGE),
            ])
            results = reply.get(0) or []
            for res in results:
                row = res.get(1)
                for cv in res.get(2) or []:
                    yield row, cv.get(3)
            if len(results) < SCAN_PAGE:
                return
            cur = results[-1].get(1) + b"\x00"

    # -- FilerStore SPI ----------------------------------------------------

    _split = staticmethod(split_dir_name)

    def insert_entry(self, entry: Entry) -> None:
        self._put(CF_META, entry.full_path.encode(),
                  entry.to_pb().SerializeToString())

    update_entry = insert_entry

    def find_entry(self, full_path: str) -> Entry | None:
        blob = self._get(CF_META, full_path.encode())
        if blob is None:
            return None
        d, _ = self._split(full_path)
        return Entry.from_pb(d, filer_pb2.Entry.FromString(blob))

    def delete_entry(self, full_path: str) -> None:
        self._delete(CF_META, full_path.encode())

    def delete_folder_children(self, full_path: str) -> None:
        base = full_path.rstrip("/") or "/"
        prefix = (base.rstrip("/") + "/").encode()
        stop = prefix[:-1] + b"0"  # '/' + 1 == '0': end of the subtree
        for row, _ in list(self._scan(prefix, stop)):
            self._delete(CF_META, row)

    def list_directory_entries(self, dir_path: str, start_file_name: str = "",
                               include_start: bool = False,
                               limit: int = 1024,
                               prefix: str = "") -> Iterator[Entry]:
        base = dir_path.rstrip("/") or "/"
        child_prefix = (base.rstrip("/") + "/").encode()
        start = max(start_file_name, prefix) if prefix else start_file_name
        lo = child_prefix + start.encode()
        if start_file_name and not include_start \
                and start == start_file_name:
            lo += b"\x00"
        if prefix:
            # every matching child row AND its descendants start with
            # dir/<prefix>, so this bound keeps the scan from paging
            # through the rest of the subtree discarding rows
            hi = prefix_end(child_prefix + prefix.encode())
        else:
            hi = child_prefix[:-1] + b"0"  # '/'+1: the whole subtree
        count = 0
        for row, blob in self._scan(lo, hi):
            fullpath = row.decode("utf-8", "replace")
            d, name = self._split(fullpath)
            if d != base:
                continue  # a grandchild's row: same prefix, deeper dir
            if prefix and not name.startswith(prefix):
                continue  # defensive; the range already bounds it
            pb = filer_pb2.Entry.FromString(blob)
            yield Entry.from_pb(base, pb)
            count += 1
            if count >= limit:
                return

    # -- kv (hbase_store_kv.go: kv family, same qualifier) -----------------

    def kv_put(self, key: bytes, value: bytes) -> None:
        self._put(CF_KV, key, value)

    def kv_get(self, key: bytes) -> bytes | None:
        return self._get(CF_KV, key)

    def close(self) -> None:
        self.client.close()


register_store("hbase", HbaseStore)
