"""Pure-python MongoDB wire-protocol client + filer store.

Rebuild of /root/reference/weed/filer/mongodb/mongodb_store.go (which
uses the official mongo-driver): no pymongo in this image, so this
speaks OP_MSG (opcode 2013, the only opcode modern servers accept)
with the in-repo BSON codec, like pg_wire/mysql_wire do for SQL.

Surface — exactly the reference store's command set:

  * ``update`` with upsert (InsertEntry/UpdateEntry,
    mongodb_store.go:103-127)
  * ``find`` with filter/sort/limit + ``getMore`` cursor draining
    (FindEntry :129, ListDirectoryEntries :186)
  * ``delete`` (DeleteEntry :157, DeleteFolderChildren :170)
  * ``createIndexes`` unique (directory, name) (indexUnique :68)
  * SCRAM-SHA-256 auth via saslStart/saslContinue on $db=admin
    (the driver's default for MongoDB >= 4.0)

The kv_* family mirrors mongodb_store_kv.go's genDirAndName split
(first 8 key bytes -> directory, rest -> name); binary keys are mapped
through latin-1 so they stay valid BSON UTF-8 strings losslessly (the
Go driver writes raw bytes into the string, which is out-of-spec BSON).

DeleteFolderChildren in the reference removes only the exact directory
row set (the filer recurses); this store additionally accepts the
repo-wide subtree contract by matching descendants with an anchored
$regex, matching the other stores' LIKE semantics.
"""

from __future__ import annotations

import re
import socket
import struct
import threading
from typing import Iterator

from ...pb import filer_pb2
from ..entry import Entry
from ..filerstore import register_store
from .bson import Int64, Regex, decode_doc, encode_doc
from .wire_common import ScramClient, split_dir_name

OP_MSG = 2013


class MongoError(Exception):
    def __init__(self, code: int, message: str):
        self.code = code
        self.message = message
        super().__init__(f"({code}) {message}")


class MongoConnection:
    def __init__(self, *, host="localhost", port=27017, user="",
                 password="", connect_timeout=10, **_ignored):
        self._host, self._port = host, int(port)
        self._user, self._password = user, password
        self._connect_timeout = connect_timeout
        self._lock = threading.Lock()
        self._sock: socket.socket | None = None
        self._buf = b""
        self._req = 0
        self._connect()

    def _connect(self) -> None:
        self._sock = socket.create_connection(
            (self._host, self._port), timeout=self._connect_timeout)
        self._sock.settimeout(30)
        self._buf = b""
        try:
            if self._user:
                self._auth()
        except Exception:
            self._mark_broken()
            raise

    def _mark_broken(self) -> None:
        try:
            if self._sock is not None:
                self._sock.close()
        except OSError:
            pass
        self._sock = None
        self._buf = b""

    def _recv_exact(self, n: int) -> bytes:
        while len(self._buf) < n:
            chunk = self._sock.recv(65536)
            if not chunk:
                raise ConnectionError("mongodb server closed connection")
            self._buf += chunk
        out, self._buf = self._buf[:n], self._buf[n:]
        return out

    def _roundtrip(self, doc: dict) -> dict:
        self._req += 1
        body = b"\x00\x00\x00\x00" + b"\x00" + encode_doc(doc)
        header = struct.pack("<iiii", 16 + len(body), self._req, 0, OP_MSG)
        self._sock.sendall(header + body)
        (length, _rid, _rto, opcode) = struct.unpack("<iiii",
                                                     self._recv_exact(16))
        payload = self._recv_exact(length - 16)
        if opcode != OP_MSG:
            raise ConnectionError(f"unexpected reply opcode {opcode}")
        # flagBits(4) + kind-0 section document
        if payload[4] != 0:
            raise ConnectionError("unsupported OP_MSG section kind")
        reply, _ = decode_doc(payload, 5)
        return reply

    @staticmethod
    def _check_ok(reply: dict, what: str) -> dict:
        if reply.get("ok") != 1:     # covers int 1 and double 1.0
            raise MongoError(int(reply.get("code", 0)),
                             str(reply.get("errmsg", what)))
        # ok:1 with per-document failures is still a failure (the Go
        # driver surfaces writeErrors from UpdateOne/DeleteMany too) —
        # swallowing them would silently lose acknowledged metadata
        werrs = reply.get("writeErrors")
        if werrs:
            first = werrs[0] if isinstance(werrs, list) else {}
            raise MongoError(int(first.get("code", 0)),
                             f"write error: {first.get('errmsg', werrs)}")
        return reply

    def command(self, db: str, doc: dict) -> dict:
        with self._lock:
            if self._sock is None:
                self._connect()
            try:
                reply = self._roundtrip({**doc, "$db": db})
            except MongoError:
                raise
            except Exception:
                self._mark_broken()
                raise
        return self._check_ok(reply, "command failed")

    def _auth(self) -> None:
        scram = ScramClient(self._password, username=self._user)
        first = self._check_ok(self._roundtrip({
            "saslStart": 1, "mechanism": "SCRAM-SHA-256",
            "payload": scram.client_first(), "$db": "admin"}),
            "saslStart failed")
        final = self._check_ok(self._roundtrip({
            "saslContinue": 1,
            "conversationId": first.get("conversationId", 1),
            "payload": scram.client_final(first["payload"]),
            "$db": "admin"}), "auth failed")
        scram.verify_server(final["payload"])
        for _ in range(3):           # bounded: a server may want one empty
            if final.get("done"):    # closing exchange, never more
                return
            final = self._check_ok(self._roundtrip({
                "saslContinue": 1,
                "conversationId": first.get("conversationId", 1),
                "payload": b"", "$db": "admin"}), "auth failed")
        if not final.get("done"):
            raise MongoError(0, "SASL conversation never completed")

    def close(self) -> None:
        self._mark_broken()


class MongodbStore:
    """FilerStore over the OP_MSG client (mongodb_store.go:21)."""

    name = "mongodb"
    COLLECTION = "filemeta"

    def __init__(self, *, host="localhost", port=27017, database="seaweedfs",
                 user="", password="", **kwargs):
        self.database = database
        self.conn = MongoConnection(host=host, port=port, user=user,
                                    password=password, **kwargs)
        self.conn.command(self.database, {
            "createIndexes": self.COLLECTION,
            "indexes": [{"key": {"directory": 1, "name": 1},
                         "name": "directory_1_name_1", "unique": True}]})

    _split = staticmethod(split_dir_name)

    def _upsert(self, d: str, n: str, meta: bytes) -> None:
        self.conn.command(self.database, {
            "update": self.COLLECTION,
            "updates": [{"q": {"directory": d, "name": n},
                         "u": {"$set": {"meta": meta}}, "upsert": True}]})

    def insert_entry(self, entry: Entry) -> None:
        d, n = self._split(entry.full_path)
        self._upsert(d, n, entry.to_pb().SerializeToString())

    update_entry = insert_entry

    def _find(self, flt: dict, sort: dict | None = None,
              limit: int = 0) -> Iterator[dict]:
        cmd: dict = {"find": self.COLLECTION, "filter": flt}
        if sort:
            cmd["sort"] = sort
        if limit:
            cmd["limit"] = limit
        reply = self.conn.command(self.database, cmd)
        cursor = reply["cursor"]
        try:
            batch = cursor.get("firstBatch", [])
            yield from batch
            seen = len(batch)
            while cursor.get("id"):
                reply = self.conn.command(self.database, {
                    "getMore": Int64(cursor["id"]),
                    "collection": self.COLLECTION})
                cursor = reply["cursor"]
                batch = cursor.get("nextBatch", [])
                if limit and seen + len(batch) > limit:
                    batch = batch[:limit - seen]
                yield from batch
                seen += len(batch)
                if limit and seen >= limit:
                    break
        finally:
            # consumer may abandon the generator mid-listing; a live
            # server-side cursor would otherwise linger for its full
            # timeout and count against open-cursor limits
            if cursor.get("id"):
                try:
                    self.conn.command(self.database, {
                        "killCursors": self.COLLECTION,
                        "cursors": [Int64(cursor["id"])]})
                except Exception:
                    pass

    def find_entry(self, full_path: str) -> Entry | None:
        d, n = self._split(full_path)
        for doc in self._find({"directory": d, "name": n}, limit=1):
            meta = doc.get("meta") or b""
            if not meta:
                return None
            pb = filer_pb2.Entry.FromString(meta)
            return Entry.from_pb(d, pb)
        return None

    def delete_entry(self, full_path: str) -> None:
        d, n = self._split(full_path)
        self.conn.command(self.database, {
            "delete": self.COLLECTION,
            "deletes": [{"q": {"directory": d, "name": n}, "limit": 0}]})

    def delete_folder_children(self, full_path: str) -> None:
        base = full_path.rstrip("/") or "/"
        q = {"$or": [{"directory": base},
                     {"directory": Regex("^" + re.escape(base) + "/")}]}
        self.conn.command(self.database, {
            "delete": self.COLLECTION, "deletes": [{"q": q, "limit": 0}]})

    def list_directory_entries(self, dir_path: str, start_file_name: str = "",
                               include_start: bool = False,
                               limit: int = 1024,
                               prefix: str = "") -> Iterator[Entry]:
        base = dir_path.rstrip("/") or "/"
        name_cond: dict = {"$gte" if include_start else "$gt":
                           start_file_name}
        flt: dict = {"directory": base, "name": name_cond}
        if prefix:
            flt["name"] = {**name_cond,
                           "$regex": Regex("^" + re.escape(prefix))}
        for doc in self._find(flt, sort={"name": 1}, limit=limit):
            meta = doc.get("meta") or b""
            if not meta:
                continue
            pb = filer_pb2.Entry.FromString(meta)
            yield Entry.from_pb(base, pb)

    # -- kv (mongodb_store_kv.go; 8-byte dir/name split) -------------------

    @staticmethod
    def _kv_dir_name(key: bytes) -> tuple[str, str]:
        key = key + b"\x00" * max(0, 8 - len(key))
        return (key[:8].decode("latin-1"), key[8:].decode("latin-1"))

    def kv_put(self, key: bytes, value: bytes) -> None:
        d, n = self._kv_dir_name(key)
        self._upsert(d, n, value)

    def kv_get(self, key: bytes) -> bytes | None:
        d, n = self._kv_dir_name(key)
        for doc in self._find({"directory": d, "name": n}, limit=1):
            # empty value != absent key (matches memory/redis stores)
            return doc.get("meta")
        return None

    def close(self) -> None:
        self.conn.close()


register_store("mongodb", MongodbStore)
