"""ArangoDB filer store over its REST + AQL cursor API.

Rebuild of /root/reference/weed/filer/arangodb/arangodb_store.go
(backed by arangodb/go-driver): HTTP+JSON end to end, so the store
drives it with the same pooled stdlib client the elastic store uses.
Layout matches the reference:

  * document _key = sha-hash of the full path (hashString,
    helpers.go:16; md5 here, same role), fields {directory, name,
    meta, ttl} with meta as an int array (bytesToArray — the Go
    driver's JSON-safe byte encoding; kept for data-format parity)
  * collection per bucket under /buckets/<name>, default
    ``seaweed_no_bucket`` for everything else (BUCKET_PREFIX /
    DEFAULT_COLLECTION, arangodb_store.go:25-26)
  * upserts via ``overwriteMode=replace`` (the reference's
    CreateDocument + conflict->UpdateDocument dance collapsed into
    the server-side form)
  * listings and subtree deletes via AQL over /_api/cursor with
    bindVars, batched (PUT /_api/cursor/<id> drains hasMore pages)
  * basic auth
"""

from __future__ import annotations

import hashlib
import re
from typing import Iterator

from ...pb import filer_pb2
from ..entry import Entry
from ..filerstore import register_store
from .elastic_wire import ElasticClient, ElasticError
from .wire_common import split_dir_name

BUCKET_PREFIX = "/buckets"
DEFAULT_COLLECTION = "seaweed_no_bucket"
KV_COLLECTION = "seaweed_kv"

LIST_AQL = ("FOR d IN @@collection FILTER d.directory == @dir "
            "AND d.name {op} @start AND STARTS_WITH(d.name, @prefix) "
            "SORT d.name ASC LIMIT @limit RETURN d")
SUBTREE_DELETE_AQL = (
    "FOR d IN @@collection FILTER d.directory == @dir OR "
    "STARTS_WITH(d.directory, @sub) REMOVE d IN @@collection")


def _hash_key(full_path: str) -> str:
    return hashlib.md5(full_path.encode()).hexdigest()


class ArangodbStore:
    """FilerStore over the REST/AQL client (ArangodbStore,
    arangodb_store.go:30)."""

    name = "arangodb"

    def __init__(self, *, host="localhost", port=8529, username="root",
                 password="", database="_system", **kwargs):
        self.client = ElasticClient(host=host, port=port,
                                    username=username, password=password,
                                    **kwargs)
        self.db = database
        self._collections: set[str] = set()
        self._ensure_collection(DEFAULT_COLLECTION)
        self._ensure_collection(KV_COLLECTION)

    # -- plumbing ----------------------------------------------------------

    def _api(self, path: str) -> str:
        return f"/_db/{self.db}/_api{path}"

    def _ensure_collection(self, coll: str) -> None:
        if coll in self._collections:
            return
        self.client.request("POST", self._api("/collection"),
                            {"name": coll},
                            ok_statuses=(200, 409))  # 409 = exists
        self._collections.add(coll)

    @staticmethod
    def _bucket_of(full_path: str) -> str | None:
        """Bucket name iff the path is strictly INSIDE a bucket
        (/buckets/<b>/...). The /buckets dir and the bucket dir entries
        themselves live in the default collection so that listing
        /buckets works — the reference resolves '/buckets' itself to
        the default collection but also writes bucket DIR entries into
        bucket collections, making ListAllMyBuckets unserviceable."""
        if not full_path.startswith(BUCKET_PREFIX + "/"):
            return None
        rest = full_path[len(BUCKET_PREFIX) + 1:]
        bucket, sep, tail = rest.partition("/")
        if not sep or not tail:
            return None              # the bucket dir entry itself
        if re.fullmatch(r"[A-Za-z0-9_\-.]+", bucket):
            return bucket
        return None

    def _collection_of(self, full_path: str, create: bool = True) -> str:
        bucket = self._bucket_of(full_path)
        if bucket is None:
            return DEFAULT_COLLECTION
        # ArangoDB collection names can't contain '.'; a plain
        # '.'->'_' swap makes buckets 'a.b' and 'a_b' SHARE a
        # collection (deleting one would wipe the other — S3 bucket
        # names legitimately contain dots). Escape-code instead:
        # '_'->'__' first, then '.'->'_d' — prefix-free, so the
        # mapping is injective for EVERY pair of bucket names, and
        # dot-free, underscore-free names keep their plain form.
        # Layout change from the earlier '.'->'_' scheme: buckets with
        # '_' or '.' in the name map to a NEW collection (the old
        # mapping was lossy, so data written under it was already at
        # risk of cross-bucket deletion; no read-fallback is kept).
        coll = "bucket_" + bucket.replace("_", "__").replace(".", "_d")
        if create:
            self._ensure_collection(coll)
        return coll

    def _collection_for_dir(self, base: str) -> str:
        """Collection holding the CHILDREN of directory `base`."""
        return self._collection_of(base + "/x", create=False)

    def _aql(self, query: str, bind: dict) -> Iterator[dict]:
        res = self.client.request("POST", self._api("/cursor"),
                                  {"query": query, "bindVars": bind,
                                   "batchSize": 1000},
                                  ok_statuses=(200, 201))
        yield from res.get("result") or []
        while res.get("hasMore"):
            res = self.client.request(
                "PUT", self._api(f"/cursor/{res['id']}"), {},
                ok_statuses=(200,))
            yield from res.get("result") or []

    _split = staticmethod(split_dir_name)

    # -- entries -----------------------------------------------------------

    def insert_entry(self, entry: Entry) -> None:
        d, n = self._split(entry.full_path)
        blob = entry.to_pb().SerializeToString()
        coll = self._collection_of(entry.full_path)
        self.client.request(
            "POST",
            self._api(f"/document/{coll}?overwriteMode=replace"),
            {"_key": _hash_key(entry.full_path), "directory": d,
             "name": n, "meta": list(blob)})

    update_entry = insert_entry

    def _decode(self, doc: dict, directory: str) -> Entry | None:
        meta = doc.get("meta")
        if not meta:
            return None
        pb = filer_pb2.Entry.FromString(bytes(meta))
        return Entry.from_pb(directory, pb)

    def find_entry(self, full_path: str) -> Entry | None:
        coll = self._collection_of(full_path, create=False)
        try:
            doc = self.client.request(
                "GET",
                self._api(f"/document/{coll}/{_hash_key(full_path)}"),
                ok_statuses=(200,))
        except ElasticError as e:
            if e.status == 404:
                return None
            raise
        d, _ = self._split(full_path)
        return self._decode(doc, d)

    def delete_entry(self, full_path: str) -> None:
        coll = self._collection_of(full_path, create=False)
        try:
            self.client.request(
                "DELETE",
                self._api(f"/document/{coll}/{_hash_key(full_path)}"),
                ok_statuses=(200, 202, 404))
        except ElasticError as e:
            if e.status != 404:
                raise

    def _drop_bucket_collections(self) -> None:
        res = self.client.request("GET", self._api("/collection"),
                                  ok_statuses=(200,))
        for c in res.get("result", []):
            name = c["name"] if isinstance(c, dict) else c
            if name.startswith("bucket_"):
                self.client.request("DELETE",
                                    self._api(f"/collection/{name}"),
                                    ok_statuses=(200, 404))
                self._collections.discard(name)

    def delete_folder_children(self, full_path: str) -> None:
        base = full_path.rstrip("/") or "/"
        coll = self._collection_for_dir(base)
        bucket = self._bucket_of(base + "/x")
        if bucket is not None and base == f"{BUCKET_PREFIX}/{bucket}":
            # whole-bucket wipe: drop the bucket collection O(1)
            self.client.request("DELETE",
                                self._api(f"/collection/{coll}"),
                                ok_statuses=(200, 404))
            self._collections.discard(coll)
            return
        if base in ("/", BUCKET_PREFIX):
            # the wipe spans every bucket collection too; and at root
            # the descendant prefix must be "/" itself (base + "/"
            # would be "//", which no directory starts with)
            self._drop_bucket_collections()
        sub = "/" if base == "/" else base + "/"
        try:
            list(self._aql(SUBTREE_DELETE_AQL,
                           {"@collection": coll, "dir": base,
                            "sub": sub}))
        except ElasticError as e:
            if e.status != 404:
                raise

    def list_directory_entries(self, dir_path: str, start_file_name: str = "",
                               include_start: bool = False,
                               limit: int = 1024,
                               prefix: str = "") -> Iterator[Entry]:
        base = dir_path.rstrip("/") or "/"
        coll = self._collection_for_dir(base)
        op = ">=" if include_start else ">"
        query = LIST_AQL.replace("{op}", op)
        try:
            docs = self._aql(query, {"@collection": coll, "dir": base,
                                     "start": start_file_name,
                                     "prefix": prefix or "",
                                     "limit": limit})
            for doc in docs:
                entry = self._decode(doc, base)
                if entry is not None:
                    yield entry
        except ElasticError as e:
            if e.status == 404:
                return
            raise

    # -- kv (arangodb_store_kv.go: hashed key doc in a kv collection) ------

    def kv_put(self, key: bytes, value: bytes) -> None:
        self.client.request(
            "POST",
            self._api(f"/document/{KV_COLLECTION}?overwriteMode=replace"),
            {"_key": key.hex(), "value": list(value)})

    def kv_get(self, key: bytes) -> bytes | None:
        try:
            doc = self.client.request(
                "GET", self._api(f"/document/{KV_COLLECTION}/{key.hex()}"),
                ok_statuses=(200,))
        except ElasticError as e:
            if e.status == 404:
                return None
            raise
        v = doc.get("value")
        return bytes(v) if v is not None else None

    def close(self) -> None:
        self.client.close()


register_store("arangodb", ArangodbStore)
