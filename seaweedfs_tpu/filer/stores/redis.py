"""Redis filer store over a stdlib RESP wire client.

Counterpart of /root/reference/weed/filer/redis2/redis_store.go: the
entry protobuf lives at the full-path key, and each directory keeps a
sorted set of child names (score 0 — member order is the lexical order
listings need). redis-py is not in this image, so the wire client
speaks RESP itself over a socket; the store therefore runs against any
real Redis server, and the test suite runs it against the in-process
pure-python RESP server in tests/fake_redis.py.

Registered as `redis` and `redis2` (the reference's redis/ and redis2/
differ only in the member structure — plain set vs sorted set; this
implementation uses the sorted-set layout of redis2 for both names).
"""

from __future__ import annotations

import socket
import threading

from ...pb import filer_pb2
from ..entry import Entry
from ..filerstore import register_store

DIR_SET_SUFFIX = b"\x00"  # per-directory sorted-set key (redis2 layout)
KV_PREFIX = b"kv:"  # path keys always start with '/': no collision


class RespError(IOError):
    """Server-reported error (-ERR ...); the connection stays in sync."""


class RespProtocolError(RespError):
    """Framing/IO failure mid-reply; the connection must be discarded."""


class RespClient:
    """Minimal RESP2 client: encode command arrays, parse replies.
    One in-flight command at a time (lock-serialized), like the
    reference's default non-pipelined go-redis usage."""

    def __init__(self, host: str = "localhost", port: int = 6379, *,
                 db: int = 0, password: str = "", timeout: float = 30):
        self._sock = socket.create_connection((host, port), timeout=timeout)
        self._f = self._sock.makefile("rb")
        self._lock = threading.Lock()
        if password:
            self.cmd("AUTH", password)
        if db:
            self.cmd("SELECT", str(db))

    def close(self) -> None:
        try:
            self._f.close()
            self._sock.close()
        except OSError:
            pass

    @staticmethod
    def _encode(args) -> bytes:
        out = [b"*%d\r\n" % len(args)]
        for a in args:
            b = a if isinstance(a, bytes) else str(a).encode()
            out.append(b"$%d\r\n%s\r\n" % (len(b), b))
        return b"".join(out)

    def _exchange_locked(self, payload: bytes, read):
        """Send `payload` and return read(); caller holds the lock.
        Any I/O failure (timeout, short read) poisons the connection —
        a stale reply could still be queued on the socket, and parsing
        it as the NEXT command's reply would silently return wrong data
        (redis-py likewise closes on I/O errors). A server -ERR reply
        (RespError) leaves the connection in sync."""
        if self._sock is None:
            raise RespProtocolError(
                "connection is closed (previous I/O error)")
        try:
            self._sock.sendall(payload)
            return read()
        except RespProtocolError:
            self.close()
            self._sock = None
            raise
        except RespError:
            raise  # server -ERR reply: connection is still in sync
        except OSError:  # NB: RespError subclasses OSError — order!
            self.close()
            self._sock = None
            raise

    def cmd(self, *args):
        """-> reply (str for simple strings, int, bytes | None for bulk,
        list for arrays). Raises RespError for server errors."""
        payload = self._encode(args)
        with self._lock:
            return self._exchange_locked(payload, self._read_reply)

    def transaction(self, *cmds):
        """MULTI ... EXEC as one locked unit -> EXEC's reply array.

        The lock is held across the whole exchange: sending MULTI and
        EXEC as separate cmd() calls would let another thread's command
        land inside the open transaction, where the server QUEUEs it
        (its caller then reads '+QUEUED' as its reply) and EXEC's array
        absorbs its result — reply-stream corruption under the filer's
        threaded HTTP server. All frames go out in one sendall and the
        replies (+OK, +QUEUED xN, EXEC array) are read back in order.
        """
        payload = b"".join(self._encode(args) for args in
                           ((("MULTI",),) + cmds + (("EXEC",),)))

        def read_all():
            replies = []
            err = None
            for _ in range(len(cmds) + 2):
                try:
                    replies.append(self._read_reply())
                except RespProtocolError:
                    raise
                except RespError as e:
                    # queue-time error (e.g. bad command): the server
                    # still answers the remaining frames, so keep
                    # draining to stay in sync
                    replies.append(e)
                    err = err or e
            if err is not None:
                raise err
            exec_reply = replies[-1]
            if exec_reply is None:
                # EXEC replied nil: the server aborted the transaction
                # (WATCH conflict, cluster failover). The stream is fully
                # drained, so raising keeps the connection in sync —
                # returning None here let callers (redis3 segment split)
                # mistake an aborted transaction for a commit.
                raise RespError("transaction aborted: EXEC returned nil")
            if isinstance(exec_reply, list):
                # exec-time failures arrive as error ELEMENTS inside
                # the reply array; the stream is fully drained, so
                # raising keeps the connection in sync
                for el in exec_reply:
                    if isinstance(el, RespError):
                        raise el
            return exec_reply

        with self._lock:
            return self._exchange_locked(payload, read_all)

    def _read_reply(self, nested: bool = False):
        line = self._f.readline()
        if not line.endswith(b"\r\n"):
            raise RespProtocolError("connection closed mid-reply")
        kind, rest = line[:1], line[1:-2]
        if kind == b"+":
            return rest.decode()
        if kind == b"-":
            # Inside an array (EXEC replies): raising here would abandon
            # the remaining elements on the socket and desynchronize the
            # stream — return the error as a value (redis-py does the
            # same) and let the caller decide.
            if nested:
                return RespError(rest.decode())
            raise RespError(rest.decode())
        if kind == b":":
            return int(rest)
        if kind == b"$":
            n = int(rest)
            if n < 0:
                return None
            blob = self._f.read(n + 2)
            if len(blob) != n + 2:
                raise RespProtocolError("short bulk read")
            return blob[:-2]
        if kind == b"*":
            n = int(rest)
            if n < 0:
                return None
            return [self._read_reply(nested=True) for _ in range(n)]
        raise RespProtocolError(f"bad RESP type byte {kind!r}")


def _dir_set_key(dir_path: str) -> bytes:
    return (dir_path.rstrip("/") or "/").encode() + DIR_SET_SUFFIX


class RedisStore:
    name = "redis"

    def __init__(self, host: str = "localhost", port: int = 6379, *,
                 address: str = "", db: int = 0, database: int = 0,
                 password: str = "", **_ignored):
        # `address`/`database` are the filer.toml field names the
        # reference's [redis2] section uses (scaffold.go)
        if address:
            host, _, p = address.partition(":")
            port = int(p or 6379)
        self.client = RespClient(host, port, db=db or database,
                                 password=password)

    # -- child-index hooks (redis3 overrides these with the segmented
    #    layout; entry-blob handling stays shared) -------------------------

    def _index_child(self, dir_path: str, name: str) -> None:
        self.client.cmd("ZADD", _dir_set_key(dir_path), "0", name.encode())

    def _unindex_child(self, dir_path: str, name: str) -> None:
        self.client.cmd("ZREM", _dir_set_key(dir_path), name.encode())

    def _iter_child_names(self, dir_path: str, lo: str,
                          inclusive: bool):
        """Child names >= lo (or > lo), ascending. Paged so an
        emptiness probe never pulls a huge directory over the wire."""
        set_key = _dir_set_key(dir_path)
        if lo:
            bound = (("[" if inclusive else "(") + lo).encode()
        else:
            bound = b"-"
        offset, page_size = 0, 1024
        while True:
            page = self.client.cmd("ZRANGEBYLEX", set_key, bound, b"+",
                                   "LIMIT", str(offset), str(page_size))
            if not page:
                return
            for m in page:
                yield m.decode()
            if len(page) < page_size:
                return
            offset += len(page)

    # -- FilerStore SPI ----------------------------------------------------

    def insert_entry(self, entry: Entry) -> None:
        blob = filer_pb2.FullEntry(
            dir=entry.parent, entry=entry.to_pb()).SerializeToString()
        self.client.cmd("SET", entry.full_path.encode(), blob)
        self._index_child(entry.parent, entry.name)

    update_entry = insert_entry

    def find_entry(self, full_path: str) -> Entry | None:
        blob = self.client.cmd("GET", full_path.encode())
        if blob is None:
            return None
        fe = filer_pb2.FullEntry.FromString(blob)
        return Entry.from_pb(fe.dir, fe.entry)

    def delete_entry(self, full_path: str) -> None:
        d, _, name = full_path.rpartition("/")
        self.client.cmd("DEL", full_path.encode())
        self._unindex_child(d or "/", name)

    def delete_folder_children(self, full_path: str) -> None:
        """BFS over the per-directory sets: every descendant entry key
        and set key goes (DeleteFolderChildren, redis2_store.go —
        extended to the whole subtree, matching the leveldb store).
        Child entry keys + the set key go in ONE variadic DEL per
        directory; an empty ZRANGEBYLEX means the set key doesn't exist
        (redis removes empty zsets), so no DEL is issued for leaves."""
        stack = [full_path.rstrip("/") or "/"]
        while stack:
            d = stack.pop()
            set_key = _dir_set_key(d)
            members = self.client.cmd("ZRANGEBYLEX", set_key, "-", "+")
            if not members:
                continue
            children = [(d.rstrip("/") or "") + "/" + m.decode()
                        for m in members]
            self.client.cmd("DEL", *[c.encode() for c in children],
                            set_key)
            stack.extend(children)  # any may be a dir: sets get swept

    def list_directory_entries(self, dir_path: str,
                               start_file_name: str = "",
                               include_start: bool = False,
                               limit: int = 1024, prefix: str = ""):
        """Paged ZRANGEBYLEX ... LIMIT: a limit=2 emptiness probe against
        a 100k-child directory must not pull 100k member names over the
        wire (the reference redis2 store pushes LIMIT down the same
        way)."""
        d = dir_path.rstrip("/") or "/"
        lo, inclusive = start_file_name, include_start or not start_file_name
        if prefix and prefix > lo:
            lo, inclusive = prefix, True
        count = 0
        for name in self._iter_child_names(d, lo, inclusive):
            if prefix and not name.startswith(prefix):
                if name > prefix:  # lex-sorted: no more matches
                    return
                continue
            e = self.find_entry((d.rstrip("/") or "") + "/" + name)
            if e is None:
                continue
            yield e
            count += 1
            if count >= limit:
                return

    def kv_get(self, key: bytes) -> bytes | None:
        return self.client.cmd("GET", KV_PREFIX + key)

    def kv_put(self, key: bytes, value: bytes) -> None:
        self.client.cmd("SET", KV_PREFIX + key, value)

    def close(self) -> None:
        self.client.close()


class Redis2Store(RedisStore):
    name = "redis2"


register_store("redis", RedisStore)
register_store("redis2", Redis2Store)
