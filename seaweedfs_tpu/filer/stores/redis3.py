"""redis3 filer store: bounded-size directory listings.

Rebuild of /root/reference/weed/filer/redis3/ (UniversalRedis3Store):
entry blobs are stored exactly like redis/redis2 (path-keyed, shared
code in RedisStore), but a directory's children live in a
*size-bounded* structure instead of one unbounded sorted set — redis3's
reason to exist is directories with millions of children, where a
single ZSET key becomes a hot, unsharded giant. The reference builds a
redis-backed skiplist of name batches (ItemList.go + util/skiplist,
~3.3k LoC); this store keeps the same invariants with a flatter shape —
a segment index:

  * ``<dir>\\x00idx``      — ZSET of segment START names (the implicit
    root segment "" is not listed; the NUL byte keeps these keys out
    of the entry-path keyspace, like redis.py's DIR_SET_SUFFIX)
  * ``<dir>\\x00seg:<b64(start)>`` — ZSET of the names in that segment

Each segment holds at most 2*batch names; inserts that overflow split
the segment at its median inside a MULTI/EXEC transaction (a crash
between the member move and the index update must not strand a batch
of durable entries in an unreachable segment), and removals drop empty
non-root segments. Lookups/listings locate the segment by
ZREVRANGEBYLEX over the index — the same O(log-ish) contact pattern as
the skiplist, with per-key cardinality bounded by the batch size.

Deviation, documented: the on-wire layout is NOT compatible with data
written by the Go redis3 store (its skiplist serde lives in redis
hashes); entry blobs ARE compatible with this repo's redis/redis2.
"""

from __future__ import annotations

import base64
from typing import Iterator

from ..filerstore import register_store
from .redis import RedisStore

DEFAULT_BATCH = 1000
IDX_SUFFIX = b"\x00idx"
SEG_SUFFIX = b"\x00seg:"


class SegmentedNameList:
    """Size-bounded sorted name list over redis ZSET segments."""

    def __init__(self, client, dir_key: bytes, batch: int = DEFAULT_BATCH):
        self.client = client
        self.idx = dir_key + IDX_SUFFIX
        self._seg_prefix = dir_key + SEG_SUFFIX
        self.batch = batch

    def _seg_key(self, start: str) -> bytes:
        return self._seg_prefix + base64.urlsafe_b64encode(start.encode())

    def _seg_start_for(self, name: str) -> str:
        """Greatest segment start <= name; '' is the implicit root."""
        got = self.client.cmd("ZREVRANGEBYLEX", self.idx,
                              b"[" + name.encode(), b"-",
                              "LIMIT", "0", "1")
        return got[0].decode() if got else ""

    def insert(self, name: str) -> None:
        start = self._seg_start_for(name)
        seg = self._seg_key(start)
        self.client.cmd("ZADD", seg, "0", name.encode())
        if int(self.client.cmd("ZCARD", seg)) > 2 * self.batch:
            self._split(seg)

    def _split(self, seg: bytes) -> None:
        members = self.client.cmd("ZRANGEBYLEX", seg, "-", "+")
        mid = members[len(members) // 2].decode()
        upper = members[len(members) // 2:]
        new_seg = self._seg_key(mid)
        # atomic: a crash between moving members and indexing the new
        # segment would otherwise strand `upper` unreachable to listings;
        # transaction() holds the client lock across MULTI..EXEC so a
        # concurrent thread's command can't be QUEUED into it
        self.client.transaction(
            ("ZADD", new_seg, *[x for m in upper for x in (b"0", m)]),
            ("ZADD", self.idx, "0", mid.encode()),
            ("ZREM", seg, *upper))

    def remove(self, name: str) -> None:
        start = self._seg_start_for(name)
        seg = self._seg_key(start)
        self.client.cmd("ZREM", seg, name.encode())
        if start and not int(self.client.cmd("ZCARD", seg)):
            self.client.cmd("ZREM", self.idx, start.encode())

    def iterate(self, lo: str = "", inclusive: bool = True,
                page_size: int = 1024) -> Iterator[str]:
        """Names >= lo (or > lo), ascending, across segments."""
        start = self._seg_start_for(lo) if lo else ""
        bound = (("[" if inclusive else "(") + lo).encode() if lo else b"-"
        while True:
            seg = self._seg_key(start)
            offset = 0
            while True:
                page = self.client.cmd("ZRANGEBYLEX", seg, bound, b"+",
                                       "LIMIT", str(offset),
                                       str(page_size))
                if not page:
                    break
                for m in page:
                    yield m.decode()
                if len(page) < page_size:
                    break
                offset += len(page)
            nxt = self.client.cmd("ZRANGEBYLEX", self.idx,
                                  b"(" + start.encode() if start else b"-",
                                  b"+", "LIMIT", "0", "1")
            if not nxt:
                return
            start = nxt[0].decode()
            bound = b"-"  # subsequent segments stream from their head

    def collect_with_keys(self) -> tuple[list[str], list[bytes]]:
        """(all names, all redis keys incl. index) in ~2 + segments
        round trips; ([], []) for a leaf with neither segment nor index
        so callers can skip the DEL entirely."""
        root = self._seg_key("")
        names = [m.decode() for m in
                 (self.client.cmd("ZRANGEBYLEX", root, "-", "+") or [])]
        starts = [s.decode() for s in
                  (self.client.cmd("ZRANGEBYLEX", self.idx, "-", "+")
                   or [])]
        if not names and not starts:
            return [], []
        keys = [root]
        for s in starts:
            seg = self._seg_key(s)
            keys.append(seg)
            names += [m.decode() for m in
                      (self.client.cmd("ZRANGEBYLEX", seg, "-", "+")
                       or [])]
        keys.append(self.idx)
        return names, keys


class Redis3Store(RedisStore):
    """RedisStore with segmented (bounded-key) directory listings
    (universal_redis_store.go in redis3/). Entry-blob handling is the
    parent's; only the child-index hooks differ."""

    name = "redis3"

    def __init__(self, *args, batch: int = DEFAULT_BATCH, **kwargs):
        super().__init__(*args, **kwargs)
        self.batch = int(batch)

    def _names(self, dir_path: str) -> SegmentedNameList:
        key = (dir_path.rstrip("/") or "/").encode()
        return SegmentedNameList(self.client, key, self.batch)

    # child-index hooks (see RedisStore)
    def _index_child(self, dir_path: str, name: str) -> None:
        self._names(dir_path).insert(name)

    def _unindex_child(self, dir_path: str, name: str) -> None:
        self._names(dir_path).remove(name)

    def _iter_child_names(self, dir_path: str, lo: str, inclusive: bool):
        return self._names(dir_path).iterate(lo, inclusive)

    def delete_folder_children(self, full_path: str) -> None:
        stack = [full_path.rstrip("/") or "/"]
        while stack:
            d = stack.pop()
            names, keys = self._names(d).collect_with_keys()
            if not keys:
                continue  # leaf: nothing indexed, nothing to DEL
            children = [(d.rstrip("/") or "") + "/" + n for n in names]
            self.client.cmd("DEL", *[c.encode() for c in children], *keys)
            stack.extend(children)  # dirs among them get swept next


register_store("redis3", Redis3Store)
