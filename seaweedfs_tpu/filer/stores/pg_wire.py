"""Pure-python PostgreSQL wire-protocol (v3) client, DB-API flavored.

Rebuild of the client side the reference gets from lib/pq
(/root/reference/weed/filer/postgres/postgres_store.go:1 imports
_ "github.com/lib/pq"): no psycopg2 in this image, so the store speaks
the v3 protocol itself, the same way stores/redis.py speaks RESP.

Scope — exactly what AbstractSqlStore needs, implemented on the real
wire format so the same code path talks to an actual postgres:

  * StartupMessage + auth: trust, cleartext (3), md5 (5), and
    SCRAM-SHA-256 (10/11/12, RFC 7677 via hashlib.pbkdf2_hmac)
  * extended query protocol: Parse/Bind/Describe/Execute/Sync —
    ``%s`` DB-API placeholders are rewritten to ``$N``; parameters are
    sent with per-parameter format codes (text for str, binary for
    bytes) so bytea round-trips without hex-escaping games
  * all-binary result columns, decoded by RowDescription type OID
    (text/varchar/name -> str, bytea -> bytes, int2/4/8 -> int)
  * one statement per Sync; errors surface as PgError with the
    server's SQLSTATE + message

Transactions: like the reference's database/sql usage, statements
autocommit; ``commit()`` is a no-op kept for DB-API shape.
"""

from __future__ import annotations

import hashlib
import socket
import struct
import threading

from .wire_common import (
    ScramClient,
    WireCursor,
    rewrite_placeholders,
)


class PgError(Exception):
    def __init__(self, fields: dict[str, str]):
        self.sqlstate = fields.get("C", "")
        self.message = fields.get("M", "postgres error")
        super().__init__(f"{self.sqlstate}: {self.message}")


# binary-format decoders by type OID
_OID_TEXT = {25, 1043, 19, 18, 2275}   # text, varchar, name, char, cstring
_OID_BYTEA = 17
_OID_INT = {20: 8, 23: 4, 21: 2}       # int8/int4/int2
_OID_BOOL = 16


def _decode_col(oid: int, data: bytes | None):
    if data is None:
        return None
    if oid == _OID_BYTEA:
        return bytes(data)
    if oid in _OID_INT:
        return int.from_bytes(data, "big", signed=True)
    if oid == _OID_BOOL:
        return data != b"\x00"
    if oid in _OID_TEXT:
        return data.decode("utf-8", errors="replace")
    return bytes(data)  # unknown: hand back raw


class PgCursor(WireCursor):
    pass


class PgConnection:
    def __init__(self, *, host="localhost", port=5432, user="postgres",
                 password="", dbname="seaweedfs", connect_timeout=10,
                 application_name="seaweedfs_tpu", **_ignored):
        self.user = user
        self.password = password
        self._host, self._port = host, int(port)
        self._dbname, self._appname = dbname, application_name
        self._connect_timeout = connect_timeout
        self._lock = threading.Lock()
        self._sock: socket.socket | None = None
        self._buf = b""
        self._connect()

    def _connect(self) -> None:
        self._sock = socket.create_connection(
            (self._host, self._port), timeout=self._connect_timeout)
        self._sock.settimeout(30)
        self._buf = b""
        try:
            self._startup(self.user, self._dbname, self._appname)
        except Exception:
            # never keep a half-authenticated socket for the next query
            self._mark_broken()
            raise

    def _mark_broken(self) -> None:
        """A socket error mid-exchange leaves the stream desynchronized —
        drop the connection so the next query reconnects cleanly."""
        try:
            if self._sock is not None:
                self._sock.close()
        except OSError:
            pass
        self._sock = None
        self._buf = b""

    # -- wire primitives ---------------------------------------------------

    def _send(self, type_byte: bytes, payload: bytes) -> None:
        self._sock.sendall(type_byte + struct.pack(">I", len(payload) + 4)
                           + payload)

    def _recv_exact(self, n: int) -> bytes:
        while len(self._buf) < n:
            chunk = self._sock.recv(65536)
            if not chunk:
                raise ConnectionError("postgres server closed connection")
            self._buf += chunk
        out, self._buf = self._buf[:n], self._buf[n:]
        return out

    def _recv_msg(self) -> tuple[bytes, bytes]:
        head = self._recv_exact(5)
        tag = head[:1]
        (length,) = struct.unpack(">I", head[1:5])
        return tag, self._recv_exact(length - 4)

    # -- startup + auth ----------------------------------------------------

    def _startup(self, user: str, dbname: str, appname: str) -> None:
        kv = (f"user\0{user}\0database\0{dbname}\0"
              f"application_name\0{appname}\0client_encoding\0UTF8\0\0")
        payload = struct.pack(">I", 196608) + kv.encode()
        self._sock.sendall(struct.pack(">I", len(payload) + 4) + payload)
        scram = None
        while True:
            tag, body = self._recv_msg()
            if tag == b"E":
                raise PgError(self._parse_error(body))
            if tag == b"R":
                (code,) = struct.unpack(">I", body[:4])
                if code == 0:            # AuthenticationOk
                    continue
                if code == 3:            # cleartext password
                    self._send(b"p", self.password.encode() + b"\0")
                elif code == 5:          # md5
                    salt = body[4:8]
                    inner = hashlib.md5(
                        self.password.encode() + self.user.encode()
                    ).hexdigest()
                    digest = hashlib.md5(
                        inner.encode() + salt).hexdigest()
                    self._send(b"p", b"md5" + digest.encode() + b"\0")
                elif code == 10:         # SASL: mechanism list
                    mechs = body[4:].split(b"\0")
                    if b"SCRAM-SHA-256" not in mechs:
                        raise PgError({"M": "no supported SASL mechanism",
                                       "C": "28000"})
                    scram = ScramClient(self.password)
                    first = scram.client_first()
                    self._send(b"p", b"SCRAM-SHA-256\0"
                               + struct.pack(">I", len(first)) + first)
                elif code == 11:         # SASL continue
                    final = scram.client_final(body[4:])
                    self._send(b"p", final)
                elif code == 12:         # SASL final
                    scram.verify_server(body[4:])
                else:
                    raise PgError({"M": f"unsupported auth code {code}",
                                   "C": "28000"})
            elif tag == b"Z":            # ReadyForQuery
                return
            # S (ParameterStatus), K (BackendKeyData), N (Notice): skip

    # -- extended-protocol query ------------------------------------------

    def _query(self, sql: str, params: tuple) -> tuple[list[tuple], int]:
        pg_sql = rewrite_placeholders(sql, lambda n: f"${n}")
        with self._lock:
            if self._sock is None:
                self._connect()
            try:
                return self._query_locked(pg_sql, params)
            except PgError:
                raise  # server error: stream was drained to ReadyForQuery
            except Exception:
                # Parse failures (struct.error/IndexError on a malformed
                # RowDescription/DataRow) abort mid-result-stream; the
                # unread messages up to ReadyForQuery would be consumed
                # as the NEXT query's replies. Same discipline as
                # mysql_wire: poison the connection.
                self._mark_broken()
                raise

    def _query_locked(self, pg_sql: str,
                      params: tuple) -> tuple[list[tuple], int]:
        # Parse (unnamed statement)
        self._send(b"P", b"\0" + pg_sql.encode() + b"\0"
                   + struct.pack(">h", 0))
        # Bind: per-param format codes, all-binary results
        parts = [b"\0\0", struct.pack(">h", len(params))]
        for p in params:
            parts.append(struct.pack(
                ">h", 1 if isinstance(p, (bytes, bytearray, memoryview))
                else 0))
        parts.append(struct.pack(">h", len(params)))
        for p in params:
            if p is None:
                parts.append(struct.pack(">i", -1))
                continue
            if isinstance(p, (bytes, bytearray, memoryview)):
                raw = bytes(p)
            elif isinstance(p, bool):
                raw = b"true" if p else b"false"
            else:
                raw = str(p).encode("utf-8")
            parts.append(struct.pack(">i", len(raw)) + raw)
        parts.append(struct.pack(">hh", 1, 1))  # results: binary
        self._send(b"B", b"".join(parts))
        self._send(b"D", b"P\0")     # Describe portal
        self._send(b"E", b"\0" + struct.pack(">i", 0))
        self._send(b"S", b"")        # Sync
        rows: list[tuple] = []
        oids: list[int] = []
        rowcount = -1
        err: dict[str, str] | None = None
        while True:
            tag, body = self._recv_msg()
            if tag == b"T":          # RowDescription
                (ncols,) = struct.unpack(">h", body[:2])
                off = 2
                oids = []
                for _ in range(ncols):
                    end = body.index(b"\0", off)
                    off = end + 1 + 18
                    (oid,) = struct.unpack(">I", body[end + 7:end + 11])
                    oids.append(oid)
            elif tag == b"D":        # DataRow
                (ncols,) = struct.unpack(">h", body[:2])
                off = 2
                vals = []
                for ci in range(ncols):
                    (ln,) = struct.unpack(">i", body[off:off + 4])
                    off += 4
                    if ln < 0:
                        vals.append(None)
                    else:
                        oid = oids[ci] if ci < len(oids) else 17
                        vals.append(_decode_col(oid, body[off:off + ln]))
                        off += ln
                rows.append(tuple(vals))
            elif tag == b"C":        # CommandComplete
                words = body.rstrip(b"\0").split()
                if words and words[-1].isdigit():
                    rowcount = int(words[-1])
            elif tag == b"E":
                err = self._parse_error(body)
            elif tag == b"Z":        # ReadyForQuery — done
                break
            # 1/2/n/s (ParseComplete/BindComplete/NoData/Suspended): skip
        if err is not None:
            raise PgError(err)
        return rows, rowcount

    @staticmethod
    def _parse_error(body: bytes) -> dict[str, str]:
        fields: dict[str, str] = {}
        off = 0
        while off < len(body) and body[off:off + 1] != b"\0":
            code = chr(body[off])
            end = body.index(b"\0", off + 1)
            fields[code] = body[off + 1:end].decode("utf-8", "replace")
            off = end + 1
        return fields

    # -- DB-API shape ------------------------------------------------------

    def cursor(self) -> PgCursor:
        return PgCursor(self)

    def commit(self) -> None:
        pass  # autocommit, one statement per Sync

    def close(self) -> None:
        try:
            self._send(b"X", b"")        # Terminate
        except OSError:
            pass
        try:
            self._sock.close()
        except OSError:
            pass
