"""Minimal BSON codec for the mongodb wire client (mongo_wire.py).

Covers the element types the filer store exchanges with a server:
document (0x03), array (0x04), string (0x02), binary (0x05, subtype
generic), double (0x01), bool (0x08), null (0x0A), int32 (0x10), int64
(0x12), plus decode-only ObjectId (0x07), UTC datetime (0x09),
timestamp (0x11), regex (0x0B) and decimal128 (0x13, surfaced as raw
bytes) so server replies never desync the parser. Dicts preserve
insertion order, which BSON requires for commands.
"""

from __future__ import annotations

import struct


class Regex:
    """BSON regular expression (type 0x0B) — used in query filters."""

    def __init__(self, pattern: str, options: str = ""):
        self.pattern = pattern
        self.options = options

    def __repr__(self) -> str:
        return f"Regex({self.pattern!r}, {self.options!r})"


class Int64(int):
    """Force BSON int64 (0x12) even for small values — required where
    the server type-checks 'long' (e.g. getMore cursor ids)."""


def _cstring(s: str) -> bytes:
    b = s.encode("utf-8")
    if b"\x00" in b:
        raise ValueError("BSON cstring cannot contain NUL")
    return b + b"\x00"


def _encode_value(name: str, v) -> bytes:
    key = _cstring(name)
    if isinstance(v, bool):          # before int: bool is an int subclass
        return b"\x08" + key + (b"\x01" if v else b"\x00")
    if isinstance(v, Int64):
        return b"\x12" + key + struct.pack("<q", v)
    if isinstance(v, int):
        if -(1 << 31) <= v < 1 << 31:
            return b"\x10" + key + struct.pack("<i", v)
        return b"\x12" + key + struct.pack("<q", v)
    if isinstance(v, float):
        return b"\x01" + key + struct.pack("<d", v)
    if isinstance(v, str):
        raw = v.encode("utf-8")
        return b"\x02" + key + struct.pack("<i", len(raw) + 1) + raw + b"\x00"
    if isinstance(v, (bytes, bytearray, memoryview)):
        raw = bytes(v)
        return b"\x05" + key + struct.pack("<i", len(raw)) + b"\x00" + raw
    if v is None:
        return b"\x0a" + key
    if isinstance(v, Regex):
        return b"\x0b" + key + _cstring(v.pattern) + _cstring(v.options)
    if isinstance(v, dict):
        return b"\x03" + key + encode_doc(v)
    if isinstance(v, (list, tuple)):
        return b"\x04" + key + encode_doc(
            {str(i): item for i, item in enumerate(v)})
    raise TypeError(f"cannot BSON-encode {type(v).__name__}")


def encode_doc(doc: dict) -> bytes:
    body = b"".join(_encode_value(k, v) for k, v in doc.items())
    return struct.pack("<i", len(body) + 5) + body + b"\x00"


def _read_cstring(buf: bytes, off: int) -> tuple[str, int]:
    end = buf.index(b"\x00", off)
    return buf[off:end].decode("utf-8"), end + 1


def _decode_value(t: int, buf: bytes, off: int):
    if t == 0x01:
        return struct.unpack_from("<d", buf, off)[0], off + 8
    if t == 0x02:
        (n,) = struct.unpack_from("<i", buf, off)
        s = buf[off + 4:off + 4 + n - 1].decode("utf-8", "replace")
        return s, off + 4 + n
    if t in (0x03, 0x04):
        doc, off2 = decode_doc(buf, off)
        if t == 0x04:
            return [doc[k] for k in doc], off2
        return doc, off2
    if t == 0x05:
        (n,) = struct.unpack_from("<i", buf, off)
        return bytes(buf[off + 5:off + 5 + n]), off + 5 + n
    if t == 0x07:                    # ObjectId
        return bytes(buf[off:off + 12]), off + 12
    if t == 0x08:
        return buf[off] != 0, off + 1
    if t in (0x09, 0x12):            # UTC datetime / int64
        return struct.unpack_from("<q", buf, off)[0], off + 8
    if t == 0x0a:
        return None, off
    if t == 0x0b:
        pat, off = _read_cstring(buf, off)
        opts, off = _read_cstring(buf, off)
        return Regex(pat, opts), off
    if t == 0x10:
        return struct.unpack_from("<i", buf, off)[0], off + 4
    if t == 0x11:                    # timestamp
        return struct.unpack_from("<Q", buf, off)[0], off + 8
    if t == 0x13:                    # decimal128 — raw
        return bytes(buf[off:off + 16]), off + 16
    raise ValueError(f"unsupported BSON type 0x{t:02x}")


def decode_doc(buf: bytes, off: int = 0) -> tuple[dict, int]:
    (total,) = struct.unpack_from("<i", buf, off)
    end = off + total
    off += 4
    out: dict = {}
    while off < end - 1:
        t = buf[off]
        name, off = _read_cstring(buf, off + 1)
        out[name], off = _decode_value(t, buf, off)
    return out, end
