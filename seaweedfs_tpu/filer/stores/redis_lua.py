"""redis_lua filer store: mutations as server-side Lua scripts.

Rebuild of /root/reference/weed/filer/redis_lua/ (UniversalRedisLuaStore
+ stored_procedure/*.lua): the data layout is exactly redis2's —
the entry blob at the full-path key, the directory's children in a
``<dir>\\x00`` sorted set — but each mutation runs as ONE atomic Lua
script on the server (go-redis Script.Run = EVALSHA with EVAL fallback
on NOSCRIPT), so the entry write and its directory-index update cannot
interleave with another client's, without MULTI/EXEC round trips.

The scripts here are this package's own formulations of the same
semantics (insert = SET [EX ttl] + ZADD NX; delete = DEL entry+listkey
+ ZREM; delete-children = DEL every child + its list key, then clear
the set). Reads (find/list/kv) are the parent RedisStore's plain
commands, like the reference. Entry blobs and the directory index are
byte-compatible with this repo's redis/redis2 stores.
"""

from __future__ import annotations

import hashlib

from ..filerstore import register_store
from .redis import RedisStore, RespError, _dir_set_key

INSERT_SCRIPT = """\
local path = KEYS[1]
local dirset = KEYS[2]
local blob = ARGV[1]
local ttl = tonumber(ARGV[2])
local name = ARGV[3]
if ttl > 0 then
  redis.call('SET', path, blob, 'EX', ttl)
else
  redis.call('SET', path, blob)
end
if name ~= '' then
  redis.call('ZADD', dirset, 'NX', 0, name)
end
return 0
"""

DELETE_SCRIPT = """\
local path = KEYS[1]
local pathset = KEYS[2]
local dirset = KEYS[3]
local name = ARGV[1]
redis.call('DEL', path, pathset)
if name ~= '' then
  redis.call('ZREM', dirset, name)
end
return 0
"""

DELETE_CHILDREN_SCRIPT = """\
local dir = KEYS[1]
local dirset = KEYS[2]
local names = redis.call('ZRANGE', dirset, 0, -1)
for _, name in ipairs(names) do
  redis.call('DEL', dir .. '/' .. name)
end
redis.call('DEL', dirset)
return #names
"""
# NB: child LIST keys (child .. '\\0') are deliberately left to the
# python-side recursion — each subdirectory level runs this script for
# its own set, which must still be readable when its turn comes.


class ScriptRunner:
    """go-redis Script.Run over the RESP client: EVALSHA by the sha1 of
    the script body, falling back to EVAL (which also loads it) when
    the server answers NOSCRIPT."""

    def __init__(self, client, script: str):
        self.client = client
        self.script = script
        self.sha = hashlib.sha1(script.encode()).hexdigest()

    def run(self, keys: list[bytes], args: list) -> object:
        try:
            return self.client.cmd("EVALSHA", self.sha, str(len(keys)),
                                   *keys, *args)
        except RespError as e:
            if not str(e).startswith("NOSCRIPT"):
                raise
            return self.client.cmd("EVAL", self.script, str(len(keys)),
                                   *keys, *args)


class RedisLuaStore(RedisStore):
    """RedisStore whose mutations are atomic server-side scripts
    (UniversalRedisLuaStore, universal_redis_store.go:49)."""

    name = "redis_lua"

    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        self._insert = ScriptRunner(self.client, INSERT_SCRIPT)
        self._delete = ScriptRunner(self.client, DELETE_SCRIPT)
        self._delete_children = ScriptRunner(self.client,
                                             DELETE_CHILDREN_SCRIPT)

    def insert_entry(self, entry) -> None:
        from ...pb import filer_pb2

        blob = filer_pb2.FullEntry(
            dir=entry.parent, entry=entry.to_pb()).SerializeToString()
        ttl = entry.attr.ttl_sec if entry.attr else 0
        self._insert.run(
            [entry.full_path.encode(), _dir_set_key(entry.parent)],
            [blob, str(max(0, ttl)), entry.name.encode()])

    update_entry = insert_entry

    def delete_entry(self, full_path: str) -> None:
        d, _, name = full_path.rpartition("/")
        self._delete.run(
            [full_path.encode(), _dir_set_key(full_path),
             _dir_set_key(d or "/")],
            [name.encode()])

    def delete_folder_children(self, full_path: str) -> None:
        """One atomic level at a time; recursion over subdirectories
        happens here (the whole-subtree contract every store in this
        package keeps), reading each level BEFORE its set is dropped."""
        stack = [full_path.rstrip("/") or "/"]
        while stack:
            d = stack.pop()
            children = [(d.rstrip("/") or "") + "/" + m.decode()
                        for m in self.client.cmd(
                            "ZRANGEBYLEX", _dir_set_key(d),
                            "-", "+") or []]
            if not children:
                continue  # leaf: no set, nothing for the script to do
            # KEYS[1] is the '/'-stripped dir ('' for root) so the
            # script's dir..'/'..name concatenation yields /name, not
            # //name, at the root
            self._delete_children.run(
                [d.rstrip("/").encode(), _dir_set_key(d)], [])
            stack.extend(children)


register_store("redis_lua", RedisLuaStore)
