"""Log-structured on-disk filer store.

Counterpart of /root/reference/weed/filer/leveldb{,2,3}/ — the reference's
default on-disk metadata backend. No LevelDB binding ships in this image,
so this is a pure-Python equivalent with the same shape: an append-only
record log + in-memory directory index, compacted when garbage
accumulates. Registered as `leveldb` (and `leveldb2`/`leveldb3`, which in
the reference only change key layout/sharding).
"""

from __future__ import annotations

import bisect
import os
import struct

from ...pb import filer_pb2
from ...utils import locks
from ..entry import Entry
from ..filerstore import register_store

_PUT, _DEL, _KV = 1, 2, 3


class LevelDbStore:
    name = "leveldb"

    def __init__(self, directory: str = "./filerldb", **_ignored):
        self.dir = directory
        os.makedirs(directory, exist_ok=True)
        self._lock = locks.wrlock("filer.store.mu", rank=500)
        self._path = os.path.join(directory, "filer.log")
        # dir -> sorted [names]; (dir, name) -> log offset of latest record
        self._dirs: dict[str, list[str]] = {}
        self._offsets: dict[str, int] = {}
        self._kv: dict[bytes, bytes] = {}
        self._garbage = 0
        self._log = open(self._path, "a+b")
        self._replay()

    # -- log format: [1B op][4B klen][4B vlen][key][value] -----------------

    def _append(self, op: int, key: bytes, value: bytes) -> int:
        self._log.seek(0, 2)
        off = self._log.tell()
        self._log.write(struct.pack("<BII", op, len(key), len(value)))
        self._log.write(key)
        self._log.write(value)
        self._log.flush()
        return off

    def _read_at(self, off: int) -> tuple[int, bytes, bytes]:
        hdr = os.pread(self._log.fileno(), 9, off)
        op, klen, vlen = struct.unpack("<BII", hdr)
        blob = os.pread(self._log.fileno(), klen + vlen, off + 9)
        return op, blob[:klen], blob[klen:]

    def _replay(self) -> None:
        self._log.seek(0, 2)
        size = self._log.tell()
        off = 0
        while off + 9 <= size:
            hdr = os.pread(self._log.fileno(), 9, off)
            op, klen, vlen = struct.unpack("<BII", hdr)
            # a crash mid-append can leave a torn tail: truncate it off,
            # the same repair the volume startup integrity check does
            if op not in (_PUT, _DEL, _KV) or off + 9 + klen + vlen > size:
                self._log.truncate(off)
                break
            blob = os.pread(self._log.fileno(), klen + vlen, off + 9)
            key, value = blob[:klen], blob[klen:]
            try:
                if op == _PUT:
                    self._index_put(key.decode(), off, replay=True)
                elif op == _DEL:
                    self._index_del(key.decode())
                elif op == _KV:
                    self._kv[key] = value
            except (UnicodeDecodeError, ValueError):
                self._log.truncate(off)
                break
            off += 9 + klen + vlen

    def _index_put(self, path: str, off: int, replay: bool = False) -> None:
        d, name = path.rsplit("/", 1)
        d = d or "/"
        names = self._dirs.setdefault(d, [])
        i = bisect.bisect_left(names, name)
        if i >= len(names) or names[i] != name:
            names.insert(i, name)
        else:
            self._garbage += 1
        self._offsets[path] = off

    def _index_del(self, path: str) -> None:
        d, name = path.rsplit("/", 1)
        d = d or "/"
        names = self._dirs.get(d)
        if names:
            i = bisect.bisect_left(names, name)
            if i < len(names) and names[i] == name:
                names.pop(i)
        self._offsets.pop(path, None)
        self._garbage += 1

    def _maybe_compact(self) -> None:
        if self._garbage < 4096 or \
                self._garbage < len(self._offsets):
            return
        tmp = self._path + ".tmp"
        with open(tmp, "wb") as out:
            new_offsets = {}
            for path, off in self._offsets.items():
                op, key, value = self._read_at(off)
                new_off = out.tell()
                out.write(struct.pack("<BII", _PUT, len(key), len(value)))
                out.write(key)
                out.write(value)
                new_offsets[path] = new_off
            for k, v in self._kv.items():
                out.write(struct.pack("<BII", _KV, len(k), len(v)))
                out.write(k)
                out.write(v)
        self._log.close()
        os.replace(tmp, self._path)
        self._log = open(self._path, "a+b")
        self._offsets = new_offsets
        self._garbage = 0

    # -- FilerStore SPI ----------------------------------------------------

    def insert_entry(self, entry: Entry) -> None:
        with self._lock:
            blob = filer_pb2.FullEntry(
                dir=entry.parent, entry=entry.to_pb()).SerializeToString()
            off = self._append(_PUT, entry.full_path.encode(), blob)
            self._index_put(entry.full_path, off)
            self._maybe_compact()

    update_entry = insert_entry

    def find_entry(self, full_path: str) -> Entry | None:
        with self._lock:
            off = self._offsets.get(full_path)
            if off is None:
                return None
            _op, _key, value = self._read_at(off)
            fe = filer_pb2.FullEntry.FromString(value)
            return Entry.from_pb(fe.dir, fe.entry)

    def delete_entry(self, full_path: str) -> None:
        with self._lock:
            if full_path in self._offsets:
                self._append(_DEL, full_path.encode(), b"")
                self._index_del(full_path)
                self._maybe_compact()

    def delete_folder_children(self, full_path: str) -> None:
        with self._lock:
            prefix = full_path.rstrip("/")
            doomed = [p for p in self._offsets
                      if p.startswith(prefix + "/")]
            for p in doomed:
                self._append(_DEL, p.encode(), b"")
                self._index_del(p)
            dirs = [d for d in self._dirs
                    if d == prefix or d.startswith(prefix + "/")]
            for d in dirs:
                if d != prefix:
                    self._dirs.pop(d, None)
            self._maybe_compact()

    def list_directory_entries(self, dir_path: str, start_file_name: str = "",
                               include_start: bool = False,
                               limit: int = 1024, prefix: str = ""):
        with self._lock:
            d = dir_path.rstrip("/") or "/"
            names = list(self._dirs.get(d, ()))
        count = 0
        for name in names:
            if prefix and not name.startswith(prefix):
                continue
            if start_file_name:
                if name < start_file_name:
                    continue
                if name == start_file_name and not include_start:
                    continue
            e = self.find_entry((d.rstrip("/") or "") + "/" + name)
            if e is None:
                continue
            yield e
            count += 1
            if count >= limit:
                return

    def kv_get(self, key: bytes) -> bytes | None:
        with self._lock:
            return self._kv.get(key)

    def kv_put(self, key: bytes, value: bytes) -> None:
        with self._lock:
            self._kv[key] = value
            self._append(_KV, key, value)

    def close(self) -> None:
        with self._lock:
            self._log.close()


class LevelDb2Store(LevelDbStore):
    name = "leveldb2"


class LevelDb3Store(LevelDbStore):
    name = "leveldb3"


register_store("leveldb", LevelDbStore)
register_store("leveldb2", LevelDb2Store)
register_store("leveldb3", LevelDb3Store)
