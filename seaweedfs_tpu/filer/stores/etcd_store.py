"""etcd filer store over the real etcd v3 gRPC API.

Rebuild of /root/reference/weed/filer/etcd/etcd_store.go (backed by
go.etcd.io/etcd/client/v3): no etcd3 python client in this image, but
grpcio is — so the store drives the actual ``etcdserverpb.KV`` service
(proto mirrored in pb/proto/etcd_kv.proto) through the repo's generic
stub plumbing. Layout matches the reference exactly:

  * key = directory + b"\\x00" + name (DIR_FILE_SEPARATOR,
    etcd_store.go:19, genKey)
  * InsertEntry/UpdateEntry -> Put (:78-98)
  * FindEntry -> Range on the exact key (:104)
  * DeleteEntry -> DeleteRange on the exact key
  * DeleteFolderChildren -> DeleteRange on the ``dir\\x00`` prefix
    (which in etcd key-space is precisely the directory's children —
    descendants' keys embed deeper directories so the whole subtree
    shares the ``dir`` prefix; we range on ``dir`` + separator-or-slash
    to honor the repo-wide subtree contract)
  * ListDirectoryEntries -> Range [dir\\x00start, dir\\x01) sorted
    ascending with limit
  * kv_* -> Put/Range on the raw key bytes (etcd_store_kv.go)
"""

from __future__ import annotations

from typing import Iterator

from ...pb import filer_pb2
from ..entry import Entry
from ..filerstore import register_store
from .wire_common import prefix_end, split_dir_name

SEP = b"\x00"


def _prefix_end(prefix: bytes) -> bytes:
    """etcd clientv3.GetPrefixRangeEnd (b"\\x00" = whole keyspace)."""
    return prefix_end(prefix, unbounded=b"\x00")


class EtcdStore:
    """FilerStore over etcdserverpb.KV (EtcdStore, etcd_store.go:26)."""

    name = "etcd"

    def __init__(self, *, servers: str = "localhost:2379", timeout: int = 10,
                 **_kwargs):
        import grpc

        from ...pb import rpc

        self._channel = grpc.insecure_channel(
            servers.split(",")[0],
            options=[("grpc.max_receive_message_length", 1 << 30)])
        self._svc = rpc.etcd_kv_service()
        self.kv = rpc.Stub(self._channel, self._svc)
        self._timeout = timeout
        from ...pb import etcd_kv_pb2 as E

        self._E = E
        # fail fast if nothing is listening (the Go client dials eagerly)
        self.kv.Range(E.RangeRequest(key=b"\x00", limit=1),
                      timeout=timeout)

    _split = staticmethod(split_dir_name)

    def _key(self, full_path: str) -> bytes:
        d, n = self._split(full_path)
        return d.encode() + SEP + n.encode()

    def insert_entry(self, entry: Entry) -> None:
        blob = entry.to_pb().SerializeToString()
        self.kv.Put(self._E.PutRequest(key=self._key(entry.full_path),
                                       value=blob), timeout=self._timeout)

    update_entry = insert_entry

    def find_entry(self, full_path: str) -> Entry | None:
        resp = self.kv.Range(self._E.RangeRequest(
            key=self._key(full_path), limit=1), timeout=self._timeout)
        if not resp.kvs:
            return None
        d, _ = self._split(full_path)
        pb = filer_pb2.Entry.FromString(resp.kvs[0].value)
        return Entry.from_pb(d, pb)

    def delete_entry(self, full_path: str) -> None:
        self.kv.DeleteRange(self._E.DeleteRangeRequest(
            key=self._key(full_path)), timeout=self._timeout)

    def delete_folder_children(self, full_path: str) -> None:
        base = (full_path.rstrip("/") or "/").encode()
        # direct children: "<base>\x00..."; descendants' keys start
        # "<base>/..." (their directory string extends base) — two
        # prefix deletes cover the subtree. Root is the special case:
        # EVERY key starts with "/", one prefix covers it all (the
        # two-prefix split would compute b"//", which matches nothing)
        prefixes = ((base,) if base == b"/"
                    else (base + SEP, base + b"/"))
        for prefix in prefixes:
            self.kv.DeleteRange(self._E.DeleteRangeRequest(
                key=prefix, range_end=_prefix_end(prefix)),
                timeout=self._timeout)

    def list_directory_entries(self, dir_path: str, start_file_name: str = "",
                               include_start: bool = False,
                               limit: int = 1024,
                               prefix: str = "") -> Iterator[Entry]:
        base = dir_path.rstrip("/") or "/"
        # the prefix narrows the RANGE itself, so the server-side limit
        # counts prefix-matching entries (a client-side filter after a
        # server-side limit silently truncates prefixed listings)
        start = max(start_file_name, prefix) if prefix else start_file_name
        lo = base.encode() + SEP + start.encode()
        if start_file_name and not include_start \
                and start == start_file_name:
            lo += b"\x00"  # skip the exact start key
        hi = _prefix_end(base.encode() + SEP
                         + prefix.encode() if prefix
                         else base.encode() + SEP)
        resp = self.kv.Range(self._E.RangeRequest(
            key=lo, range_end=hi, limit=limit,
            sort_order=self._E.RangeRequest.ASCEND,
            sort_target=self._E.RangeRequest.KEY), timeout=self._timeout)
        for kv in resp.kvs:
            name = kv.key.split(SEP, 1)[1].decode("utf-8", "replace")
            if prefix and not name.startswith(prefix):
                continue  # defensive; range already bounds the prefix
            pb = filer_pb2.Entry.FromString(kv.value)
            yield Entry.from_pb(base, pb)

    # -- kv (etcd_store_kv.go: the raw key bytes ARE the etcd key) ---------

    def kv_put(self, key: bytes, value: bytes) -> None:
        self.kv.Put(self._E.PutRequest(key=key, value=value),
                    timeout=self._timeout)

    def kv_get(self, key: bytes) -> bytes | None:
        resp = self.kv.Range(self._E.RangeRequest(key=key, limit=1),
                             timeout=self._timeout)
        return resp.kvs[0].value if resp.kvs else None

    def close(self) -> None:
        self._channel.close()


register_store("etcd", EtcdStore)
