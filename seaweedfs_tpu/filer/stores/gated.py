"""Client-library-gated filer stores.

The reference registers 21 metadata backends (SURVEY.md §2.5); the ones
whose client libraries aren't baked into this image register here as
gated placeholders that fail at construction with clear guidance, the
same pattern the notification queues use. Each maps to its reference
package under /root/reference/weed/filer/<name>/.
"""

from __future__ import annotations

from ..filerstore import register_store

_GATED = {
    "rocksdb": "python-rocksdb (cgo-gated in the reference too)",
    # redis/redis2 are REAL now: stores/redis.py speaks RESP itself;
    # redis3 likewise via stores/redis3.py (segmented bounded-key
    # directory listings)
    # redis_lua is REAL now: stores/redis_lua.py runs the three
    # mutations as server-side Lua via EVALSHA/EVAL over the RESP wire
    # postgres/postgres2 are REAL now: stores/pg_wire.py speaks the v3
    # wire protocol itself (extended query + SCRAM auth); mysql/mysql2
    # likewise via stores/mysql_wire.py (binary prepared statements)
    # cassandra is REAL now: stores/cql_wire.py speaks CQL protocol v4
    # mongodb is REAL now: stores/mongo_wire.py speaks OP_MSG + BSON
    # elastic/elastic7 are REAL now: stores/elastic_wire.py drives the
    # REST/JSON API with the stdlib http client
    # etcd is REAL now: stores/etcd_store.py drives the
    # etcdserverpb.KV gRPC API via the repo pb stack
    # tikv is REAL now: stores/tikv_store.py drives the RawKV
    # gRPC API with pdpb region routing via the repo pb stack
    # ydb is REAL now: stores/ydb_store.py drives the
    # Ydb.Table.V1.TableService gRPC API (sessions, Operation/Any
    # envelope, typed YQL parameters) via the repo pb stack
    # hbase is REAL now: stores/hbase_store.py drives the Thrift2
    # gateway (THBaseService) via stores/thrift_wire.py
    # arangodb is REAL now: stores/arango_wire.py drives
    # the REST + AQL cursor API
}


def _make(name: str, lib: str):
    class GatedStore:
        def __init__(self, **_kwargs):
            raise RuntimeError(
                f"filer store {name!r} needs the {lib} client library, "
                f"which is not available in this environment; use "
                f"`memory`, `sqlite`, or `leveldb`")

    GatedStore.name = name
    GatedStore.__name__ = f"Gated_{name}"
    return GatedStore


for _name, _lib in _GATED.items():
    register_store(_name, _make(_name, _lib))
