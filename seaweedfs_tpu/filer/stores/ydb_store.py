"""YDB filer store over the real Table-service gRPC API.

Rebuild of /root/reference/weed/filer/ydb/ydb_store.go (backed by
ydb-go-sdk/v3): no YDB client library in this image, so the store
drives YDB's public wire surface itself — ``Ydb.Table.V1.TableService``
(CreateSession / ExecuteDataQuery / ExecuteSchemeQuery) with the
Operation/Any response envelope, through the repo pb stack
(pb/proto/ydb_*.proto). The data model matches the reference exactly:

  * one `filemeta` table: (dir_hash Int64, name Utf8, directory Utf8,
    meta String, expire_at Optional<Uint32>), PK (dir_hash, name)
    (ydb_types.go:38 createTableOptions)
  * dir_hash = md5-prefix int64 of the directory
    (util.HashStringToLong, weed/util/bytes.go:77)
  * the six YQL statements are the reference's verbatim
    (ydb_queries.go): DECLARE'd parameters, UPSERT upserts,
    paged LIKE-prefixed listings with Truncated() continuation
  * kv_*: key -> (base64(key[:8]) dir, int64 head hash, base64 tail
    name) through the same upsert/find/delete statements
    (abstract_sql.GenDirAndName, ydb_store_kv.go:17)
  * sessions: created lazily, recreated on BAD_SESSION/SESSION_EXPIRED
    (the sdk's session pool collapsed to pool-size 1 — the filer's
    store SPI is lock-serialized per connection here like the other
    wire stores)
"""

from __future__ import annotations

import base64
import hashlib
import struct
import threading
from typing import Iterator

import grpc

from ...pb import filer_pb2, rpc
from ...pb import ydb_operation_pb2 as O
from ...pb import ydb_table_pb2 as T
from ...pb import ydb_value_pb2 as V
from ..entry import Entry
from ..filerstore import register_store
from .abstract_sql import like_escape
from .wire_common import split_dir_name

TABLE = "filemeta"

# ydb_queries.go — kept verbatim modulo the PRAGMA prefix value
_UPSERT = """
PRAGMA TablePathPrefix("{p}");
DECLARE $dir_hash AS int64;
DECLARE $directory AS Utf8;
DECLARE $name AS Utf8;
DECLARE $meta AS String;
DECLARE $expire_at AS Optional<uint32>;

UPSERT INTO filemeta
    (dir_hash, name, directory, meta, expire_at)
VALUES
    ($dir_hash, $name, $directory, $meta, $expire_at);"""

_DELETE = """
PRAGMA TablePathPrefix("{p}");
DECLARE $dir_hash AS int64;
DECLARE $name AS Utf8;

DELETE FROM filemeta
WHERE dir_hash = $dir_hash AND name = $name;"""

_FIND = """
PRAGMA TablePathPrefix("{p}");
DECLARE $dir_hash AS int64;
DECLARE $name AS Utf8;

SELECT meta
FROM filemeta
WHERE dir_hash = $dir_hash AND name = $name;"""

_DELETE_FOLDER_CHILDREN = """
PRAGMA TablePathPrefix("{p}");
DECLARE $dir_hash AS int64;
DECLARE $directory AS Utf8;

DELETE FROM filemeta
WHERE dir_hash = $dir_hash AND directory = $directory;"""

_LIST = """
PRAGMA TablePathPrefix("{p}");
DECLARE $dir_hash AS int64;
DECLARE $directory AS Utf8;
DECLARE $start_name AS Utf8;
DECLARE $prefix AS Utf8;
DECLARE $limit AS Uint64;

SELECT name, meta
FROM filemeta
WHERE dir_hash = $dir_hash AND directory = $directory and name > $start_name and name LIKE $prefix ESCAPE '!'
ORDER BY name ASC LIMIT $limit;"""
# ESCAPE '!' + like_escape'd prefix: a literal '_'/'%' in the prefix
# must not act as a YQL wildcard — unescaped, 'my_' also matched 'myX',
# and those rows were then dropped client-side WITHOUT advancing
# `emitted`, so real matches past the server page silently vanished
# from listings (the reference inherits this; abstract_sql here escapes)

_LIST_INCLUSIVE = _LIST.replace("name > $start_name", "name >= $start_name")

_CREATE_TABLE = """
PRAGMA TablePathPrefix("{p}");
CREATE TABLE filemeta (
    dir_hash Int64,
    directory Utf8,
    name Utf8,
    meta String,
    expire_at Uint32,
    PRIMARY KEY (dir_hash, name)
)
WITH (
    TTL = Interval("PT0S") ON expire_at AS SECONDS
);"""
# The WITH TTL clause is createTableOptions' TimeToLiveSettings
# (ydb_types.go:46: expire_at, unit seconds, value-since-epoch) in YQL
# form — a real server purges rows once expire_at passes. NB the
# reference writes entry.TtlSec (a DURATION) into this epoch-seconds
# column; the value layout is kept verbatim for data compatibility.


class YdbError(IOError):
    def __init__(self, status: int, issues: str):
        self.status = status
        super().__init__(f"ydb status {status}: {issues}")


def hash_string_to_long(s: str) -> int:
    """util.HashStringToLong (weed/util/bytes.go:77): the md5 prefix
    folded big-endian into a SIGNED int64."""
    b = hashlib.md5(s.encode()).digest()
    v = 0
    for i in range(8):
        v = (v << 8) + b[i]
    return struct.unpack(">q", struct.pack(">Q", v & 0xFFFFFFFFFFFFFFFF))[0]


def gen_dir_and_name(key: bytes) -> tuple[str, int, str]:
    """abstract_sql.GenDirAndName: kv keys ride the filemeta table."""
    key = key + b"\x00" * max(0, 8 - len(key))
    dir_hash = struct.unpack(">q", key[:8])[0]
    return (base64.b64encode(key[:8]).decode(), dir_hash,
            base64.b64encode(key[8:]).decode())


# -- typed parameter helpers (types.Int64Value etc., ydb_types.go) ---------

def _int64(v: int) -> V.TypedValue:
    return V.TypedValue(type=V.Type(type_id=V.Type.INT64),
                        value=V.Value(int64_value=v))


def _utf8(s: str) -> V.TypedValue:
    return V.TypedValue(type=V.Type(type_id=V.Type.UTF8),
                        value=V.Value(text_value=s))


def _string(b: bytes) -> V.TypedValue:
    return V.TypedValue(type=V.Type(type_id=V.Type.STRING),
                        value=V.Value(bytes_value=b))


def _uint64(v: int) -> V.TypedValue:
    return V.TypedValue(type=V.Type(type_id=V.Type.UINT64),
                        value=V.Value(uint64_value=v))


def _opt_uint32(v: int | None) -> V.TypedValue:
    t = V.Type(optional_type=V.OptionalType(
        item=V.Type(type_id=V.Type.UINT32)))
    if v is None:
        return V.TypedValue(type=t, value=V.Value(null_flag_value=0))
    return V.TypedValue(type=t, value=V.Value(uint32_value=v))


_RO_TX = T.TransactionControl(
    begin_tx=T.TransactionSettings(online_read_only=T.OnlineModeSettings()),
    commit_tx=True)
_RW_TX = T.TransactionControl(
    begin_tx=T.TransactionSettings(
        serializable_read_write=T.SerializableModeSettings()),
    commit_tx=True)

# session loss -> recreate the session, then retry; transient server
# states -> plain retry (the ydb-go-sdk retryer the reference rides via
# DB.Table().Do does both transparently)
_SESSION_GONE = {O.BAD_SESSION, O.SESSION_EXPIRED}
_TRANSIENT = {O.ABORTED, O.OVERLOADED, O.UNAVAILABLE}


class YdbStore:
    """FilerStore over Ydb.Table.V1.TableService (YdbStore,
    ydb_store.go:40)."""

    name = "ydb"

    def __init__(self, *, dsn: str = "grpc://localhost:2136/local",
                 prefix: str = "", timeout: int = 10, **_kwargs):
        # dsn: grpc://host:port/database (command/scaffold.go [ydb] dsn);
        # grpcs:// dials TLS like the reference SDK — silently downgrading
        # a secure DSN to plaintext would leak metadata on the wire
        scheme, sep, rest = dsn.partition("://")
        if not sep:
            scheme, rest = "grpc", dsn
        endpoint, _, database = rest.partition("/")
        self._database = "/" + database if database else "/local"
        self._prefix = (self._database + "/" + prefix.strip("/")
                        if prefix else self._database)
        self._timeout = timeout
        if scheme == "grpc":
            self._channel = grpc.insecure_channel(endpoint)
        elif scheme == "grpcs":
            self._channel = grpc.secure_channel(
                endpoint, grpc.ssl_channel_credentials())
        else:
            raise ValueError(
                f"unsupported ydb dsn scheme {scheme!r} "
                f"(use grpc:// or grpcs://)")
        self.table = rpc.Stub(self._channel, rpc.ydb_table_service())
        self._mu = threading.Lock()      # guards _session
        self._op_mu = threading.Lock()   # serializes query round trips
        self._session = ""
        self._ensure_session()
        self._create_table()

    # -- session + operation plumbing --------------------------------------

    def _ensure_session(self) -> str:
        with self._mu:
            if self._session:
                return self._session
            resp = self.table.CreateSession(T.CreateSessionRequest(),
                                            timeout=self._timeout)
            result = self._unwrap(resp.operation, T.CreateSessionResult)
            self._session = result.session_id
            return self._session

    @staticmethod
    def _unwrap(operation: O.Operation, result_cls):
        if operation.status != O.SUCCESS:
            raise YdbError(operation.status,
                           "; ".join(i.message for i in operation.issues))
        out = result_cls()
        if operation.result.value or operation.result.type_url:
            if not operation.result.Unpack(out):
                raise YdbError(operation.status,
                               f"unexpected result type "
                               f"{operation.result.type_url}")
        return out

    def _create_table(self) -> None:
        try:
            self._scheme(_CREATE_TABLE.format(p=self._prefix))
        except YdbError as e:
            # already-exists surfaces as SCHEME_ERROR/GENERIC_ERROR on
            # a live server; the reference logs and continues too
            if e.status not in (O.SCHEME_ERROR, O.GENERIC_ERROR):
                raise

    def _scheme(self, yql: str) -> None:
        with self._op_mu:
            sid = self._ensure_session()
            resp = self.table.ExecuteSchemeQuery(
                T.ExecuteSchemeQueryRequest(session_id=sid, yql_text=yql),
                timeout=self._timeout)
            self._unwrap(resp.operation, T.ExecuteSchemeQueryResponse)

    def _execute(self, yql: str, params: dict, tx=_RW_TX
                 ) -> T.ExecuteQueryResult:
        # one in-flight query per session: a real YDB answers
        # SESSION_BUSY to concurrent queries on one session, so the
        # whole round trip is serialized like the sibling wire stores
        with self._op_mu:
            last: YdbError | None = None
            for attempt in range(3):
                sid = self._ensure_session()
                resp = self.table.ExecuteDataQuery(
                    T.ExecuteDataQueryRequest(
                        session_id=sid, tx_control=tx,
                        query=T.Query(yql_text=yql), parameters=params,
                        query_cache_policy=T.QueryCachePolicy(
                            keep_in_cache=True)),
                    timeout=self._timeout)
                try:
                    return self._unwrap(resp.operation,
                                        T.ExecuteQueryResult)
                except YdbError as e:
                    last = e
                    if e.status in _SESSION_GONE:
                        with self._mu:
                            self._session = ""  # stale: recreate
                        continue
                    if e.status in _TRANSIENT:
                        continue  # e.g. tx-lock ABORTED on a write race
                    raise
            raise last

    # -- FilerStore SPI ----------------------------------------------------

    _split = staticmethod(split_dir_name)

    def insert_entry(self, entry: Entry) -> None:
        d, n = self._split(entry.full_path)
        ttl = entry.attr.ttl_sec if entry.attr else 0
        self._execute(_UPSERT.format(p=self._prefix), {
            "$dir_hash": _int64(hash_string_to_long(d)),
            "$directory": _utf8(d),
            "$name": _utf8(n),
            "$meta": _string(entry.to_pb().SerializeToString()),
            "$expire_at": _opt_uint32(ttl if ttl > 0 else None),
        })

    update_entry = insert_entry

    def _find_meta(self, dir_hash: int, name: str) -> bytes | None:
        res = self._execute(_FIND.format(p=self._prefix), {
            "$dir_hash": _int64(dir_hash),
            "$name": _utf8(name),
        }, tx=_RO_TX)
        for rs in res.result_sets:
            for row in rs.rows:
                return row.items[0].bytes_value
        return None

    def find_entry(self, full_path: str) -> Entry | None:
        d, n = self._split(full_path)
        blob = self._find_meta(hash_string_to_long(d), n)
        if blob is None:
            return None
        return Entry.from_pb(d, filer_pb2.Entry.FromString(blob))

    def delete_entry(self, full_path: str) -> None:
        d, n = self._split(full_path)
        self._execute(_DELETE.format(p=self._prefix), {
            "$dir_hash": _int64(hash_string_to_long(d)),
            "$name": _utf8(n),
        })

    def _all_subdir_names(self, d: str) -> list[str]:
        """Every subdirectory child of `d`, paged to exhaustion — a
        fixed listing cap would strand subtrees past it as orphans once
        the parent rows are deleted."""
        out: list[str] = []
        start, inclusive = "", True
        while True:
            page = list(self.list_directory_entries(
                d, start, include_start=inclusive, limit=4096))
            out.extend(e.name for e in page if e.is_directory)
            if len(page) < 4096:
                return out
            start, inclusive = page[-1].name, False

    def delete_folder_children(self, full_path: str) -> None:
        """One dir_hash bucket per call in the reference; this repo's
        store contract is whole-subtree, so recurse through listings
        (same shape as the tikv store)."""
        stack = [full_path.rstrip("/") or "/"]
        while stack:
            d = stack.pop()
            subdirs = self._all_subdir_names(d)
            self._execute(
                _DELETE_FOLDER_CHILDREN.format(p=self._prefix), {
                    "$dir_hash": _int64(hash_string_to_long(d)),
                    "$directory": _utf8(d),
                })
            stack.extend((d.rstrip("/") or "") + "/" + s for s in subdirs)

    def list_directory_entries(self, dir_path: str, start_file_name: str = "",
                               include_start: bool = False,
                               limit: int = 1024,
                               prefix: str = "") -> Iterator[Entry]:
        base = dir_path.rstrip("/") or "/"
        dir_hash = hash_string_to_long(base)
        yql = (_LIST_INCLUSIVE if include_start else _LIST)
        start = start_file_name
        emitted = 0
        while emitted < limit:
            res = self._execute(yql.format(p=self._prefix), {
                "$dir_hash": _int64(dir_hash),
                "$directory": _utf8(base),
                "$start_name": _utf8(start),
                "$prefix": _utf8(like_escape(prefix) + "%"),
                "$limit": _uint64(limit - emitted),
            }, tx=_RO_TX)
            rows = [row for rs in res.result_sets for row in rs.rows]
            truncated = any(rs.truncated for rs in res.result_sets)
            for row in rows:
                name = row.items[0].text_value
                blob = row.items[1].bytes_value
                start = name
                if prefix and not name.startswith(prefix):
                    # YQL LIKE treats '_'/'%' as wildcards; the siblings
                    # all re-verify the literal prefix client-side
                    continue
                yield Entry.from_pb(base,
                                    filer_pb2.Entry.FromString(blob))
                emitted += 1
                if emitted >= limit:
                    return
            if not truncated or not rows:
                return
            yql = _LIST  # continuation pages are strictly-greater

    # -- kv (ydb_store_kv.go via abstract_sql.GenDirAndName) ---------------

    def kv_put(self, key: bytes, value: bytes) -> None:
        d, dir_hash, name = gen_dir_and_name(key)
        self._execute(_UPSERT.format(p=self._prefix), {
            "$dir_hash": _int64(dir_hash),
            "$directory": _utf8(d),
            "$name": _utf8(name),
            "$meta": _string(value),
            "$expire_at": _opt_uint32(None),
        })

    def kv_get(self, key: bytes) -> bytes | None:
        _, dir_hash, name = gen_dir_and_name(key)
        return self._find_meta(dir_hash, name)

    def close(self) -> None:
        try:
            if self._session:
                self.table.DeleteSession(
                    T.DeleteSessionRequest(session_id=self._session),
                    timeout=2)
        except grpc.RpcError:
            pass
        self._channel.close()


register_store("ydb", YdbStore)
