"""TiKV filer store over the real RawKV gRPC API with PD routing.

Rebuild of /root/reference/weed/filer/tikv/tikv_store.go (backed by
tikv/client-go's txnkv): no TiKV client library in this image, so the
store drives TiKV's public wire surface itself through the repo pb
stack — ``pdpb.PD`` for key->region routing (GetRegion/GetStore, the
same discovery loop client-go's RegionCache runs) and ``tikvpb.Tikv``
RawKV for data. Layout matches the reference exactly:

  * key = sha1(dir) + name (tikv_store.go:358 generateKey /
    hashToBytes), value = the entry protobuf
  * InsertEntry/UpdateEntry -> RawPut (:77-95)
  * FindEntry -> RawGet (:101)
  * DeleteEntry -> RawDelete (:135)
  * DeleteFolderChildren -> RawDeleteRange over the sha1(dir) prefix
    (:157 iterates then DeleteRange; RawDeleteRange does it
    server-side). NOTE the sha1 keyspace is FLAT — children of a
    directory live under sha1(dir) but grandchildren live under
    sha1(child-dir), so the subtree walk recurses through listings,
    exactly like the reference's filer-level recursive delete.
  * ListDirectoryEntries -> RawScan from sha1(dir)+start bounded by
    the prefix (:203), following region boundaries
  * kv_* -> RawPut/RawGet on the raw key bytes (tikv_store_kv.go:13)

Deviation, documented: the reference uses the *transactional* KV API
(txnkv); single-key filer ops don't need 2PC, and RawKV is TiKV's
first-class API for exactly this shape, so this build uses RawKV and
keeps the reference's on-disk key layout.
"""

from __future__ import annotations

import hashlib
import threading
from typing import Iterator

import grpc

from ...pb import filer_pb2, rpc
from ...pb import tikv_kvrpc_pb2 as K
from ...pb import tikv_pd_pb2 as P
from ..entry import Entry
from ..filerstore import register_store
from .wire_common import prefix_end, split_dir_name

SHA1_SIZE = 20


class TikvError(IOError):
    pass


def _hash(dir_path: str) -> bytes:
    return hashlib.sha1(dir_path.encode()).digest()


def _prefix_end(prefix: bytes) -> bytes:
    return prefix_end(prefix, unbounded=b"")


class TikvStore:
    """FilerStore over pdpb.PD + tikvpb.Tikv RawKV (TikvStore,
    tikv_store.go:30)."""

    name = "tikv"

    def __init__(self, *, pdaddrs: str = "localhost:2379", timeout: int = 10,
                 **_kwargs):
        self._timeout = timeout
        self._pd_channel = grpc.insecure_channel(pdaddrs.split(",")[0])
        self.pd = rpc.Stub(self._pd_channel, rpc.tikv_pd_service())
        self._stores_mu = threading.Lock()
        self._store_stubs: dict[str, tuple[grpc.Channel, rpc.Stub]] = {}
        self._store_addrs: dict[int, str] = {}
        # fail fast if no PD answers (client-go dials PD eagerly too)
        members = self.pd.GetMembers(P.GetMembersRequest(),
                                     timeout=timeout)
        self._cluster_id = members.header.cluster_id

    # -- routing (client-go RegionCache, slimmed) --------------------------

    def _header(self) -> P.RequestHeader:
        return P.RequestHeader(cluster_id=self._cluster_id)

    def _region_for(self, key: bytes):
        r = self.pd.GetRegion(P.GetRegionRequest(
            header=self._header(), region_key=key), timeout=self._timeout)
        if r.header.error.message:
            raise TikvError(f"pd GetRegion: {r.header.error.message}")
        if not r.region.id or not r.region.peers:
            raise TikvError(
                f"pd GetRegion: no region serves key {key[:24].hex()}")
        return r.region, r.leader

    def _stub_for_store(self, store_id: int) -> rpc.Stub:
        # store_id -> address is stable (a store keeps its id for life),
        # so cache it: without this every data op pays a PD GetStore
        # round trip on top of GetRegion
        with self._stores_mu:
            addr = self._store_addrs.get(store_id)
        if addr is None:
            s = self.pd.GetStore(P.GetStoreRequest(
                header=self._header(), store_id=store_id),
                timeout=self._timeout)
            if s.header.error.message:
                raise TikvError(f"pd GetStore: {s.header.error.message}")
            addr = s.store.address
        with self._stores_mu:
            self._store_addrs[store_id] = addr
            cached = self._store_stubs.get(addr)
            if cached is None:
                ch = grpc.insecure_channel(addr)
                cached = (ch, rpc.Stub(ch, rpc.tikv_service()))
                self._store_stubs[addr] = cached
            return cached[1]

    def _ctx_and_stub(self, key: bytes):
        region, leader = self._region_for(key)
        peer = leader if leader.store_id else region.peers[0]
        ctx = K.Context(region_id=region.id,
                        region_epoch=region.region_epoch, peer=peer)
        return ctx, self._stub_for_store(peer.store_id), region

    @staticmethod
    def _check(resp) -> None:
        if resp.region_error.message:
            raise TikvError(f"region error: {resp.region_error.message}")
        if getattr(resp, "error", ""):
            raise TikvError(resp.error)

    # -- raw ops (region-aware) --------------------------------------------

    def _raw_put(self, key: bytes, value: bytes) -> None:
        ctx, stub, _ = self._ctx_and_stub(key)
        resp = stub.RawPut(K.RawPutRequest(context=ctx, key=key,
                                           value=value),
                           timeout=self._timeout)
        self._check(resp)

    def _raw_get(self, key: bytes) -> bytes | None:
        ctx, stub, _ = self._ctx_and_stub(key)
        resp = stub.RawGet(K.RawGetRequest(context=ctx, key=key),
                           timeout=self._timeout)
        self._check(resp)
        if resp.not_found:
            return None
        return resp.value

    def _raw_delete(self, key: bytes) -> None:
        ctx, stub, _ = self._ctx_and_stub(key)
        resp = stub.RawDelete(K.RawDeleteRequest(context=ctx, key=key),
                              timeout=self._timeout)
        self._check(resp)

    def _raw_delete_range(self, start: bytes, end: bytes) -> None:
        """DeleteRange [start, end), region by region (client-go splits
        ranges on region boundaries the same way)."""
        cur = start
        while True:
            ctx, stub, region = self._ctx_and_stub(cur)
            stop = end
            if region.end_key and (not end or region.end_key < end):
                stop = region.end_key
            resp = stub.RawDeleteRange(K.RawDeleteRangeRequest(
                context=ctx, start_key=cur, end_key=stop),
                timeout=self._timeout)
            self._check(resp)
            if stop == end or not region.end_key:
                return
            cur = region.end_key

    def _raw_scan(self, start: bytes, end: bytes, limit: int
                  ) -> Iterator[K.KvPair]:
        """Ascending scan of [start, end), following region boundaries
        and paging inside each region."""
        cur = start
        remaining = limit
        while remaining > 0:
            ctx, stub, region = self._ctx_and_stub(cur)
            stop = end
            if region.end_key and (not end or region.end_key < end):
                stop = region.end_key
            page = min(remaining, 1024)
            resp = stub.RawScan(K.RawScanRequest(
                context=ctx, start_key=cur, end_key=stop, limit=page),
                timeout=self._timeout)
            self._check(resp)
            for kv in resp.kvs:
                yield kv
                remaining -= 1
                if remaining <= 0:
                    return
            if len(resp.kvs) == page and resp.kvs:
                cur = resp.kvs[-1].key + b"\x00"
                continue
            if stop == end or not region.end_key:
                return
            cur = region.end_key

    # -- FilerStore SPI ----------------------------------------------------

    _split = staticmethod(split_dir_name)

    def _key(self, full_path: str) -> bytes:
        d, n = self._split(full_path)
        return _hash(d) + n.encode()

    def insert_entry(self, entry: Entry) -> None:
        self._raw_put(self._key(entry.full_path),
                      entry.to_pb().SerializeToString())

    update_entry = insert_entry

    def find_entry(self, full_path: str) -> Entry | None:
        blob = self._raw_get(self._key(full_path))
        if blob is None:
            return None
        d, _ = self._split(full_path)
        return Entry.from_pb(d, filer_pb2.Entry.FromString(blob))

    def delete_entry(self, full_path: str) -> None:
        self._raw_delete(self._key(full_path))

    def delete_folder_children(self, full_path: str) -> None:
        """The sha1 keyspace is flat per-directory: recurse through
        listings so grandchildren under sha1(child) go too (the
        reference store only clears one directory per call and relies
        on the filer's recursive walk; this repo's store contract is
        whole-subtree)."""
        stack = [full_path.rstrip("/") or "/"]
        while stack:
            d = stack.pop()
            sub = [e for e in self.list_directory_entries(d,
                                                          limit=1_000_000)]
            prefix = _hash(d)
            self._raw_delete_range(prefix, _prefix_end(prefix))
            stack.extend((d.rstrip("/") or "") + "/" + e.name
                         for e in sub if e.is_directory)

    def list_directory_entries(self, dir_path: str, start_file_name: str = "",
                               include_start: bool = False,
                               limit: int = 1024,
                               prefix: str = "") -> Iterator[Entry]:
        base = dir_path.rstrip("/") or "/"
        h = _hash(base)
        start = max(start_file_name, prefix) if prefix else start_file_name
        lo = h + start.encode()
        if start_file_name and not include_start \
                and start == start_file_name:
            lo += b"\x00"
        hi = _prefix_end(h + prefix.encode()) if prefix else _prefix_end(h)
        for kv in self._raw_scan(lo, hi, limit):
            pb = filer_pb2.Entry.FromString(kv.value)
            yield Entry.from_pb(base, pb)

    # -- kv (tikv_store_kv.go: the raw key bytes ARE the tikv key) ---------

    def kv_put(self, key: bytes, value: bytes) -> None:
        self._raw_put(key, value)

    def kv_get(self, key: bytes) -> bytes | None:
        return self._raw_get(key)

    def close(self) -> None:
        self._pd_channel.close()
        with self._stores_mu:
            for ch, _ in self._store_stubs.values():
                ch.close()
            self._store_stubs.clear()


register_store("tikv", TikvStore)
