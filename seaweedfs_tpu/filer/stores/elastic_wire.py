"""Elasticsearch filer store over its plain REST/JSON API.

Rebuild of /root/reference/weed/filer/elastic/v7/elastic_store.go
(build-tag-gated in the reference and backed by olivere/elastic): no
client library here either — Elasticsearch's API is HTTP+JSON, so the
store drives it with the stdlib http.client, matching the reference's
layout exactly:

  * one index per top-level directory, named ``.seaweedfs_<seg>``
    (indexPrefix, elastic_store.go:22; getIndex), ``.seaweedfs_``
    bare for root-level entries
  * document id = md5 hex of the full path; ``ParentId`` = md5 hex of
    the directory (InsertEntry :107-118)
  * listings are term queries on ParentId with search_after
    pagination (listDirectoryEntries :200+). Deviation: the reference
    sorts on _id DESCENDING (Sort("_id", false), elastic_store.go:277)
    — i.e. md5-of-path order — which breaks lexicographic listing and
    start/prefix pagination; this store indexes Name and sorts on it,
    keeping the repo-wide ordering contract the filer requires
  * deleting a top-level directory drops its whole index
    (DeleteEntry :160-166)
  * kv entries live in ``.seaweedfs_kv_entries`` (indexKV :23)

Entry metadata is stored as base64 of the filer pb (the reference
marshals its Entry struct to JSON; the pb blob is this repo's
canonical serialized form, and binary fields must be base64 in JSON
either way).
"""

from __future__ import annotations

import base64
import hashlib
import http.client
import json
import threading
from typing import Iterator

from ...pb import filer_pb2
from ..entry import Entry
from ..filerstore import register_store
from .wire_common import split_dir_name

INDEX_PREFIX = ".seaweedfs_"
INDEX_KV = ".seaweedfs_kv_entries"


class ElasticError(Exception):
    def __init__(self, status: int, message: str):
        self.status = status
        super().__init__(f"HTTP {status}: {message}")


class ElasticClient:
    """Tiny pooled REST client (one http.client conn per thread)."""

    def __init__(self, *, host="localhost", port=9200, username="",
                 password="", timeout=30):
        self.host, self.port, self.timeout = host, int(port), timeout
        self._auth = None
        if username:
            self._auth = "Basic " + base64.b64encode(
                f"{username}:{password}".encode()).decode()
        self._local = threading.local()
        # every conn ever opened, so close() can reach the ones parked
        # in OTHER threads' locals (a thread-local-only close leaks fds)
        self._all_conns: list[http.client.HTTPConnection] = []
        self._conns_lock = threading.Lock()

    def _conn(self) -> http.client.HTTPConnection:
        c = getattr(self._local, "conn", None)
        if c is None:
            c = http.client.HTTPConnection(self.host, self.port,
                                           timeout=self.timeout)
            self._local.conn = c
            with self._conns_lock:
                self._all_conns.append(c)
        return c

    def request(self, method: str, path: str, body: dict | None = None,
                ok_statuses: tuple = (200, 201)) -> dict:
        payload = json.dumps(body).encode() if body is not None else None
        headers = {"Content-Type": "application/json"}
        if self._auth:
            headers["Authorization"] = self._auth
        for attempt in (0, 1):
            c = self._conn()
            try:
                c.request(method, path, body=payload, headers=headers)
                resp = c.getresponse()
                raw = resp.read()
                break
            except (http.client.HTTPException, OSError):
                # stale pooled connection: rebuild once, then surface
                try:
                    c.close()
                except OSError:
                    pass
                self._local.conn = None
                with self._conns_lock:
                    try:
                        self._all_conns.remove(c)
                    except ValueError:
                        pass
                if attempt:
                    raise
        doc = json.loads(raw) if raw else {}
        if resp.status not in ok_statuses:
            raise ElasticError(resp.status,
                               str(doc.get("error", raw[:200])))
        return doc

    def close(self) -> None:
        with self._conns_lock:
            conns, self._all_conns = self._all_conns, []
        for c in conns:
            try:
                c.close()
            except OSError:
                pass
        self._local.conn = None


def _md5(s: str) -> str:
    return hashlib.md5(s.encode()).hexdigest()


def _seg_index(seg: str) -> str:
    """ES index names must be lowercase; the reference just lower()s
    (getIndex, elastic_store.go:301) so /Data and /data COLLIDE in one
    index — and an index drop for one destroys the other. Disambiguate
    case variants with a short md5 suffix instead."""
    low = seg.lower()
    if seg != low:
        return INDEX_PREFIX + low + "-" + _md5(seg)[:6]
    return INDEX_PREFIX + low


def _index_of(full_path: str, is_directory: bool = False) -> str:
    """getIndex (elastic_store.go:298-310): '/a/b' -> .seaweedfs_a;
    a top-level FILE '/a' lives in the bare '.seaweedfs_' index, while
    DIRECTORY '/a' (for listing its children) maps to .seaweedfs_a."""
    parts = full_path.split("/")
    if is_directory and len(parts) >= 2:
        return _seg_index(parts[1])
    if len(parts) > 2:
        return _seg_index(parts[1])
    return INDEX_PREFIX


class ElasticStore:
    """FilerStore over the REST client (ElasticStore,
    elastic_store.go:48)."""

    name = "elastic7"

    def __init__(self, *, host="localhost", port=9200, username="",
                 password="", max_page_size=10000, **kwargs):
        self.client = ElasticClient(host=host, port=port,
                                    username=username, password=password,
                                    **kwargs)
        self.max_page_size = max_page_size
        self._known_indices: set[str] = set()
        # kv index exists up front (initialize, elastic_store.go:79-86)
        self.client.request("PUT", "/" + INDEX_KV, {},
                            ok_statuses=(200, 400))  # 400 = already exists

    _ENTRY_MAPPINGS = {
        "mappings": {"properties": {
            # keyword, not text: real ES dynamic-maps strings as text,
            # on which sort and exact term/prefix queries are rejected
            # ("Fielddata is disabled on text fields")
            "ParentId": {"type": "keyword"},
            "Name": {"type": "keyword"},
            "FullPath": {"type": "keyword"},
            "Meta": {"type": "keyword", "index": False},
        }}}

    def _ensure_index(self, index: str) -> None:
        if index in self._known_indices:
            return
        self.client.request("PUT", "/" + index, self._ENTRY_MAPPINGS,
                            ok_statuses=(200, 400))
        self._known_indices.add(index)

    # -- entries -----------------------------------------------------------

    def _doc_path(self, full_path: str) -> str:
        return f"/{_index_of(full_path)}/_doc/{_md5(full_path)}"

    def insert_entry(self, entry: Entry) -> None:
        d, n = self._split(entry.full_path)
        blob = entry.to_pb().SerializeToString()
        self._ensure_index(_index_of(entry.full_path))
        self.client.request("PUT", self._doc_path(entry.full_path), {
            "ParentId": _md5(d),
            "FullPath": entry.full_path,
            "Name": n,
            "Meta": base64.b64encode(blob).decode()})

    update_entry = insert_entry

    _split = staticmethod(split_dir_name)

    def _decode(self, src: dict, directory: str) -> Entry | None:
        meta = src.get("Meta")
        if not meta:
            return None
        pb = filer_pb2.Entry.FromString(base64.b64decode(meta))
        return Entry.from_pb(directory, pb)

    def find_entry(self, full_path: str) -> Entry | None:
        try:
            doc = self.client.request("GET", self._doc_path(full_path),
                                      ok_statuses=(200,))
        except ElasticError as e:
            if e.status == 404:
                return None
            raise
        if not doc.get("found"):
            return None
        d, _ = self._split(full_path)
        return self._decode(doc.get("_source", {}), d)

    def delete_entry(self, full_path: str) -> None:
        # top-level DIRECTORY: drop its whole index (DeleteEntry
        # :160-166 — which passes isDirectory=false to getIndex and
        # would nuke the shared bare index, and drops it for top-level
        # FILES too; both corrected here — a file named /Data must not
        # wipe the /Data directory tree)
        if full_path.count("/") == 1 and full_path != "/":
            e = self.find_entry(full_path)
            # a MISSING entry must not drop the index: deletes are
            # idempotent everywhere else, and a stray second delete of
            # a file racing a same-named directory's creation would
            # otherwise wipe that directory's whole subtree
            if e is not None and e.is_directory:
                index = _index_of(full_path, is_directory=True)
                self.client.request("DELETE", "/" + index,
                                    ok_statuses=(200, 404))
                self._known_indices.discard(index)
        try:
            self.client.request("DELETE", self._doc_path(full_path),
                                ok_statuses=(200, 404))
        except ElasticError as e:
            if e.status != 404:
                raise

    def delete_folder_children(self, full_path: str) -> None:
        base = full_path.rstrip("/") or "/"
        if base.count("/") == 1 and base != "/":
            # every descendant of /a lives in .seaweedfs_a (getIndex):
            # dropping the index deletes the whole subtree O(1); the
            # /a entry itself (bare index) is the caller's to keep
            index = _index_of(base, is_directory=True)
            self.client.request("DELETE", "/" + index,
                                ok_statuses=(200, 404))
            self._known_indices.discard(index)
            return
        # deeper dirs: list + delete (DeleteFolderChildren :193-201),
        # recursing for the subtree contract
        for entry in list(self.list_directory_entries(base,
                                                      limit=1 << 30)):
            if entry.is_directory:
                self.delete_folder_children(entry.full_path)
            self.delete_entry(entry.full_path)

    def list_directory_entries(self, dir_path: str, start_file_name: str = "",
                               include_start: bool = False,
                               limit: int = 1024,
                               prefix: str = "") -> Iterator[Entry]:
        base = dir_path.rstrip("/") or "/"
        index = _index_of(base, is_directory=True)
        parent = _md5(base)
        # one refresh per listing instead of refresh=true on every
        # write (the per-write form serializes real-ES ingest behind
        # segment creation; GET-by-id is realtime and needs neither)
        try:
            self.client.request("POST", f"/{index}/_refresh", {},
                                ok_statuses=(200, 404))
        except ElasticError:
            pass
        must: list = [{"term": {"ParentId": parent}}]
        if start_file_name:
            op = "gte" if include_start else "gt"
            must.append({"range": {"Name": {op: start_file_name}}})
        if prefix:
            must.append({"prefix": {"Name": prefix}})
        search_after = None
        got = 0
        while got < limit:
            body: dict = {
                "query": {"bool": {"must": must}},
                "sort": [{"Name": "asc"}],
                "size": min(self.max_page_size, limit - got),
            }
            if search_after:
                body["search_after"] = search_after
            try:
                res = self.client.request(
                    "POST", f"/{index}/_search", body, ok_statuses=(200,))
            except ElasticError as e:
                if e.status == 404:
                    return
                raise
            hits = res.get("hits", {}).get("hits", [])
            if not hits:
                return
            for h in hits:
                search_after = h.get("sort") or [
                    h.get("_source", {}).get("Name", "")]
                entry = self._decode(h.get("_source", {}), base)
                if entry is None:
                    continue
                yield entry
                got += 1
                if got >= limit:
                    return
            if len(hits) < body["size"]:
                return

    # -- kv (elastic_store_kv.go) ------------------------------------------

    def kv_put(self, key: bytes, value: bytes) -> None:
        self.client.request(
            "PUT", f"/{INDEX_KV}/_doc/{key.hex()}",
            {"Value": base64.b64encode(value).decode()})

    def kv_get(self, key: bytes) -> bytes | None:
        try:
            doc = self.client.request("GET",
                                      f"/{INDEX_KV}/_doc/{key.hex()}",
                                      ok_statuses=(200,))
        except ElasticError as e:
            if e.status == 404:
                return None
            raise
        if not doc.get("found"):
            return None
        return base64.b64decode(doc["_source"]["Value"])

    def close(self) -> None:
        self.client.close()


register_store("elastic7", ElasticStore)
register_store("elastic", ElasticStore)
