"""Shared pieces of the wire-protocol DB clients (pg_wire, mysql_wire):
the DB-API cursor shell and the %s-placeholder rewriter."""

from __future__ import annotations

import base64
import hashlib
import hmac
import os
from typing import Callable


class WireCursor:
    """Minimal DB-API cursor over a connection exposing
    ``_query(sql, params) -> (rows, rowcount)``."""

    def __init__(self, conn):
        self._conn = conn
        self._rows: list[tuple] = []
        self._idx = 0
        self.rowcount = -1

    def execute(self, sql: str, params: tuple = ()) -> "WireCursor":
        self._rows, self.rowcount = self._conn._query(sql, tuple(params))
        self._idx = 0
        return self

    def fetchone(self):
        if self._idx >= len(self._rows):
            return None
        row = self._rows[self._idx]
        self._idx += 1
        return row

    def fetchall(self) -> list[tuple]:
        rows = self._rows[self._idx:]
        self._idx = len(self._rows)
        return rows

    def close(self) -> None:
        self._rows = []


def rewrite_placeholders(sql: str, token: Callable[[int], str]) -> str:
    """Replace DB-API ``%s`` placeholders outside '...' string literals
    with ``token(n)`` (1-based): ``lambda n: "?"`` for mysql,
    ``lambda n: f"${n}"`` for postgres."""
    out, n, i, in_str = [], 0, 0, False
    while i < len(sql):
        ch = sql[i]
        if in_str:
            out.append(ch)
            if ch == "'":
                in_str = False
            i += 1
        elif ch == "'":
            in_str = True
            out.append(ch)
            i += 1
        elif ch == "%" and i + 1 < len(sql) and sql[i + 1] == "s":
            n += 1
            out.append(token(n))
            i += 2
        else:
            out.append(ch)
            i += 1
    return "".join(out)


def split_dir_name(full_path: str) -> tuple[str, str]:
    """'/a/b/c.txt' -> ('/a/b', 'c.txt'); root is ('', '/'). The one
    canonical path splitter for every wire store."""
    if full_path == "/":
        return "", "/"
    d, _, n = full_path.rstrip("/").rpartition("/")
    return d or "/", n


class ScramClient:
    """Client side of SCRAM-SHA-256 (RFC 5802/7677). postgres leaves
    the authzid/username empty (the startup message names the user);
    mongodb sends n=<user>."""

    def __init__(self, password: str, username: str = ""):
        self.password = password.encode("utf-8")
        self.nonce = base64.b64encode(os.urandom(18)).decode()
        user = username.replace("=", "=3D").replace(",", "=2C")
        self.first_bare = f"n={user},r={self.nonce}"
        self.server_sig: bytes | None = None

    def client_first(self) -> bytes:
        return ("n,," + self.first_bare).encode()

    def client_final(self, server_first: bytes) -> bytes:
        sf = server_first.decode()
        attrs = dict(kv.split("=", 1) for kv in sf.split(","))
        r, salt, iters = attrs["r"], base64.b64decode(attrs["s"]), \
            int(attrs["i"])
        if not r.startswith(self.nonce):
            raise ConnectionError("SCRAM server nonce mismatch")
        salted = hashlib.pbkdf2_hmac("sha256", self.password, salt, iters)
        client_key = hmac.new(salted, b"Client Key", hashlib.sha256).digest()
        stored_key = hashlib.sha256(client_key).digest()
        final_bare = f"c=biws,r={r}"
        auth_msg = ",".join([self.first_bare, sf, final_bare]).encode()
        client_sig = hmac.new(stored_key, auth_msg, hashlib.sha256).digest()
        proof = bytes(a ^ b for a, b in zip(client_key, client_sig))
        server_key = hmac.new(salted, b"Server Key", hashlib.sha256).digest()
        self.server_sig = hmac.new(server_key, auth_msg,
                                   hashlib.sha256).digest()
        return (final_bare
                + ",p=" + base64.b64encode(proof).decode()).encode()

    def verify_server(self, server_final: bytes) -> None:
        attrs = dict(kv.split("=", 1)
                     for kv in server_final.decode().split(","))
        if base64.b64decode(attrs.get("v", "")) != self.server_sig:
            raise ConnectionError("SCRAM server signature mismatch")


def prefix_end(prefix: bytes, *, unbounded: bytes = b"") -> bytes:
    """Smallest key greater than every key with `prefix` (etcd
    clientv3.GetPrefixRangeEnd: increment the last non-0xFF byte).
    `unbounded` is returned when no such key exists (all-0xFF prefix):
    etcd's convention is b"\\x00" ("whole keyspace"), tikv's is b""."""
    b = bytearray(prefix)
    for i in reversed(range(len(b))):
        if b[i] < 0xFF:
            b[i] += 1
            return bytes(b[:i + 1])
    return unbounded
