"""Shared pieces of the wire-protocol DB clients (pg_wire, mysql_wire):
the DB-API cursor shell and the %s-placeholder rewriter."""

from __future__ import annotations

from typing import Callable


class WireCursor:
    """Minimal DB-API cursor over a connection exposing
    ``_query(sql, params) -> (rows, rowcount)``."""

    def __init__(self, conn):
        self._conn = conn
        self._rows: list[tuple] = []
        self._idx = 0
        self.rowcount = -1

    def execute(self, sql: str, params: tuple = ()) -> "WireCursor":
        self._rows, self.rowcount = self._conn._query(sql, tuple(params))
        self._idx = 0
        return self

    def fetchone(self):
        if self._idx >= len(self._rows):
            return None
        row = self._rows[self._idx]
        self._idx += 1
        return row

    def fetchall(self) -> list[tuple]:
        rows = self._rows[self._idx:]
        self._idx = len(self._rows)
        return rows

    def close(self) -> None:
        self._rows = []


def rewrite_placeholders(sql: str, token: Callable[[int], str]) -> str:
    """Replace DB-API ``%s`` placeholders outside '...' string literals
    with ``token(n)`` (1-based): ``lambda n: "?"`` for mysql,
    ``lambda n: f"${n}"`` for postgres."""
    out, n, i, in_str = [], 0, 0, False
    while i < len(sql):
        ch = sql[i]
        if in_str:
            out.append(ch)
            if ch == "'":
                in_str = False
            i += 1
        elif ch == "'":
            in_str = True
            out.append(ch)
            i += 1
        elif ch == "%" and i + 1 < len(sql) and sql[i + 1] == "s":
            n += 1
            out.append(token(n))
            i += 2
        else:
            out.append(ch)
            i += 1
    return "".join(out)
