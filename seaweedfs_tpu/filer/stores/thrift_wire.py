"""Minimal Apache Thrift binary-protocol client (TBinaryProtocol,
strict framing) over a plain socket.

Supports exactly what the hbase filer store needs to drive HBase's
Thrift2 gateway (THBaseService): CALL/REPLY messages, struct/list/
string/i32/i64/bool field encoding, and declared-exception decoding.
No thrift library exists in this image; the encoding below follows the
public Thrift binary protocol spec (thrift.apache.org,
TBinaryProtocol.java): strict messages lead with
``0x8001`` | version, fields are ``(type:i8, id:i16, value)`` ending in
a 0x00 stop byte.

Value model: python values are encoded by explicit (type, value) pairs
so field ids/types stay visible at call sites — a deliberate mirror of
the IDL, auditable against hbase's ``hbase.thrift``.
"""

from __future__ import annotations

import socket
import struct
import threading

VERSION_1 = 0x80010000
CALL, REPLY, EXCEPTION = 1, 2, 3

# thrift type ids (TType)
BOOL, BYTE, DOUBLE = 2, 3, 4
I16, I32, I64 = 6, 8, 10
STRING, STRUCT, MAP, SET, LIST = 11, 12, 13, 14, 15
STOP = 0


class ThriftError(IOError):
    """Server-side TApplicationException or declared IDL exception."""


class ThriftProtocolError(ThriftError):
    """Framing failure; the connection must be discarded."""


# -- encoding ---------------------------------------------------------------

def enc_value(ttype: int, v) -> bytes:
    if ttype == BOOL:
        return b"\x01" if v else b"\x00"
    if ttype == BYTE:
        return struct.pack(">b", v)
    if ttype == I16:
        return struct.pack(">h", v)
    if ttype == I32:
        return struct.pack(">i", v)
    if ttype == I64:
        return struct.pack(">q", v)
    if ttype == DOUBLE:
        return struct.pack(">d", v)
    if ttype == STRING:
        b = v if isinstance(v, bytes) else str(v).encode()
        return struct.pack(">i", len(b)) + b
    if ttype == STRUCT:
        return enc_struct(v)
    if ttype == LIST:
        etype, elems = v
        return (struct.pack(">bi", etype, len(elems))
                + b"".join(enc_value(etype, e) for e in elems))
    if ttype == MAP:
        ktype, vtype, pairs = v
        return (struct.pack(">bbi", ktype, vtype, len(pairs))
                + b"".join(enc_value(ktype, k) + enc_value(vtype, val)
                           for k, val in pairs))
    raise ValueError(f"unsupported thrift type {ttype}")


def enc_struct(fields: list[tuple[int, int, object]]) -> bytes:
    """fields: [(field_id, ttype, value), ...] -> struct bytes."""
    out = []
    for fid, ttype, v in fields:
        out.append(struct.pack(">bh", ttype, fid))
        out.append(enc_value(ttype, v))
    out.append(b"\x00")
    return b"".join(out)


# -- decoding ---------------------------------------------------------------

class Reader:
    def __init__(self, f):
        self.f = f

    def read(self, n: int) -> bytes:
        b = self.f.read(n)
        if len(b) != n:
            raise ThriftProtocolError("connection closed mid-message")
        return b

    def i8(self) -> int:
        return struct.unpack(">b", self.read(1))[0]

    def i16(self) -> int:
        return struct.unpack(">h", self.read(2))[0]

    def i32(self) -> int:
        return struct.unpack(">i", self.read(4))[0]

    def i64(self) -> int:
        return struct.unpack(">q", self.read(8))[0]

    def binary(self) -> bytes:
        return self.read(self.i32())

    def value(self, ttype: int):
        if ttype == BOOL:
            return self.read(1) != b"\x00"
        if ttype == BYTE:
            return self.i8()
        if ttype == DOUBLE:
            return struct.unpack(">d", self.read(8))[0]
        if ttype == I16:
            return self.i16()
        if ttype == I32:
            return self.i32()
        if ttype == I64:
            return self.i64()
        if ttype == STRING:
            return self.binary()
        if ttype == STRUCT:
            return self.struct()
        if ttype in (LIST, SET):
            etype = self.i8()
            return [self.value(etype) for _ in range(self.i32())]
        if ttype == MAP:
            ktype, vtype = self.i8(), self.i8()
            return [(self.value(ktype), self.value(vtype))
                    for _ in range(self.i32())]
        raise ThriftProtocolError(f"unsupported thrift type {ttype}")

    def struct(self) -> dict[int, object]:
        """-> {field_id: value}; nested structs are dicts too."""
        fields: dict[int, object] = {}
        while True:
            ttype = self.i8()
            if ttype == STOP:
                return fields
            fid = self.i16()
            fields[fid] = self.value(ttype)


# -- client -----------------------------------------------------------------

class ThriftClient:
    """One-connection strict-binary-protocol client; call() is
    lock-serialized like the RESP/pg wire clients in this package."""

    def __init__(self, host: str, port: int, *, timeout: float = 30):
        self._sock = socket.create_connection((host, port), timeout=timeout)
        self._f = self._sock.makefile("rb")
        self._lock = threading.Lock()
        self._seq = 0

    def close(self) -> None:
        try:
            self._f.close()
            if self._sock is not None:  # call() Nones it after poisoning
                self._sock.close()
        except OSError:
            pass

    def call(self, method: str, args: list[tuple[int, int, object]]
             ) -> dict[int, object]:
        """-> the REPLY struct ({0: success, or exception fields}).
        Raises ThriftError on EXCEPTION messages or declared-exception
        reply fields; any framing failure poisons the connection."""
        name = method.encode()
        msg = (struct.pack(">I", VERSION_1 | CALL)
               + struct.pack(">i", len(name)) + name)
        with self._lock:
            if self._sock is None:
                raise ThriftProtocolError(
                    "connection is closed (previous I/O error)")
            self._seq += 1
            try:
                self._sock.sendall(msg + struct.pack(">i", self._seq)
                                   + enc_struct(args))
                r = Reader(self._f)
                head = r.i32() & 0xFFFFFFFF  # strict header, unsigned view
                if head & 0xFFFF0000 != VERSION_1:
                    raise ThriftProtocolError(
                        f"bad thrift version 0x{head:x}")
                mtype = head & 0xFF
                rname = r.binary()
                seq = r.i32()
                if seq != self._seq or rname != name:
                    raise ThriftProtocolError(
                        f"reply mismatch: {rname!r} seq {seq}")
                reply = r.struct()
            except ThriftProtocolError:
                self.close()
                self._sock = None
                raise
            except OSError:
                self.close()
                self._sock = None
                raise
            if mtype == EXCEPTION:
                # TApplicationException {1: message, 2: type}
                msg = reply.get(1, b"?")
                raise ThriftError(msg.decode("utf-8", "replace")
                                  if isinstance(msg, bytes) else str(msg))
            for fid, v in reply.items():
                if fid != 0 and isinstance(v, dict):
                    # declared exception (TIOError {1: message})
                    raise ThriftError(
                        v.get(1, b"server exception").decode("utf-8",
                                                             "replace")
                        if isinstance(v.get(1), bytes) else str(v))
            return reply
