"""SQLite filer store — the durable default.

Rebuild of the reference's abstract_sql/sqlite backends
(/root/reference/weed/filer/sqlite/sqlite_store.go,
abstract_sql/abstract_sql_store.go): one row per entry keyed by
(directory-hash, name) with the Entry protobuf as the value blob, plus a
generic KV table. Serialization reuses the filer_pb.Entry wire format so
store contents survive backend swaps.
"""

from __future__ import annotations

import sqlite3
import threading
from typing import Iterator

from ...pb import filer_pb2
from ..entry import Entry
from ..filerstore import register_store

_SCHEMA = """
CREATE TABLE IF NOT EXISTS filemeta (
  directory TEXT NOT NULL,
  name      TEXT NOT NULL,
  meta      BLOB,
  PRIMARY KEY (directory, name)
);
CREATE TABLE IF NOT EXISTS kv (
  k BLOB PRIMARY KEY,
  v BLOB
);
"""


class SqliteStore:
    name = "sqlite"

    _mem_seq = 0

    def __init__(self, db_path: str = ":memory:", **_):
        self._uri = False
        if db_path == ":memory:":
            # per-connection private :memory: DBs won't do — every server
            # thread must see one namespace. Use a named shared-cache DB and
            # pin it with an anchor connection.
            SqliteStore._mem_seq += 1
            db_path = (f"file:filer_mem_{id(self)}_{SqliteStore._mem_seq}"
                       f"?mode=memory&cache=shared")
            self._uri = True
        self._db_path = db_path
        self._local = threading.local()
        self._lock = threading.Lock()
        self._anchor = sqlite3.connect(db_path, uri=self._uri,
                                       check_same_thread=False)
        self._anchor.executescript(_SCHEMA)
        self._anchor.commit()

    def _conn(self) -> sqlite3.Connection:
        c = getattr(self._local, "conn", None)
        if c is None:
            c = sqlite3.connect(self._db_path, uri=self._uri,
                                check_same_thread=False)
            c.execute("PRAGMA journal_mode=WAL")
            c.execute("PRAGMA synchronous=NORMAL")
            c.execute("PRAGMA busy_timeout=5000")
            self._local.conn = c
        return c

    @staticmethod
    def _split(full_path: str) -> tuple[str, str]:
        if full_path == "/":
            return "", "/"
        d, _, n = full_path.rstrip("/").rpartition("/")
        return d or "/", n

    def insert_entry(self, entry: Entry) -> None:
        d, n = self._split(entry.full_path)
        blob = entry.to_pb().SerializeToString()
        c = self._conn()
        with self._lock:
            c.execute(
                "INSERT INTO filemeta(directory,name,meta) VALUES(?,?,?) "
                "ON CONFLICT(directory,name) DO UPDATE SET meta=excluded.meta",
                (d, n, blob))
            c.commit()

    update_entry = insert_entry

    def find_entry(self, full_path: str) -> Entry | None:
        d, n = self._split(full_path)
        row = self._conn().execute(
            "SELECT meta FROM filemeta WHERE directory=? AND name=?",
            (d, n)).fetchone()
        if row is None:
            return None
        pb = filer_pb2.Entry.FromString(row[0])
        return Entry.from_pb(d, pb)

    def delete_entry(self, full_path: str) -> None:
        d, n = self._split(full_path)
        c = self._conn()
        with self._lock:
            c.execute("DELETE FROM filemeta WHERE directory=? AND name=?", (d, n))
            c.commit()

    def delete_folder_children(self, full_path: str) -> None:
        base = full_path.rstrip("/") or "/"
        c = self._conn()
        with self._lock:
            c.execute("DELETE FROM filemeta WHERE directory=? OR directory LIKE ?",
                      (base, base + "/%"))
            c.commit()

    def list_directory_entries(self, dir_path: str, start_file_name: str = "",
                               include_start: bool = False, limit: int = 1024,
                               prefix: str = "") -> Iterator[Entry]:
        base = dir_path.rstrip("/") or "/"
        op = ">=" if include_start else ">"
        q = (f"SELECT name, meta FROM filemeta WHERE directory=? AND name {op} ? "
             f"AND name LIKE ? ORDER BY name LIMIT ?")
        rows = self._conn().execute(
            q, (base, start_file_name, (prefix or "") + "%", limit)).fetchall()
        for name, blob in rows:
            pb = filer_pb2.Entry.FromString(blob)
            yield Entry.from_pb(base, pb)

    def kv_get(self, key: bytes) -> bytes | None:
        row = self._conn().execute("SELECT v FROM kv WHERE k=?", (key,)).fetchone()
        return row[0] if row else None

    def kv_put(self, key: bytes, value: bytes) -> None:
        c = self._conn()
        with self._lock:
            c.execute("INSERT INTO kv(k,v) VALUES(?,?) "
                      "ON CONFLICT(k) DO UPDATE SET v=excluded.v", (key, value))
            c.commit()

    def close(self) -> None:
        c = getattr(self._local, "conn", None)
        if c is not None:
            c.close()
            self._local.conn = None
        self._anchor.close()


register_store("sqlite", SqliteStore)
