"""SQLite filer store — the durable default.

Rebuild of the reference's sqlite backend
(/root/reference/weed/filer/sqlite/sqlite_store.go): since round 2 a thin
dialect over the shared SQL layer (stores/abstract_sql.py), exactly how the
reference layers sqlite_store.go on abstract_sql_store.go. Serialization
reuses the filer_pb.Entry wire format so store contents survive backend
swaps.
"""

from __future__ import annotations

from ..filerstore import register_store
from .abstract_sql import AbstractSqlStore, SqliteDialect


class SqliteStore(AbstractSqlStore):
    def __init__(self, db_path: str = ":memory:", **_):
        super().__init__(SqliteDialect(db_path))


register_store("sqlite", SqliteStore)
