"""Cassandra filer store speaking the CQL binary protocol v4.

Rebuild of /root/reference/weed/filer/cassandra/cassandra_store.go
(backed by gocql): no cassandra-driver in this image, so the store
implements the native protocol itself — frame codec, STARTUP/READY,
PasswordAuthenticator (AUTHENTICATE/AUTH_RESPONSE/AUTH_SUCCESS), and
QUERY with bound values — the same statement set the reference runs:

  * ``INSERT INTO filemeta (directory,name,meta) VALUES(?,?,?)
    USING TTL ?`` (InsertEntry, cassandra_store.go:108; CQL inserts
    are upserts, so UpdateEntry shares it)
  * ``SELECT meta FROM filemeta WHERE directory=? AND name=?`` (:130)
  * ``DELETE FROM filemeta WHERE directory=? AND name=?`` (:160)
  * ``DELETE FROM filemeta WHERE directory=?`` (:174) — plus
    python-side recursion for the repo-wide subtree contract
  * ``SELECT name, meta FROM filemeta WHERE directory=? AND name>?
    ORDER BY name ASC LIMIT ?`` (:192-194)
  * kv_* via the 8-byte dir/name key split (cassandra_store_kv.go:53);
    binary keys map through latin-1 so they stay valid UTF-8 varchars

The keyspace and table are created IF NOT EXISTS at startup (the
reference asks operators to create them by hand; self-bootstrap is
kinder and harmless when they already exist).
"""

from __future__ import annotations

import socket
import struct
import threading
from typing import Iterator

from ...pb import filer_pb2
from ..entry import Entry
from ..filerstore import register_store
from .wire_common import split_dir_name

# opcodes
OP_ERROR, OP_STARTUP, OP_READY, OP_AUTHENTICATE = 0x00, 0x01, 0x02, 0x03
OP_QUERY, OP_RESULT, OP_AUTH_RESPONSE, OP_AUTH_SUCCESS = (
    0x07, 0x08, 0x0F, 0x10)

# result kinds
K_VOID, K_ROWS, K_SET_KEYSPACE = 1, 2, 3

# type option ids
T_BLOB, T_INT, T_VARCHAR = 0x0003, 0x0009, 0x000D


class CqlError(Exception):
    def __init__(self, code: int, message: str):
        self.code = code
        self.message = message
        super().__init__(f"(0x{code:04x}) {message}")


def _prefix_upper(prefix: str) -> str | None:
    """Smallest string greater than every string with this prefix
    (rightmost incrementable char bumped); None if none exists."""
    for i in reversed(range(len(prefix))):
        if ord(prefix[i]) < 0x10FFFF:
            return prefix[:i] + chr(ord(prefix[i]) + 1)
    return None


def _string(s: str) -> bytes:
    b = s.encode("utf-8")
    return struct.pack(">H", len(b)) + b


def _long_string(s: str) -> bytes:
    b = s.encode("utf-8")
    return struct.pack(">I", len(b)) + b


def _value(v) -> bytes:
    if v is None:
        return struct.pack(">i", -1)
    if isinstance(v, int):
        raw = struct.pack(">i", v)
    elif isinstance(v, (bytes, bytearray, memoryview)):
        raw = bytes(v)
    else:
        raw = str(v).encode("utf-8")
    return struct.pack(">i", len(raw)) + raw


class CqlConnection:
    def __init__(self, *, host="localhost", port=9042, username="",
                 password="", connect_timeout=10, **_ignored):
        self._host, self._port = host, int(port)
        self._user, self._password = username, password
        self._connect_timeout = connect_timeout
        self._lock = threading.Lock()
        self._sock: socket.socket | None = None
        self._buf = b""
        self._keyspace = ""
        self._connect()

    # -- frames ------------------------------------------------------------

    def _recv_exact(self, n: int) -> bytes:
        while len(self._buf) < n:
            chunk = self._sock.recv(65536)
            if not chunk:
                raise ConnectionError("cassandra server closed connection")
            self._buf += chunk
        out, self._buf = self._buf[:n], self._buf[n:]
        return out

    def _send_frame(self, opcode: int, body: bytes) -> None:
        self._sock.sendall(struct.pack(">BBhBI", 0x04, 0, 0, opcode,
                                       len(body)) + body)

    def _recv_frame(self) -> tuple[int, bytes]:
        header = self._recv_exact(9)
        _ver, _flags, _stream, opcode, length = struct.unpack(">BBhBI",
                                                              header)
        return opcode, self._recv_exact(length)

    # -- connect + auth ----------------------------------------------------

    def _connect(self) -> None:
        self._sock = socket.create_connection(
            (self._host, self._port), timeout=self._connect_timeout)
        self._sock.settimeout(30)
        self._buf = b""
        try:
            self._send_frame(OP_STARTUP, struct.pack(">H", 1)
                             + _string("CQL_VERSION") + _string("3.0.0"))
            opcode, body = self._recv_frame()
            if opcode == OP_AUTHENTICATE:
                token = (b"\x00" + self._user.encode()
                         + b"\x00" + self._password.encode())
                self._send_frame(OP_AUTH_RESPONSE,
                                 struct.pack(">i", len(token)) + token)
                opcode, body = self._recv_frame()
                if opcode == OP_ERROR:
                    raise self._parse_error(body)
                if opcode != OP_AUTH_SUCCESS:
                    raise CqlError(0, f"unexpected auth opcode {opcode}")
            elif opcode == OP_ERROR:
                raise self._parse_error(body)
            elif opcode != OP_READY:
                raise CqlError(0, f"unexpected startup opcode {opcode}")
            if self._keyspace:
                # a reconnect must replay USE: statements are
                # unqualified, and a fresh session has no keyspace
                self._query_locked(f"USE {self._keyspace}", ())
        except Exception:
            self._mark_broken()
            raise

    def _mark_broken(self) -> None:
        try:
            if self._sock is not None:
                self._sock.close()
        except OSError:
            pass
        self._sock = None
        self._buf = b""

    @staticmethod
    def _parse_error(body: bytes) -> CqlError:
        (code,) = struct.unpack(">i", body[:4])
        (n,) = struct.unpack(">H", body[4:6])
        return CqlError(code, body[6:6 + n].decode("utf-8", "replace"))

    # -- query -------------------------------------------------------------

    def set_keyspace(self, keyspace: str) -> None:
        """USE now and on every reconnect."""
        self.query(f"USE {keyspace}")
        self._keyspace = keyspace

    def query(self, cql: str, params: tuple = ()) -> list[tuple]:
        with self._lock:
            if self._sock is None:
                self._connect()
            try:
                return self._query_locked(cql, params)
            except CqlError:
                raise               # server error: stream is still framed
            except Exception:
                self._mark_broken()
                raise

    def _query_locked(self, cql: str, params: tuple) -> list[tuple]:
        flags = 0x01 if params else 0x00
        body = _long_string(cql) + struct.pack(">HB", 0x0001, flags)
        if params:
            body += struct.pack(">H", len(params))
            body += b"".join(_value(p) for p in params)
        self._send_frame(OP_QUERY, body)
        opcode, rbody = self._recv_frame()
        if opcode == OP_ERROR:
            raise self._parse_error(rbody)
        if opcode != OP_RESULT:
            raise CqlError(0, f"unexpected result opcode {opcode}")
        (kind,) = struct.unpack(">i", rbody[:4])
        if kind != K_ROWS:
            return []
        off = 4
        (mflags, ncols) = struct.unpack_from(">ii", rbody, off)
        off += 8
        if mflags & 0x0001:          # global_tables_spec
            for _ in range(2):       # keyspace + table
                (n,) = struct.unpack_from(">H", rbody, off)
                off += 2 + n
        types = []
        for _ in range(ncols):
            if not mflags & 0x0001:
                for _ in range(2):
                    (n,) = struct.unpack_from(">H", rbody, off)
                    off += 2 + n
            (n,) = struct.unpack_from(">H", rbody, off)   # column name
            off += 2 + n
            (tid,) = struct.unpack_from(">H", rbody, off)
            off += 2
            if tid == 0x0000:        # custom type: string follows
                (n,) = struct.unpack_from(">H", rbody, off)
                off += 2 + n
            types.append(tid)
        (nrows,) = struct.unpack_from(">i", rbody, off)
        off += 4
        rows = []
        for _ in range(nrows):
            vals = []
            for tid in types:
                (ln,) = struct.unpack_from(">i", rbody, off)
                off += 4
                if ln < 0:
                    vals.append(None)
                    continue
                raw = rbody[off:off + ln]
                off += ln
                if tid == T_INT:
                    vals.append(int.from_bytes(raw, "big", signed=True))
                elif tid == T_VARCHAR:
                    vals.append(raw.decode("utf-8", "replace"))
                else:
                    vals.append(bytes(raw))
            rows.append(tuple(vals))
        return rows

    def close(self) -> None:
        self._mark_broken()


class CassandraStore:
    """FilerStore over the CQL client (CassandraStore,
    cassandra_store.go:23)."""

    name = "cassandra"

    def __init__(self, *, host="localhost", port=9042,
                 keyspace="seaweedfs", username="", password="", **kwargs):
        self.conn = CqlConnection(host=host, port=port, username=username,
                                  password=password, **kwargs)
        self.conn.query(
            f"CREATE KEYSPACE IF NOT EXISTS {keyspace} WITH replication = "
            f"{{'class': 'SimpleStrategy', 'replication_factor': 1}}")
        self.conn.set_keyspace(keyspace)
        self.conn.query(
            "CREATE TABLE IF NOT EXISTS filemeta (directory varchar, "
            "name varchar, meta blob, PRIMARY KEY ((directory), name)) "
            "WITH CLUSTERING ORDER BY (name ASC)")

    _split = staticmethod(split_dir_name)

    def insert_entry(self, entry: Entry) -> None:
        d, n = self._split(entry.full_path)
        blob = entry.to_pb().SerializeToString()
        self.conn.query(
            "INSERT INTO filemeta (directory,name,meta) VALUES(?,?,?) "
            "USING TTL ?", (d, n, blob,
                            max(int(entry.attr.ttl_sec or 0), 0)))

    update_entry = insert_entry

    def find_entry(self, full_path: str) -> Entry | None:
        d, n = self._split(full_path)
        rows = self.conn.query(
            "SELECT meta FROM filemeta WHERE directory=? AND name=?",
            (d, n))
        if not rows or not rows[0][0]:
            return None
        pb = filer_pb2.Entry.FromString(rows[0][0])
        return Entry.from_pb(d, pb)

    def delete_entry(self, full_path: str) -> None:
        d, n = self._split(full_path)
        self.conn.query(
            "DELETE FROM filemeta WHERE directory=? AND name=?", (d, n))

    def delete_folder_children(self, full_path: str) -> None:
        base = full_path.rstrip("/") or "/"
        # the reference deletes only the exact partition (:174) and lets
        # the filer recurse; recurse here for the repo-wide contract
        for entry in list(self.list_directory_entries(base,
                                                      limit=1 << 30)):
            if entry.is_directory:
                self.delete_folder_children(entry.full_path)
        self.conn.query("DELETE FROM filemeta WHERE directory=?", (base,))

    def list_directory_entries(self, dir_path: str, start_file_name: str = "",
                               include_start: bool = False,
                               limit: int = 1024,
                               prefix: str = "") -> Iterator[Entry]:
        base = dir_path.rstrip("/") or "/"
        op = ">=" if include_start else ">"
        start = start_file_name
        if prefix and prefix > start:
            start, op = prefix, ">="
        # bound the clustering range by the prefix so the server-side
        # LIMIT counts prefix-matching rows (filtering after LIMIT
        # silently truncates prefixed listings)
        upper = _prefix_upper(prefix) if prefix else None
        cql = (f"SELECT name, meta FROM filemeta WHERE directory=? "
               f"AND name{op}?"
               + (" AND name<?" if upper else "")
               + " ORDER BY name ASC LIMIT ?")
        params = ((base, start, upper, limit) if upper
                  else (base, start, limit))
        for name, blob in self.conn.query(cql, params):
            if prefix and not name.startswith(prefix):
                continue  # defensive; range already bounds the prefix
            if not blob:
                continue
            pb = filer_pb2.Entry.FromString(blob)
            yield Entry.from_pb(base, pb)

    # -- kv (cassandra_store_kv.go; 8-byte dir/name split) -----------------

    @staticmethod
    def _kv_dir_name(key: bytes) -> tuple[str, str]:
        key = key + b"\x00" * max(0, 8 - len(key))
        return (key[:8].decode("latin-1"), key[8:].decode("latin-1"))

    def kv_put(self, key: bytes, value: bytes) -> None:
        d, n = self._kv_dir_name(key)
        self.conn.query(
            "INSERT INTO filemeta (directory,name,meta) VALUES(?,?,?) "
            "USING TTL ?", (d, n, value, 0))

    def kv_get(self, key: bytes) -> bytes | None:
        d, n = self._kv_dir_name(key)
        rows = self.conn.query(
            "SELECT meta FROM filemeta WHERE directory=? AND name=?",
            (d, n))
        return rows[0][0] if rows else None

    def close(self) -> None:
        self.conn.close()


register_store("cassandra", CassandraStore)
