"""Pure-python MySQL client/server protocol client, DB-API flavored.

Rebuild of the client side the reference gets from go-sql-driver/mysql
(/root/reference/weed/filer/mysql/mysql_store.go:1): no pymysql in this
image, so the store speaks the wire protocol itself, like stores/
pg_wire.py does for postgres and stores/redis.py for RESP.

Scope — what AbstractSqlStore needs, on the real wire format:

  * handshake v10 + HandshakeResponse41, mysql_native_password
    scramble (SHA1(pw) XOR SHA1(salt + SHA1(SHA1(pw)))), including the
    AuthSwitchRequest path
  * parameterized statements via the prepared-statement BINARY
    protocol (COM_STMT_PREPARE / COM_STMT_EXECUTE) — the same choice
    go-sql-driver makes — so strings, blobs and NULLs are typed on the
    wire, no escaping games; statements are cached per connection
  * parameterless statements (DDL, catalog queries) via COM_QUERY with
    text-resultset decoding (charset 63 -> bytes, else str)
  * ``%s`` placeholders are rewritten to ``?`` outside string literals
  * transparent reconnect after socket drops (autocommit)
"""

from __future__ import annotations

import hashlib
import socket
import struct
import threading

from .wire_common import WireCursor, rewrite_placeholders

_MAX_CACHED_STMTS = 64

# column types
T_TINY, T_SHORT, T_LONG, T_FLOAT, T_DOUBLE, T_LONGLONG = 1, 2, 3, 4, 5, 8
T_VARCHAR, T_VAR_STRING, T_STRING, T_BLOB = 15, 253, 254, 252
# fixed-width binary-protocol integer types: TINY/SHORT/LONG/LONGLONG,
# YEAR (13, 2 bytes) and INT24 (9, sent as 4 bytes on the wire)
_INT_SIZES = {T_TINY: 1, T_SHORT: 2, T_LONG: 4, T_LONGLONG: 8, 13: 2, 9: 4}

CAP_LONG_PASSWORD = 0x1
CAP_CONNECT_WITH_DB = 0x8
CAP_PROTOCOL_41 = 0x200
CAP_TRANSACTIONS = 0x2000
CAP_SECURE_CONNECTION = 0x8000
CAP_PLUGIN_AUTH = 0x80000


class MySqlError(Exception):
    def __init__(self, code: int, message: str, server: bool = False):
        self.code = code
        self.message = message
        # server=True: a well-framed ERR packet — the stream is still in
        # sync. Anything else means our parser lost its place.
        self.server = server
        super().__init__(f"({code}) {message}")


def native_password_scramble(password: str, salt: bytes) -> bytes:
    """mysql_native_password: SHA1(pw) XOR SHA1(salt + SHA1(SHA1(pw)))."""
    if not password:
        return b""
    h1 = hashlib.sha1(password.encode()).digest()
    h2 = hashlib.sha1(h1).digest()
    h3 = hashlib.sha1(salt + h2).digest()
    return bytes(a ^ b for a, b in zip(h1, h3))


def _lenenc_int(n: int) -> bytes:
    if n < 0xfb:
        return bytes([n])
    if n < 1 << 16:
        return b"\xfc" + struct.pack("<H", n)
    if n < 1 << 24:
        return b"\xfd" + struct.pack("<I", n)[:3]
    return b"\xfe" + struct.pack("<Q", n)


def _read_lenenc_int(buf: bytes, off: int) -> tuple[int | None, int]:
    c = buf[off]
    if c < 0xfb:
        return c, off + 1
    if c == 0xfb:                  # NULL (in text rows)
        return None, off + 1
    if c == 0xfc:
        return struct.unpack_from("<H", buf, off + 1)[0], off + 3
    if c == 0xfd:
        return int.from_bytes(buf[off + 1:off + 4], "little"), off + 4
    return struct.unpack_from("<Q", buf, off + 1)[0], off + 9


def _read_lenenc_bytes(buf: bytes, off: int) -> tuple[bytes | None, int]:
    n, off = _read_lenenc_int(buf, off)
    if n is None:
        return None, off
    return buf[off:off + n], off + n


class MySqlCursor(WireCursor):
    pass


class MySqlConnection:
    def __init__(self, *, host="localhost", port=3306, user="root",
                 password="", database="seaweedfs", connect_timeout=10,
                 **_ignored):
        self.user = user
        self.password = password
        self._host, self._port = host, int(port)
        self._database = database
        self._connect_timeout = connect_timeout
        self._lock = threading.Lock()
        self._sock: socket.socket | None = None
        self._buf = b""
        self._seq = 0
        self._stmts: dict[str, tuple[int, int]] = {}  # sql -> (id, nparams)
        self._connect()

    # -- packet framing ----------------------------------------------------

    def _recv_exact(self, n: int) -> bytes:
        while len(self._buf) < n:
            chunk = self._sock.recv(65536)
            if not chunk:
                raise ConnectionError("mysql server closed connection")
            self._buf += chunk
        out, self._buf = self._buf[:n], self._buf[n:]
        return out

    def _read_packet(self) -> bytes:
        head = self._recv_exact(4)
        length = int.from_bytes(head[:3], "little")
        self._seq = head[3] + 1
        payload = self._recv_exact(length)
        if length == 0xffffff:     # multi-packet payload
            payload += self._read_packet()
        return payload

    def _send_packet(self, payload: bytes) -> None:
        while True:
            chunk, payload = payload[:0xffffff], payload[0xffffff:]
            self._sock.sendall(len(chunk).to_bytes(3, "little")
                               + bytes([self._seq & 0xff]) + chunk)
            self._seq += 1
            if len(chunk) < 0xffffff:
                return

    def _command(self, payload: bytes) -> None:
        self._seq = 0
        self._send_packet(payload)

    @staticmethod
    def _parse_err(payload: bytes) -> MySqlError:
        code = struct.unpack_from("<H", payload, 1)[0]
        msg = payload[3:]
        if msg[:1] == b"#":        # sql-state marker
            msg = msg[6:]
        return MySqlError(code, msg.decode("utf-8", "replace"), server=True)

    # -- connect + auth ----------------------------------------------------

    def _connect(self) -> None:
        self._sock = socket.create_connection(
            (self._host, self._port), timeout=self._connect_timeout)
        self._sock.settimeout(30)
        self._buf = b""
        self._stmts = {}
        try:
            self._handshake()
        except Exception:
            # a half-open unauthenticated socket must not survive — the
            # next query would be sent pre-auth on a desynced stream
            self._mark_broken()
            raise

    def _handshake(self) -> None:
        greeting = self._read_packet()
        if greeting[:1] == b"\xff":
            raise self._parse_err(greeting)
        if greeting[0] != 10:
            raise MySqlError(0, f"unsupported protocol {greeting[0]}")
        off = 1
        end = greeting.index(b"\0", off)
        off = end + 1 + 4                      # server version + conn id
        salt = greeting[off:off + 8]
        off += 8 + 1                           # filler
        off += 2 + 1 + 2 + 2                   # caps-lo, charset, status, hi
        auth_len = greeting[off]
        off += 1 + 10
        salt += greeting[off:off + max(13, auth_len - 8)].rstrip(b"\0")[:12]
        caps = (CAP_LONG_PASSWORD | CAP_CONNECT_WITH_DB | CAP_PROTOCOL_41
                | CAP_TRANSACTIONS | CAP_SECURE_CONNECTION | CAP_PLUGIN_AUTH)
        token = native_password_scramble(self.password, salt)
        resp = (struct.pack("<IIB", caps, 1 << 24, 33) + b"\0" * 23
                + self.user.encode() + b"\0"
                + bytes([len(token)]) + token
                + self._database.encode() + b"\0"
                + b"mysql_native_password\0")
        self._send_packet(resp)
        pkt = self._read_packet()
        if pkt[:1] == b"\xfe":                 # AuthSwitchRequest
            end = pkt.index(b"\0", 1)
            plugin = pkt[1:end].decode()
            if plugin != "mysql_native_password":
                raise MySqlError(0, f"unsupported auth plugin {plugin}")
            new_salt = pkt[end + 1:].rstrip(b"\0")[:20]
            self._send_packet(native_password_scramble(self.password,
                                                       new_salt))
            pkt = self._read_packet()
        if pkt[:1] == b"\xff":
            raise self._parse_err(pkt)
        # make the documented autocommit contract real even on servers
        # configured with autocommit=0
        self._com_query("SET autocommit=1")

    def _mark_broken(self) -> None:
        try:
            if self._sock is not None:
                self._sock.close()
        except OSError:
            pass
        self._sock = None
        self._buf = b""
        self._stmts = {}

    # -- query dispatch ----------------------------------------------------

    def _query(self, sql: str, params: tuple) -> tuple[list[tuple], int]:
        my_sql = rewrite_placeholders(sql, lambda n: "?")
        with self._lock:
            if self._sock is None:
                self._connect()
            try:
                if params:
                    return self._stmt_execute(my_sql, params)
                return self._com_query(my_sql)
            except MySqlError as e:
                if not e.server:
                    # parse desync (unexpected framing): the stream can't
                    # be trusted any more
                    self._mark_broken()
                raise
            except Exception:
                # socket errors AND struct/index parse failures both leave
                # unread response bytes behind — never reuse the stream
                self._mark_broken()
                raise

    # COM_QUERY text protocol (DDL + catalog queries, no params)
    def _com_query(self, sql: str) -> tuple[list[tuple], int]:
        self._command(b"\x03" + sql.encode("utf-8"))
        pkt = self._read_packet()
        if pkt[:1] == b"\xff":
            raise self._parse_err(pkt)
        if pkt[:1] == b"\x00":                 # OK
            affected, _ = _read_lenenc_int(pkt, 1)
            return [], affected or 0
        ncols, _ = _read_lenenc_int(pkt, 0)
        cols = [self._read_coldef() for _ in range(ncols)]
        self._expect_eof()
        rows: list[tuple] = []
        while True:
            pkt = self._read_packet()
            if pkt[:1] == b"\xfe" and len(pkt) < 9:
                break
            if pkt[:1] == b"\xff":
                raise self._parse_err(pkt)
            off, vals = 0, []
            for ctype, charset in cols:
                raw, off = _read_lenenc_bytes(pkt, off)
                vals.append(self._text_value(raw, ctype, charset))
            rows.append(tuple(vals))
        return rows, len(rows)

    @staticmethod
    def _text_value(raw: bytes | None, ctype: int, charset: int):
        if raw is None:
            return None
        if ctype in _INT_SIZES:
            return int(raw)
        if ctype in (T_FLOAT, T_DOUBLE, 0):
            return float(raw)
        if charset == 63:                      # binary
            return bytes(raw)
        return raw.decode("utf-8", "replace")

    def _read_coldef(self) -> tuple[int, int]:
        pkt = self._read_packet()
        off = 0
        for _ in range(6):                     # catalog..org_name
            raw, off = _read_lenenc_bytes(pkt, off)
        off += 1                               # fixed-len 0x0c marker
        charset = struct.unpack_from("<H", pkt, off)[0]
        ctype = pkt[off + 6]
        return ctype, charset

    def _expect_eof(self) -> None:
        pkt = self._read_packet()
        if not (pkt[:1] == b"\xfe" and len(pkt) < 9):
            raise MySqlError(0, "protocol desync: expected EOF")

    # prepared-statement binary protocol
    def _prepare(self, sql: str) -> tuple[int, int]:
        cached = self._stmts.get(sql)
        if cached is not None:
            return cached
        self._command(b"\x16" + sql.encode("utf-8"))
        pkt = self._read_packet()
        if pkt[:1] == b"\xff":
            raise self._parse_err(pkt)
        stmt_id, ncols, nparams = struct.unpack_from("<IHH", pkt, 1)
        for _ in range(nparams):
            self._read_packet()
        if nparams:
            self._expect_eof()
        for _ in range(ncols):
            self._read_packet()
        if ncols:
            self._expect_eof()
        if len(self._stmts) >= _MAX_CACHED_STMTS:
            evict_sql, (evict_id, _) = next(iter(self._stmts.items()))
            self._command(b"\x19" + struct.pack("<I", evict_id))  # CLOSE
            del self._stmts[evict_sql]
        self._stmts[sql] = (stmt_id, nparams)
        return stmt_id, nparams

    def _stmt_execute(self, sql: str,
                      params: tuple) -> tuple[list[tuple], int]:
        stmt_id, nparams = self._prepare(sql)
        if nparams != len(params):
            raise MySqlError(0, f"statement wants {nparams} params, "
                                f"got {len(params)}")
        body = [b"\x17", struct.pack("<IBI", stmt_id, 0, 1)]
        nullmap = bytearray((len(params) + 7) // 8)
        types, values = [], []
        for i, p in enumerate(params):
            if p is None:
                nullmap[i // 8] |= 1 << (i % 8)
                types.append(struct.pack("<BB", T_VAR_STRING, 0))
            elif isinstance(p, (bytes, bytearray, memoryview)):
                types.append(struct.pack("<BB", T_BLOB, 0))
                raw = bytes(p)
                values.append(_lenenc_int(len(raw)) + raw)
            elif isinstance(p, bool):
                types.append(struct.pack("<BB", T_TINY, 0))
                values.append(b"\x01" if p else b"\x00")
            elif isinstance(p, int):
                types.append(struct.pack("<BB", T_LONGLONG, 0))
                values.append(struct.pack("<q", p))
            elif isinstance(p, float):
                types.append(struct.pack("<BB", T_DOUBLE, 0))
                values.append(struct.pack("<d", p))
            else:
                types.append(struct.pack("<BB", T_VAR_STRING, 0))
                raw = str(p).encode("utf-8")
                values.append(_lenenc_int(len(raw)) + raw)
        body += [bytes(nullmap), b"\x01"] + types + values
        self._command(b"".join(body))
        pkt = self._read_packet()
        if pkt[:1] == b"\xff":
            raise self._parse_err(pkt)
        if pkt[:1] == b"\x00":                 # OK
            affected, _ = _read_lenenc_int(pkt, 1)
            return [], affected or 0
        ncols, _ = _read_lenenc_int(pkt, 0)
        cols = [self._read_coldef() for _ in range(ncols)]
        self._expect_eof()
        rows: list[tuple] = []
        while True:
            pkt = self._read_packet()
            if pkt[:1] == b"\xfe" and len(pkt) < 9:
                break
            if pkt[:1] == b"\xff":
                raise self._parse_err(pkt)
            rows.append(self._binary_row(pkt, cols))
        return rows, len(rows)

    def _binary_row(self, pkt: bytes, cols: list[tuple[int, int]]) -> tuple:
        n = len(cols)
        nullmap = pkt[1:1 + (n + 9) // 8]
        off = 1 + (n + 9) // 8
        vals = []
        for i, (ctype, charset) in enumerate(cols):
            if nullmap[(i + 2) // 8] & (1 << ((i + 2) % 8)):
                vals.append(None)
                continue
            if ctype in _INT_SIZES:
                size = _INT_SIZES[ctype]
                vals.append(int.from_bytes(pkt[off:off + size], "little",
                                           signed=True))
                off += size
            elif ctype == T_DOUBLE:
                vals.append(struct.unpack_from("<d", pkt, off)[0])
                off += 8
            elif ctype == T_FLOAT:
                vals.append(struct.unpack_from("<f", pkt, off)[0])
                off += 4
            else:
                raw, off = _read_lenenc_bytes(pkt, off)
                vals.append(bytes(raw) if charset == 63
                            else raw.decode("utf-8", "replace"))
        return tuple(vals)

    # -- DB-API shape ------------------------------------------------------

    def cursor(self) -> MySqlCursor:
        return MySqlCursor(self)

    def commit(self) -> None:
        pass  # autocommit

    def close(self) -> None:
        try:
            if self._sock is not None:
                self._command(b"\x01")         # COM_QUIT
        except OSError:
            pass
        self._mark_broken()
