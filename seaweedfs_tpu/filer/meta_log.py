"""Persisted filer metadata log under /topics/.system/log.

Rebuild of /root/reference/weed/filer/filer_notify.go: logMetaEvent (:70)
streams every metadata event into dated segment files stored through the
filer's own namespace, and ReadPersistedLogBuffer (:116) replays them for a
point-in-time resume. The round-1 build only had a bounded in-memory deque,
so a filer restart lost the stream and `filer.sync` / meta backup could not
resume; this module closes that gap.

Design differences from the reference (same behavior, simpler machinery):
  * Events are length-framed serialized SubscribeMetadataResponse protos
    (4-byte big-endian length + payload), accumulated in a small buffer and
    flushed to the current segment entry by a daemon thread (interval) or
    inline (size threshold).
  * A segment is a filer entry `/topics/.system/log/<YYYY-MM-DD>/<HH-MM-SS>.<startNs>`
    whose bytes live in the entry's inline `content` — so persistence
    inherits whatever durability the configured FilerStore has (sqlite /
    leveldb survive restart; the memory store mirrors the reference's
    behavior when its log store is wiped).
  * Segment entries are written store-direct (no _notify), the reference's
    SystemLogDir skip.
"""

from __future__ import annotations

import threading
import time
from datetime import datetime, timezone

from ..pb import filer_pb2
from .entry import Entry, new_directory_entry

SYSTEM_LOG_DIR = "/topics/.system/log"


class MetaLog:
    def __init__(self, store, *, segment_max_bytes: int = 4 << 20,
                 flush_interval: float = 2.0, flush_threshold: int = 256 << 10):
        self.store = store
        self.segment_max_bytes = segment_max_bytes
        self.flush_interval = flush_interval
        self.flush_threshold = flush_threshold
        self._lock = threading.Lock()
        self._buf = bytearray()
        self._buf_start_ns = 0
        self._segment_path: str | None = None
        self._segment_size = 0
        self._flusher: threading.Thread | None = None
        self._stop = threading.Event()

    # -- write side --------------------------------------------------------

    def append(self, msg: filer_pb2.SubscribeMetadataResponse) -> None:
        blob = msg.SerializeToString()
        flush_now = False
        with self._lock:
            if not self._buf:
                self._buf_start_ns = msg.ts_ns
            self._buf += len(blob).to_bytes(4, "big") + blob
            flush_now = len(self._buf) >= self.flush_threshold
            if self._flusher is None:
                self._flusher = threading.Thread(
                    target=self._flush_loop, name="meta-log-flush", daemon=True)
                self._flusher.start()
        if flush_now:
            self.flush()

    def _flush_loop(self) -> None:
        while not self._stop.wait(self.flush_interval):
            try:
                self.flush()
            except Exception as e:  # keep the flusher alive across store hiccups
                from ..utils import glog

                glog.warning(f"meta log flush failed: {e}")

    def flush(self) -> None:
        with self._lock:
            if not self._buf:
                return
            payload, start_ns = bytes(self._buf), self._buf_start_ns
            self._buf.clear()
            self._buf_start_ns = 0
            self._write_segment(payload, start_ns)

    def _write_segment(self, payload: bytes, start_ns: int) -> None:
        """Append to the open segment entry, rolling by date or size."""
        day = datetime.fromtimestamp(start_ns / 1e9, tz=timezone.utc)
        date_dir = f"{SYSTEM_LOG_DIR}/{day:%Y-%m-%d}"
        roll = (
            self._segment_path is None
            or not self._segment_path.startswith(date_dir + "/")
            or self._segment_size + len(payload) > self.segment_max_bytes
        )
        if roll:
            self._ensure_dir(date_dir)
            self._segment_path = f"{date_dir}/{day:%H-%M-%S}.{start_ns}"
            self._segment_size = 0
            seg = Entry(full_path=self._segment_path, content=payload)
            seg.attr.mtime = seg.attr.crtime = int(start_ns / 1e9)
            self.store.insert_entry(seg)
        else:
            seg = self.store.find_entry(self._segment_path)
            if seg is None:  # wiped underneath us — restart the segment
                self._segment_path = None
                return self._write_segment(payload, start_ns)
            seg.content += payload
            self.store.update_entry(seg)
        self._segment_size += len(payload)

    def _ensure_dir(self, dir_path: str) -> None:
        parts = dir_path.strip("/").split("/")
        path = ""
        for p in parts:
            path += "/" + p
            if self.store.find_entry(path) is None:
                self.store.insert_entry(new_directory_entry(path))

    # -- read side (ReadPersistedLogBuffer, filer_notify.go:116) -----------

    def read_since(self, since_ns: int):
        """Yield persisted events with ts_ns > since_ns, oldest first.
        Flushes the write buffer first so the persisted view is current."""
        self.flush()
        segments: list[tuple[int, str]] = []  # (start_ns, path)
        days = self.store.list_directory_entries(SYSTEM_LOG_DIR, limit=10000)
        for day in sorted(days or [], key=lambda e: e.full_path):
            kids = self.store.list_directory_entries(day.full_path, limit=100000)
            for seg in kids or []:
                try:
                    start_ns = int(seg.full_path.rsplit(".", 1)[1])
                except (IndexError, ValueError):
                    continue
                segments.append((start_ns, seg.full_path))
        segments.sort()
        for idx, (start_ns, path) in enumerate(segments):
            nxt = segments[idx + 1][0] if idx + 1 < len(segments) else None
            if nxt is not None and nxt <= since_ns:
                continue  # every event in this segment predates the cursor
            seg = self.store.find_entry(path)
            if seg is None:
                continue
            data, off = seg.content, 0
            while off + 4 <= len(data):
                ln = int.from_bytes(data[off:off + 4], "big")
                off += 4
                if off + ln > len(data):
                    break  # torn tail from an interrupted flush
                msg = filer_pb2.SubscribeMetadataResponse()
                try:
                    msg.ParseFromString(bytes(data[off:off + ln]))
                except Exception:
                    break
                off += ln
                if msg.ts_ns > since_ns:
                    yield msg

    def close(self) -> None:
        self._stop.set()
        self.flush()
