"""FilerStore SPI + registry.

Rebuild of /root/reference/weed/filer/filerstore.go:21-44 — the 9-method
KV/list interface every metadata backend implements, with stores registered
by name (the reference registers 21 backends via init(); this build ships
memory, sqlite, and leveldb-file flavors and keeps the same seam open).
"""

from __future__ import annotations

from typing import Iterator, Protocol

from .entry import Entry


class FilerStore(Protocol):
    name: str

    def insert_entry(self, entry: Entry) -> None: ...

    def update_entry(self, entry: Entry) -> None: ...

    def find_entry(self, full_path: str) -> Entry | None: ...

    def delete_entry(self, full_path: str) -> None: ...

    def delete_folder_children(self, full_path: str) -> None: ...

    def list_directory_entries(
        self, dir_path: str, start_file_name: str = "",
        include_start: bool = False, limit: int = 1024,
        prefix: str = "",
    ) -> Iterator[Entry]: ...

    def kv_get(self, key: bytes) -> bytes | None: ...

    def kv_put(self, key: bytes, value: bytes) -> None: ...

    def close(self) -> None: ...


_REGISTRY: dict[str, type] = {}


def register_store(name: str, cls: type) -> None:
    _REGISTRY[name] = cls


def get_store(name: str, **kwargs) -> FilerStore:
    from .stores import memory, sqlite  # noqa: F401 - registration side effect

    cls = _REGISTRY.get(name)
    if cls is None:
        raise ValueError(f"unknown filer store {name!r} "
                         f"(available: {sorted(_REGISTRY)})")
    return cls(**kwargs)
