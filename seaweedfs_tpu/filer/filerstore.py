"""FilerStore SPI + registry.

Rebuild of /root/reference/weed/filer/filerstore.go:21-44 — the 9-method
KV/list interface every metadata backend implements, with stores registered
by name (the reference registers 21 backends via init(); this build ships
memory, sqlite, and leveldb-file flavors and keeps the same seam open).
"""

from __future__ import annotations

from typing import Iterator, Protocol

from .entry import Entry


class FilerStore(Protocol):
    name: str

    def insert_entry(self, entry: Entry) -> None: ...

    def update_entry(self, entry: Entry) -> None: ...

    def find_entry(self, full_path: str) -> Entry | None: ...

    def delete_entry(self, full_path: str) -> None: ...

    def delete_folder_children(self, full_path: str) -> None: ...

    def list_directory_entries(
        self, dir_path: str, start_file_name: str = "",
        include_start: bool = False, limit: int = 1024,
        prefix: str = "",
    ) -> Iterator[Entry]: ...

    def kv_get(self, key: bytes) -> bytes | None: ...

    def kv_put(self, key: bytes, value: bytes) -> None: ...

    def close(self) -> None: ...


_REGISTRY: dict[str, type] = {}


def register_store(name: str, cls: type) -> None:
    _REGISTRY[name] = cls


def get_store(name: str, **kwargs) -> FilerStore:
    from .stores import (  # noqa: F401 - registration side effect
        abstract_sql,
        arango_wire,
        cql_wire,
        elastic_wire,
        etcd_store,
        gated,
        leveldb,
        memory,
        mongo_wire,
        redis,
        redis3,
        redis_lua,
        sqlite,
        hbase_store,
        tikv_store,
        ydb_store,
    )

    cls = _REGISTRY.get(name)
    if cls is None:
        raise ValueError(f"unknown filer store {name!r} "
                         f"(available: {sorted(_REGISTRY)})")
    return cls(**kwargs)


def available_stores() -> list[str]:
    from .stores import (  # noqa: F401 - registration side effect
        abstract_sql,
        arango_wire,
        cql_wire,
        elastic_wire,
        etcd_store,
        gated,
        leveldb,
        memory,
        mongo_wire,
        redis,
        redis3,
        redis_lua,
        sqlite,
        hbase_store,
        tikv_store,
        ydb_store,
    )

    return sorted(_REGISTRY)


class StoreWrapper:
    """Instrumented pass-through (filerstore_wrapper.go): per-op counters
    and cumulative latency, exported through utils.stats."""

    def __init__(self, store: FilerStore):
        self.store = store
        self.name = store.name
        from ..utils.stats import FILER_STORE_COUNTER, FILER_STORE_SECONDS

        self._counter = FILER_STORE_COUNTER
        self._seconds = FILER_STORE_SECONDS

    def _timed(self, op: str, fn, *args, **kwargs):
        import time

        t0 = time.perf_counter()
        try:
            return fn(*args, **kwargs)
        finally:
            self._counter.inc(store=self.name, op=op)
            self._seconds.inc(time.perf_counter() - t0,
                              store=self.name, op=op)

    def insert_entry(self, entry):
        return self._timed("insert", self.store.insert_entry, entry)

    def update_entry(self, entry):
        return self._timed("update", self.store.update_entry, entry)

    def find_entry(self, full_path):
        return self._timed("find", self.store.find_entry, full_path)

    def delete_entry(self, full_path):
        return self._timed("delete", self.store.delete_entry, full_path)

    def delete_folder_children(self, full_path):
        return self._timed("deleteFolderChildren",
                           self.store.delete_folder_children, full_path)

    def list_directory_entries(self, *args, **kwargs):
        return self._timed("list", lambda: list(
            self.store.list_directory_entries(*args, **kwargs)))

    def kv_get(self, key):
        return self._timed("kvGet", self.store.kv_get, key)

    def kv_put(self, key, value):
        return self._timed("kvPut", self.store.kv_put, key, value)

    def close(self):
        self.store.close()


class RetryingStore:
    """Transient-fault-absorbing pass-through: every store op retries on
    retryable transport errors (connection loss to a redis/ydb/mysql
    backend, gRPC UNAVAILABLE, injected faults) with exponential backoff
    — behind the per-target circuit breaker in utils.retry so a dead
    metadata backend sheds load instead of being hammered by every
    handler thread. Mutations additionally evaluate the
    `filer.store.mutate` failpoint so the chaos suite can flap the
    backend without monkeypatching.

    Safe to retry because the 9-op SPI is idempotent end to end: inserts
    are UPSERTs, deletes tolerate already-gone rows, reads are reads.
    """

    def __init__(self, store: FilerStore, *, attempts: int = 4,
                 wait_init: float = 0.05):
        self.store = store
        self.name = store.name
        self.attempts = attempts
        self.wait_init = wait_init

    def _run(self, op: str, fn, *, mutate: bool = False):
        from ..utils import failpoint
        from ..utils.retry import retry

        def attempt():
            if mutate:
                failpoint.fail("filer.store.mutate",
                               ctx=f"{self.name} {op}")
            return fn()

        return retry(f"store.{self.name}.{op}", attempt,
                     attempts=self.attempts, wait_init=self.wait_init)

    def insert_entry(self, entry):
        return self._run("insert", lambda: self.store.insert_entry(entry),
                         mutate=True)

    def update_entry(self, entry):
        return self._run("update", lambda: self.store.update_entry(entry),
                         mutate=True)

    def find_entry(self, full_path):
        return self._run("find", lambda: self.store.find_entry(full_path))

    def delete_entry(self, full_path):
        return self._run("delete",
                         lambda: self.store.delete_entry(full_path),
                         mutate=True)

    def delete_folder_children(self, full_path):
        return self._run(
            "deleteFolderChildren",
            lambda: self.store.delete_folder_children(full_path),
            mutate=True)

    def list_directory_entries(self, *args, **kwargs):
        # materialized so a mid-iteration transport error is retryable
        # as a unit instead of surfacing from a half-consumed generator
        return self._run("list", lambda: list(
            self.store.list_directory_entries(*args, **kwargs)))

    def kv_get(self, key):
        return self._run("kvGet", lambda: self.store.kv_get(key))

    def kv_put(self, key, value):
        return self._run("kvPut", lambda: self.store.kv_put(key, value),
                         mutate=True)

    def close(self):
        self.store.close()


class PathTranslatingStore:
    """Mounts a store under a path prefix
    (filerstore_translate_path.go): callers see `/x`, the backing store
    sees `<root>/x`. Used for per-path store routing (fs.configure)."""

    def __init__(self, store: FilerStore, root: str):
        self.store = store
        self.root = root.rstrip("/")
        self.name = f"{store.name}@{root}"

    def _to(self, path: str) -> str:
        return self.root + path if path != "/" else (self.root or "/")

    def _from(self, path: str) -> str:
        if self.root and path.startswith(self.root):
            return path[len(self.root):] or "/"
        return path

    def insert_entry(self, entry):
        import copy

        e = copy.copy(entry)
        e.full_path = self._to(entry.full_path)
        self.store.insert_entry(e)

    def update_entry(self, entry):
        import copy

        e = copy.copy(entry)
        e.full_path = self._to(entry.full_path)
        self.store.update_entry(e)

    def find_entry(self, full_path):
        e = self.store.find_entry(self._to(full_path))
        if e is not None:
            e.full_path = self._from(e.full_path)
        return e

    def delete_entry(self, full_path):
        self.store.delete_entry(self._to(full_path))

    def delete_folder_children(self, full_path):
        self.store.delete_folder_children(self._to(full_path))

    def list_directory_entries(self, dir_path, start_file_name="",
                               include_start=False, limit=1024, prefix=""):
        for e in self.store.list_directory_entries(
                self._to(dir_path), start_file_name, include_start,
                limit, prefix):
            e.full_path = self._from(e.full_path)
            yield e

    def kv_get(self, key):
        return self.store.kv_get(key)

    def kv_put(self, key, value):
        return self.store.kv_put(key, value)

    def close(self):
        self.store.close()
