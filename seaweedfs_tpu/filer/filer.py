"""Filer core: entry CRUD over a FilerStore + metadata event log.

Rebuild of /root/reference/weed/filer/filer.go (CreateEntry :175,
UpdateEntry :284, FindEntry :312), filer_delete_entry.go, filer_rename.go
(via filer gRPC AtomicRenameEntry), and filer_notify.go's metadata event
stream (LogBuffer becomes a bounded in-memory deque that subscribers drain
with a replay cursor).
"""

from __future__ import annotations

import threading
import time
from collections import deque

from ..pb import filer_pb2
from .entry import Attr, Entry, new_directory_entry
from .filerstore import FilerStore
from .meta_log import SYSTEM_LOG_DIR, MetaLog


class FilerError(Exception):
    pass


class NotFound(FilerError):
    pass


class NotEmpty(FilerError):
    pass


class Filer:
    def __init__(self, store: FilerStore, *, log_capacity: int = 16384,
                 persist_meta_log: bool = True):
        self.store = store
        self._log: deque[filer_pb2.SubscribeMetadataResponse] = deque(
            maxlen=log_capacity)
        self._log_cond = threading.Condition()
        self.signature = int(time.time_ns()) & 0x7FFFFFFF
        # filer_notify.go:70 logMetaEvent — events also flush to dated
        # segment entries under /topics/.system/log so subscribers can
        # resume point-in-time across restarts (and a lagging subscriber
        # falls back to the persisted log instead of losing drops from
        # the bounded deque).
        self.meta_log = MetaLog(store) if persist_meta_log else None
        # optional external publisher (notification.toml; filer_notify.go's
        # Queue.SendMessage side of NotifyUpdateEvent) — set by the server
        self.notification_queue = None
        # optional mutation hook (path, recursive) — the native filer hot
        # plane registers one so python-side mutations (S3 gateway,
        # DELETE, rename) invalidate its path cache (see
        # native/dataplane.cpp filer hot plane). Called AFTER the store
        # mutation commits.
        self.on_mutate = None

    def _mutated(self, path: str, recursive: bool = False) -> None:
        if self.on_mutate is not None:
            try:
                self.on_mutate(path, recursive)
            except Exception:
                pass

    # -- events (filer_notify.go:20 NotifyUpdateEvent) ---------------------

    def _notify(self, directory: str, old: Entry | None, new: Entry | None,
                delete_chunks: bool = False,
                from_other_cluster: bool = False) -> None:
        if directory.startswith(SYSTEM_LOG_DIR):
            return  # the log must not log itself (filer_notify.go SystemLogDir)
        ev = filer_pb2.EventNotification(
            delete_chunks=delete_chunks,
            is_from_other_cluster=from_other_cluster,
            signatures=[self.signature])
        if old is not None:
            ev.old_entry.CopyFrom(old.to_pb())
        if new is not None:
            ev.new_entry.CopyFrom(new.to_pb())
            if old is not None and old.parent != new.parent:
                ev.new_parent_path = new.parent
        msg = filer_pb2.SubscribeMetadataResponse(
            directory=directory, ts_ns=time.time_ns())
        msg.event_notification.CopyFrom(ev)
        with self._log_cond:
            self._log.append(msg)
            self._log_cond.notify_all()
        if self.meta_log is not None:
            self.meta_log.append(msg)
        if self.notification_queue is not None:
            key = (new or old).full_path if (new or old) else directory
            try:
                self.notification_queue.send_message(key, ev)
            except Exception as e:  # publisher failures must not fail writes
                from ..utils import glog

                glog.warning(f"notification publish failed: {e}")

    def read_events(self, since_ns: int, timeout: float = 1.0):
        """-> (events newer than since_ns, new cursor).

        Served from the in-memory tail when the cursor is inside its window;
        a cursor older than the window (subscriber lagged past the deque, or
        the filer restarted) replays the persisted log first
        (ReadPersistedLogBuffer, filer_notify.go:116)."""
        with self._log_cond:
            oldest = self._log[0].ts_ns if self._log else None
        if self.meta_log is not None and (oldest is None or since_ns < oldest):
            persisted = list(self.meta_log.read_since(since_ns))
            if persisted:
                with self._log_cond:
                    mem = {m.ts_ns for m in self._log}
                out = [m for m in persisted if m.ts_ns not in mem]
                with self._log_cond:
                    out += [m for m in self._log if m.ts_ns > since_ns]
                out.sort(key=lambda m: m.ts_ns)
                if out:
                    return out, out[-1].ts_ns
        with self._log_cond:
            out = [m for m in self._log if m.ts_ns > since_ns]
            if not out:
                self._log_cond.wait(timeout)
                out = [m for m in self._log if m.ts_ns > since_ns]
            return out, (out[-1].ts_ns if out else since_ns)

    # -- CRUD --------------------------------------------------------------

    def find_entry(self, path: str) -> Entry:
        path = normalize(path)
        if path == "/":
            return new_directory_entry("/")
        e = self.store.find_entry(path)
        if e is None:
            raise NotFound(path)
        return e

    def exists(self, path: str) -> bool:
        try:
            self.find_entry(path)
            return True
        except NotFound:
            return False

    def create_entry(self, entry: Entry, *, o_excl: bool = False,
                     skip_parents: bool = False,
                     from_other_cluster: bool = False) -> None:
        entry.full_path = normalize(entry.full_path)
        if not skip_parents:
            self._ensure_parents(entry.parent)
        old = self.store.find_entry(entry.full_path)
        if old is not None and o_excl:
            raise FilerError(f"{entry.full_path} already exists")
        if old is not None and old.is_directory and not entry.is_directory:
            raise FilerError(f"{entry.full_path} is a directory")
        self.store.insert_entry(entry)
        self._mutated(entry.full_path)
        self._notify(entry.parent, old, entry,
                     from_other_cluster=from_other_cluster)

    def _ensure_parents(self, dir_path: str) -> None:
        dir_path = normalize(dir_path)
        if dir_path == "/":
            return
        if self.store.find_entry(dir_path) is not None:
            return
        self._ensure_parents(parent_of(dir_path))
        self.store.insert_entry(new_directory_entry(dir_path))

    def update_entry(self, entry: Entry, *,
                     from_other_cluster: bool = False) -> None:
        entry.full_path = normalize(entry.full_path)
        old = self.store.find_entry(entry.full_path)
        if old is None:
            raise NotFound(entry.full_path)
        self.store.update_entry(entry)
        self._mutated(entry.full_path)
        self._notify(entry.parent, old, entry,
                     from_other_cluster=from_other_cluster)

    def delete_entry(self, path: str, *, recursive: bool = False,
                     is_delete_data: bool = True,
                     from_other_cluster: bool = False) -> list[str]:
        """-> chunk fids to garbage-collect (filer_delete_entry.go)."""
        path = normalize(path)
        entry = self.find_entry(path)
        fids: list[str] = []
        if entry.is_directory:
            kids = list(self.store.list_directory_entries(path, limit=2))
            if kids and not recursive:
                raise NotEmpty(f"directory {path} not empty")
            fids.extend(self._collect_fids_recursive(path))
            self.store.delete_folder_children(path)
        if is_delete_data:
            fids.extend(c.file_id for c in entry.chunks)
        self.store.delete_entry(path)
        self._mutated(path, recursive=entry.is_directory)
        self._notify(entry.parent, entry, None, delete_chunks=is_delete_data,
                     from_other_cluster=from_other_cluster)
        return fids

    def _collect_fids_recursive(self, dir_path: str) -> list[str]:
        fids = []
        start = ""
        while True:
            batch = list(self.store.list_directory_entries(
                dir_path, start_file_name=start, limit=1024))
            if not batch:
                break
            for e in batch:
                if e.is_directory:
                    fids.extend(self._collect_fids_recursive(e.full_path))
                else:
                    fids.extend(c.file_id for c in e.chunks)
            start = batch[-1].name
            if len(batch) < 1024:
                break
        return fids

    def rename(self, old_path: str, new_path: str) -> None:
        """AtomicRenameEntry semantics: move the entry (and any subtree) by
        rewriting paths in the store (filer_rename.go moveEntry)."""
        for _ in self.rename_stream(old_path, new_path):
            pass

    def rename_stream(self, old_path: str, new_path: str):
        """rename() that yields each (old_entry, moved_entry) as it lands
        — the engine under both AtomicRenameEntry and StreamRenameEntry
        (filer_grpc_server_rename.go:51 moveEntry): children move first,
        depth-first, then the entry itself."""
        old_path, new_path = normalize(old_path), normalize(new_path)
        entry = self.find_entry(old_path)
        self._ensure_parents(parent_of(new_path))
        if entry.is_directory:
            for child in list(self.store.list_directory_entries(
                    old_path, limit=1_000_000)):
                yield from self.rename_stream(child.full_path,
                                              new_path + "/" + child.name)
        moved = Entry(full_path=new_path, attr=entry.attr, chunks=entry.chunks,
                      extended=entry.extended, content=entry.content,
                      is_directory=entry.is_directory,
                      hard_link_id=entry.hard_link_id,
                      hard_link_counter=entry.hard_link_counter)
        self.store.delete_entry(old_path)
        self.store.insert_entry(moved)
        self._mutated(old_path, recursive=entry.is_directory)
        self._mutated(new_path, recursive=entry.is_directory)
        self._notify(moved.parent, entry, moved)
        yield entry, moved

    def list_entries(self, dir_path: str, start: str = "",
                     include_start: bool = False, limit: int = 1024,
                     prefix: str = ""):
        return self.store.list_directory_entries(
            normalize(dir_path), start, include_start, limit, prefix)


def normalize(p: str) -> str:
    if not p.startswith("/"):
        p = "/" + p
    while "//" in p:
        p = p.replace("//", "/")
    return p.rstrip("/") or "/"


def parent_of(p: str) -> str:
    p = normalize(p)
    if p == "/":
        return "/"
    return p.rsplit("/", 1)[0] or "/"
