"""Pipelined chunk engine (ISSUE 14): bounded-window GET readahead +
overlapped PUT upload fan-out.

The filer's chunk data path — the leg every S3/HTTP byte actually
crosses — was strictly sequential: `stream_file` issued one volume
round-trip at a time, and `write_stream` fully uploaded chunk N before
reading chunk N+1 from the client. For a multi-chunk object the wall
was Σ(RTT + transfer) when overlap makes it ~max(transfer, RTT) — the
RapidRAID (arXiv:1207.6744) argument PR 6 applied to archival encode,
now applied to the foreground GET/PUT legs.

Both directions share one engine over the process-wide fan-out
executor (`utils.fanout`):

  * **GET** — `readahead(views, fetch)` yields `fetch(view)` results
    STRICTLY IN ORDER while prefetching up to `SWFS_CHUNK_READAHEAD`
    (default 4) upcoming views, bounded by `SWFS_CHUNK_READAHEAD_MB`
    (default 32) in-flight bytes. Closing the generator (client
    disconnect mid-stream) cancels queued prefetches; already-running
    fetches complete harmlessly and are dropped.
  * **PUT** — `UploadWindow` keeps up to `SWFS_CHUNK_UPLOAD_OVERLAP`
    (default = readahead window) `save_chunk` uploads in flight while
    the caller keeps reading the client body. md5/offset accounting
    stays strictly ordered because the CALLER still reads
    sequentially; only the uploads overlap. The first failure cancels
    the window and `saved_fids()` hands back every chunk that made it
    to a volume server so the caller can GC them — exactly the
    sequential path's failure contract.

Pressure awareness: both windows consult `qos.pressure.SIGNAL` per
step and collapse to 1 (sequential) while the process has recently
observed shedding (tenant admission rejection, volume-server 429/503)
or strain (a chunk read forced onto the failover ladder) — prefetch
fan-out must not multiply load on a cluster that is already hot.
Pool awareness: windows are clamped to the wdclient keep-alive pool's
per-host size (`SWFS_HTTP_POOL_SIZE`) so a single streaming request
can never sweep every warm connection.

`SWFS_CHUNK_PIPELINE=0` disables both directions (the A/B OFF arm)
without touching any call site. Config is TTL-cached like utils.trace;
tests flipping the env mid-process call `refresh_config()`.
"""

from __future__ import annotations

import os
import threading
import time
from collections import deque
from concurrent.futures import FIRST_COMPLETED, wait

from ..qos.pressure import SIGNAL
from ..utils import fanout
from ..utils.stats import (
    CHUNK_PIPELINE_BYTES,
    CHUNK_PIPELINE_INFLIGHT,
    CHUNK_PIPELINE_OPS,
)

_CFG_TTL_S = 1.0
_cfg = {"t": -1.0, "enabled": True, "window": 4, "cap_bytes": 32 << 20,
        "upload_window": 0}
_cfg_lock = threading.Lock()


class ShortBodyError(IOError):
    """A PUT with a known Content-Length whose client body ended short.
    Committing the entry would silently truncate the object; the saved
    chunks are GC'd and the HTTP/S3 handlers map this to a 4xx (the
    client failed, not the cluster)."""

    def __init__(self, got: int, want: int):
        self.got = got
        self.want = want
        super().__init__(
            f"short body: read {got} of {want} declared bytes")


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, str(default)) or default)
    except ValueError:
        return default


def _config() -> dict:
    c = _cfg
    now = time.monotonic()
    if now - c["t"] > _CFG_TTL_S:
        with _cfg_lock:
            c["enabled"] = (os.environ.get("SWFS_CHUNK_PIPELINE", "1")
                            or "1").lower() not in ("0", "false", "off")
            c["window"] = max(1, _env_int("SWFS_CHUNK_READAHEAD", 4))
            c["cap_bytes"] = max(1, _env_int(
                "SWFS_CHUNK_READAHEAD_MB", 32)) << 20
            c["upload_window"] = max(0, _env_int(
                "SWFS_CHUNK_UPLOAD_OVERLAP", 0))  # 0 = follow window
            c["t"] = now
    return c


def refresh_config() -> None:
    """Drop the cached env config (tests flip the env mid-process)."""
    _cfg["t"] = -1.0


def _pool_clamp(w: int) -> int:
    """Never fan wider than the keep-alive pool keeps warm connections
    per host — beyond that every extra in-flight fetch dials cold and
    evicts someone else's warm connection at check-in."""
    from ..wdclient.pool import max_per_host

    return max(1, min(w, max_per_host()))


def _effective_get_window(n_items: int) -> tuple[int, bool]:
    """-> (window, collapsed-by-hot-signal). Pure — no metrics."""
    cfg = _config()
    if not cfg["enabled"] or n_items < 2:
        return 1, False
    if SIGNAL.is_hot():
        return 1, True
    return _pool_clamp(cfg["window"]), False


def _effective_put_window() -> tuple[int, bool]:
    cfg = _config()
    if not cfg["enabled"]:
        return 1, False
    if SIGNAL.is_hot():
        return 1, True
    return _pool_clamp(cfg["upload_window"] or cfg["window"]), False


def get_window(n_items: int) -> int:
    """Effective readahead window for a GET of `n_items` chunk views
    (1 = the sequential path). A hot-signal collapse is counted ONCE
    per call — product code calls this once per request (stream_file);
    the per-yield re-evaluation inside `readahead` counts transitions,
    not polls."""
    w, hot = _effective_get_window(n_items)
    if hot:
        CHUNK_PIPELINE_OPS.inc(direction="get", result="collapsed")
    return w


def put_window() -> int:
    """Effective upload-overlap window for a PUT (1 = sequential).
    Pure — UploadWindow does its own transition-counted collapse
    accounting (its wait loop polls this every spin)."""
    return _effective_put_window()[0]


# -- GET: bounded-window in-order readahead ---------------------------------


def readahead(items, fetch, *, direction: str = "get", span=None):
    """Generator yielding `fetch(item)` for every item STRICTLY in
    order, prefetching ahead on the shared fan-out executor.

    * the window is re-evaluated every step: a hot signal mid-stream
      degrades the remaining reads to sequential (and back);
    * in-flight bytes (by each item's `.size`, when present) are capped
      so a wide window of 4MB chunks cannot hold tens of MB hostage;
    * closing the generator cancels queued prefetches — a client
      disconnect must not fetch the rest of a large object;
    * the first fetch failure cancels the window and re-raises in
      order, exactly where the sequential loop would have raised.

    `span` (the request's active span, optional) gets per-yield
    `readaheadHit`/`inflight` attributes plus final totals — the PR-7
    answer to "did the prefetcher actually stay ahead?".
    """
    items = list(items)
    n = len(items)
    pending: deque = deque()  # (item, future), submit order == item order
    next_i = 0
    inflight_bytes = 0
    hits = waits = 0
    collapsed = False  # hot-signal transition flag (count events, not polls)
    gauge_dir = direction

    def _size(it) -> int:
        return int(getattr(it, "size", 0) or 0)

    def _run(it):
        CHUNK_PIPELINE_INFLIGHT.inc(direction=gauge_dir)
        try:
            return fetch(it)
        finally:
            CHUNK_PIPELINE_INFLIGHT.dec(direction=gauge_dir)

    def _pump():
        nonlocal next_i, inflight_bytes, collapsed
        target, hot = _effective_get_window(n)
        if hot and not collapsed:
            CHUNK_PIPELINE_OPS.inc(direction=gauge_dir, result="collapsed")
        collapsed = hot
        while next_i < n and len(pending) < target and (
                not pending
                or inflight_bytes + _size(items[next_i])
                <= _config()["cap_bytes"]):
            it = items[next_i]
            next_i += 1
            inflight_bytes += _size(it)
            CHUNK_PIPELINE_OPS.inc(direction=gauge_dir, result="launched")
            pending.append((it, fanout.submit(_run, it)))

    try:
        _pump()
        while pending:
            it, fut = pending.popleft()
            hit = fut.done()
            if hit:
                hits += 1
                CHUNK_PIPELINE_OPS.inc(direction=gauge_dir,
                                       result="prefetch_hit")
            else:
                waits += 1
                CHUNK_PIPELINE_OPS.inc(direction=gauge_dir,
                                       result="prefetch_wait")
            try:
                data = fut.result()
            except BaseException:
                # in-order failure surface: everything queued behind
                # the failing chunk is moot
                _cancel(pending, gauge_dir)
                raise
            inflight_bytes -= _size(it)
            CHUNK_PIPELINE_BYTES.inc(len(data) if data is not None else 0,
                                     direction=gauge_dir)
            if span is not None:
                span.set_attr(readaheadHit=hit, inflight=len(pending))
            _pump()  # refill BEFORE yielding: the consumer's socket
            #          write happens while the window stays full
            yield data
    except GeneratorExit:
        _cancel(pending, gauge_dir)
        raise
    finally:
        if span is not None and (hits or waits):
            span.set_attr(readaheadHits=hits, readaheadWaits=waits)


def _cancel(pending, direction: str) -> None:
    """Abandon every queued prefetch: futures not yet started are
    cancelled outright; already-running ones complete harmlessly and
    are dropped. Both count as `cancelled` — the consumer walked away
    from that many chunks mid-window."""
    for _it, fut in pending:
        fut.cancel()
        CHUNK_PIPELINE_OPS.inc(direction=direction, result="cancelled")
    pending.clear()


# -- PUT: overlapped upload fan-out -----------------------------------------


class UploadWindow:
    """Up to W concurrent `save_fn(data)` calls while the caller keeps
    reading the client body. Submit order is chunk order; `finish()`
    resolves in that order and stamps offsets, so the entry's chunk
    list is byte-identical to the sequential path's."""

    def __init__(self, save_fn):
        self._save = save_fn
        self._slots: list = []  # (future, offset, nbytes) in submit order
        self._failed: BaseException | None = None
        self._collapsed = False  # hot-signal transition flag

    def _raise_if_failed(self) -> None:
        if self._failed is not None:
            raise self._failed
        for fut, _off, _nb in self._slots:
            if fut.done() and not fut.cancelled():
                exc = fut.exception()
                if exc is not None:
                    self._failed = exc
                    raise exc

    def add(self, data: bytes, offset: int) -> None:
        """Queue one chunk upload; blocks while the window is full.
        Raises the FIRST upload failure as soon as it is visible — the
        caller stops reading the body instead of buffering a doomed
        request to completion."""
        self._raise_if_failed()
        while True:
            target, hot = _effective_put_window()
            if hot and not self._collapsed:
                CHUNK_PIPELINE_OPS.inc(direction="put",
                                       result="collapsed")
            self._collapsed = hot
            live = [f for f, _o, _n in self._slots if not f.done()]
            if len(live) < target:
                break
            wait(live, return_when=FIRST_COMPLETED)
            self._raise_if_failed()

        def _run(payload=data):
            CHUNK_PIPELINE_INFLIGHT.inc(direction="put")
            try:
                return self._save(payload)
            finally:
                CHUNK_PIPELINE_INFLIGHT.dec(direction="put")

        CHUNK_PIPELINE_OPS.inc(direction="put", result="launched")
        self._slots.append((fanout.submit(_run), offset, len(data)))

    def finish(self) -> list:
        """-> the ordered chunk list with offsets stamped. Raises the
        first failure (after letting every in-flight upload settle)."""
        chunks = []
        err: BaseException | None = self._failed
        for fut, off, nbytes in self._slots:
            try:
                c = fut.result()
            except BaseException as e:  # noqa: BLE001 — re-raised below
                if err is None:
                    err = e
                continue
            if err is None:
                c.offset = off
                chunks.append(c)
                CHUNK_PIPELINE_BYTES.inc(nbytes, direction="put")
        if err is not None:
            self._failed = err
            raise err
        return chunks

    def saved_fids(self) -> list[str]:
        """Every chunk that actually landed on a volume server — the GC
        list after a failure. Waits for in-flight uploads to settle
        first: a chunk completing AFTER the failure must not leak."""
        CHUNK_PIPELINE_OPS.inc(direction="put", result="aborted")
        fids = []
        for fut, _off, _nb in self._slots:
            try:
                c = fut.result()
            except BaseException:  # noqa: BLE001 — failed upload: no chunk
                continue
            fids.append(c.file_id)
        return fids
