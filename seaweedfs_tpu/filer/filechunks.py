"""Chunk math: visible-interval resolution + manifest chunks.

Rebuild of /root/reference/weed/filer/filechunks.go (NonOverlappingVisible
Intervals/ViewFromChunks), interval_list.go, and filechunk_manifest.go
(chunks >IntervalSize get folded into manifest chunks).

A file is a list of FileChunk extents; later-modified chunks shadow earlier
ones. Reads resolve the chunk list into non-overlapping [start, stop) views.
"""

from __future__ import annotations

from dataclasses import dataclass

from ..pb import filer_pb2

MANIFEST_BATCH = 1000  # fold manifests once a file exceeds this many chunks


@dataclass
class ChunkView:
    file_id: str
    chunk_offset: int  # offset inside the chunk
    size: int
    logical_offset: int  # offset in the file
    is_full_chunk: bool = False
    cipher_key: bytes = b""
    is_gzipped: bool = False


def total_size(chunks) -> int:
    return max((c.offset + c.size for c in chunks), default=0)


def etag(chunks) -> str:
    import hashlib

    if not chunks:
        return ""
    if len(chunks) == 1:
        return chunks[0].e_tag or chunks[0].file_id
    h = hashlib.md5()
    for c in chunks:
        h.update((c.e_tag or c.file_id).encode())
    return f"{h.hexdigest()}-{len(chunks)}"


def non_overlapping_visible_intervals(chunks) -> list[tuple[int, int, object]]:
    """-> [(start, stop, chunk)] sorted, later mtime wins on overlap
    (filechunks.go NonOverlappingVisibleIntervals)."""
    events = sorted(chunks, key=lambda c: (c.modified_ts_ns, c.file_id))
    visible: list[list] = []  # [start, stop, chunk]
    for c in events:
        start, stop = c.offset, c.offset + c.size
        out = []
        for v in visible:
            vs, ve, vc = v
            if ve <= start or vs >= stop:
                out.append(v)
                continue
            if vs < start:
                out.append([vs, start, vc])
            if ve > stop:
                out.append([stop, ve, vc])
        out.append([start, stop, c])
        visible = out
    visible.sort(key=lambda v: v[0])
    return [(s, e, c) for s, e, c in visible if e > s]


def view_from_chunks(chunks, offset: int = 0, size: int | None = None) -> list[ChunkView]:
    """Resolve a read range into per-chunk views (ViewFromChunks).
    size=None means "to end-of-file from `offset`" — callers streaming a
    whole entry (filer GET, replication materialize, the ISSUE-14
    pipelined readers) pass None instead of re-deriving total_size."""
    if size is None:
        size = max(total_size(chunks) - offset, 0)
    stop = offset + size
    views = []
    for vs, ve, c in non_overlapping_visible_intervals(chunks):
        s, e = max(vs, offset), min(ve, stop)
        if s >= e:
            continue
        views.append(ChunkView(
            file_id=c.file_id,
            chunk_offset=s - c.offset,
            size=e - s,
            logical_offset=s,
            is_full_chunk=(s == c.offset and e == c.offset + c.size),
            cipher_key=c.cipher_key,
            is_gzipped=c.is_compressed,
        ))
    return views


# -- manifests (filechunk_manifest.go) -------------------------------------

def has_chunk_manifest(chunks) -> bool:
    return any(c.is_chunk_manifest for c in chunks)


def separate_manifest_chunks(chunks):
    manifests, rest = [], []
    for c in chunks:
        (manifests if c.is_chunk_manifest else rest).append(c)
    return manifests, rest


def resolve_chunk_manifest(fetch_fn, chunks) -> list:
    """Expand manifest chunks recursively; fetch_fn(file_id) -> bytes
    (ResolveChunkManifest)."""
    out = []
    for c in chunks:
        if not c.is_chunk_manifest:
            out.append(c)
            continue
        m = filer_pb2.FileChunkManifest.FromString(fetch_fn(c.file_id))
        resolved = resolve_chunk_manifest(fetch_fn, m.chunks)
        for rc in resolved:
            rc.offset += c.offset
        out.extend(resolved)
    return out


def maybe_manifestize(save_fn, chunks) -> list:
    """Fold data chunks into manifest chunks when too many
    (MaybeManifestize): save_fn(bytes) -> FileChunk for the manifest blob."""
    data_chunks = [c for c in chunks if not c.is_chunk_manifest]
    manifest_chunks = [c for c in chunks if c.is_chunk_manifest]
    if len(data_chunks) <= MANIFEST_BATCH:
        return chunks
    folded = []
    for i in range(0, len(data_chunks) - len(data_chunks) % MANIFEST_BATCH,
                   MANIFEST_BATCH):
        batch = data_chunks[i:i + MANIFEST_BATCH]
        base = min(c.offset for c in batch)
        m = filer_pb2.FileChunkManifest()
        for c in batch:
            cc = filer_pb2.FileChunk()
            cc.CopyFrom(c)
            cc.offset -= base
            m.chunks.append(cc)
        mc = save_fn(m.SerializeToString())
        mc.offset = base
        mc.size = max(c.offset + c.size for c in batch) - base
        mc.is_chunk_manifest = True
        folded.append(mc)
    tail = data_chunks[len(data_chunks) - len(data_chunks) % MANIFEST_BATCH:]
    return manifest_chunks + folded + tail
