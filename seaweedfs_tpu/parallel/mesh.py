"""Multi-chip EC compute: shard stripe batches over a jax.sharding.Mesh.

The reference scales EC work by fanning goroutines across volume servers
(shell/command_ec_encode.go:194-251 copies shards in parallel; each server
encodes serially). The TPU-native scaling axis is different: parity is a
per-byte-column GF(2^8) matmul, so a stripe batch `data[k, B]` can be split
along B across every chip in a mesh with ZERO cross-chip communication for
encode/reconstruct — the ICI is only needed for integrity collectives
(e.g. fleet-wide parity probes via pmax).

Mesh axes used here:

  * ``stripe`` — the byte-column axis of a stripe batch (pure data parallel).
  * the SAME axis doubles as the V (volume/slab) axis for the stacked
    variants (ISSUE 5): a stacked batch ``[V, k, B]`` can shard whole
    slabs across chips instead of splitting every slab's columns —
    per-chip dispatch queues fill independently, which is what a fleet
    of concurrent encodes needs (RapidRAID's pipelined distribution of
    coding work across nodes, arXiv:1207.6744).

`shard_map` gives each device its local [k, B/n] slab; the same bitsliced
MXU matmul from ops/rs_jax.py runs per-device. Outputs keep the same
sharding, so a host only pulls back the shard slabs it will write locally.

This module is also the ONE sanctioned device-enumeration point:
tools/lint.py rejects bare ``jax.devices()`` anywhere else (bench.py
excepted) — device placement must go through the helpers here so mesh
policy stays in one file.
"""

from __future__ import annotations

import functools
import os
import threading

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

try:
    _shard_map = jax.shard_map  # jax >= 0.4.35 top-level export
except AttributeError:  # pragma: no cover - depends on jax version
    from jax.experimental.shard_map import shard_map as _shard_map

from ..ops.rs_jax import (
    fused_reconstruct_op,
    fused_reconstruct_stacked_op,
    geom_parity_op,
    geom_stacked_op,
    geom_targets_for,
    gf_matmul_bits,
    parity_matrix_op,
)
from ..ops.rs_xor import gf_matmul_xor

STRIPE_AXIS = "stripe"

# Serialized-submission guard (found by ISSUE 3's tier-1 CPU mesh): two
# threads concurrently submitting multi-device shard_map modules interleave
# XLA's cross-module rendezvous and deadlock. The lock covers SUBMISSION
# only — the returned arrays are async, so batches still pipeline
# device-side. The EC dispatch scheduler holds its own lock too; this one
# protects the direct-call paths (scheduler off, concurrent scrubbers).
_SUBMIT_MU = threading.Lock()


def local_devices() -> list:
    """Every device this process can place work on — THE sanctioned
    enumeration call (see module docstring / tools/lint.py)."""
    return list(jax.devices())


def device_count() -> int:
    """len(local_devices()) without making callers touch jax directly."""
    return len(local_devices())


def make_mesh(devices=None, axis: str = STRIPE_AXIS) -> Mesh:
    """1-D mesh over the given (default: all) devices."""
    if devices is None:
        devices = local_devices()
    return Mesh(np.asarray(devices), (axis,))


def _col_pad(b: int, n: int, quantum: int = 8) -> int:
    """Pad byte-columns so every device gets an equal, aligned slab."""
    step = n * quantum
    return (b + step - 1) // step * step


def _per_device_fn(kernel: str):
    return gf_matmul_xor if kernel == "xor" else gf_matmul_bits


def _matrix_spec(matrix_op) -> P:
    return P(*(None,) * matrix_op.ndim)


@functools.partial(jax.jit, static_argnums=(2, 3, 4))
def _apply_sharded(matrix_op, data, mesh, axis, kernel):
    fn = _shard_map(
        lambda m, d: _per_device_fn(kernel)(m, d),
        mesh=mesh,
        in_specs=(_matrix_spec(matrix_op), P(None, axis)),
        out_specs=P(None, axis),
    )
    return fn(matrix_op, data)


@functools.partial(jax.jit, static_argnums=(2, 3, 4))
def _apply_stacked_vsharded(matrix_op, stack, mesh, axis, kernel):
    """stack [V, R, B] with V sharded across the mesh -> [V, out, B].

    Each device holds whole slabs ([V/n, R, B] locally) and runs ONE
    column-concatenated GF matmul over them — the V-axis counterpart of
    `_apply_sharded`'s byte-column split. Zero cross-chip communication,
    like the column form: slabs are independent."""
    def local(m, s):
        v, r, b = s.shape
        wide = jnp.swapaxes(s, 0, 1).reshape(r, v * b)
        out = _per_device_fn(kernel)(m, wide)
        return jnp.swapaxes(out.reshape(out.shape[0], v, b), 0, 1)

    fn = _shard_map(
        local,
        mesh=mesh,
        in_specs=(_matrix_spec(matrix_op), P(axis, None, None)),
        out_specs=P(axis, None, None),
    )
    return fn(matrix_op, stack)


@functools.partial(jax.jit, static_argnums=(2, 3, 4, 5))
def _parity_probe(matrix_op, shards, mesh, axis, data_shards, kernel):
    """max over all bytes of (recomputed parity ^ stored parity); 0 iff clean.
    pmax over the mesh axis rides the ICI — cannot wrap, unlike a sum."""
    def local(m, x):
        par = _per_device_fn(kernel)(m, x[:data_shards])
        diff = jnp.max((par ^ x[data_shards:]).astype(jnp.int32))
        return jax.lax.pmax(diff, axis)

    return _shard_map(
        local,
        mesh=mesh,
        in_specs=(_matrix_spec(matrix_op), P(None, axis)),
        out_specs=P(),
    )(matrix_op, shards)


class ShardedCoder:
    """RS codec over a device mesh: same 4-call surface as RSCodecJax, with
    the byte axis sharded across `mesh` (encode/reconstruct are
    embarrassingly parallel across byte columns, SURVEY.md §5.7-5.8).
    """

    def __init__(self, data_shards: int = 10, parity_shards: int = 4,
                 mesh: Mesh | None = None, kernel: str = "xor",
                 geometry=None):
        if data_shards <= 0 or parity_shards < 0:
            raise ValueError("bad geometry")
        if data_shards + parity_shards > 256:
            raise ValueError("at most 256 total shards in GF(256)")
        if kernel not in ("xor", "bits"):
            raise ValueError(f"kernel must be 'xor' or 'bits', got {kernel!r}")
        from ..models import geometry as geom_mod

        self.data_shards = data_shards
        self.parity_shards = parity_shards
        self.total_shards = data_shards + parity_shards
        self.geometry = geom_mod.as_geometry(data_shards, parity_shards,
                                             geometry)
        self.mesh = mesh if mesh is not None else make_mesh()
        self.axis = self.mesh.axis_names[0]
        self._n = self.mesh.devices.size
        # per-device formulation: "xor" (packed-word scheme, rs_xor — the
        # faster one everywhere measured) or "bits" (bitsliced MXU matmul)
        self.kernel = kernel
        self._parity_op = jnp.asarray(
            parity_matrix_op(data_shards, parity_shards, kernel)
            if self.geometry.is_rs
            else geom_parity_op(self.geometry, kernel)
        )

    @property
    def geometry_id(self) -> str:
        return self.geometry.name

    # -- sharding helpers --------------------------------------------------

    def _shard(self, data) -> tuple[jax.Array, int]:
        """Place [rows, B] on the mesh with columns sharded; pad B to the
        device quantum. Device-resident correctly-sharded input passes
        through without a host round-trip."""
        b = data.shape[1]
        padded = _col_pad(b, self._n)
        sharding = NamedSharding(self.mesh, P(None, self.axis))
        if isinstance(data, jax.Array) and padded == b and data.sharding == sharding:
            return data, b
        data = np.asarray(data, dtype=np.uint8)
        if padded != b:
            data = np.pad(data, ((0, 0), (0, padded - b)))
        return jax.device_put(data, sharding), b

    # -- codec surface -----------------------------------------------------

    def encode_parity(self, data) -> jax.Array:
        """data [k, B] -> parity [m, B]; columns computed mesh-parallel."""
        assert data.shape[0] == self.data_shards, data.shape
        arr, b = self._shard(data)
        with _SUBMIT_MU:
            out = _apply_sharded(self._parity_op, arr, self.mesh, self.axis,
                                 self.kernel)
        return out[:, :b]

    def _vshard_wanted(self, v: int) -> bool:
        """V-axis sharding pays when every chip gets at least one whole
        slab; SWFS_EC_MESH_VSHARD=0 pins the ISSUE-3 column split."""
        if v < self._n or self._n <= 1:
            return False
        return os.environ.get("SWFS_EC_MESH_VSHARD", "1").lower() not in (
            "0", "false", "off")

    def _vshard_put(self, stack: np.ndarray) -> tuple[jax.Array, int, int]:
        """Zero-pad V to a device multiple (and B to the kernel's word
        quantum) and place slab-sharded. Zero slabs/columns encode and
        reconstruct to zero bytes and are sliced away, the same argument
        as the scheduler's ragged-tail column padding."""
        v, r, b = stack.shape
        pad_v = -(-v // self._n) * self._n
        pad_b = -(-b // 8) * 8
        if pad_v != v or pad_b != b:
            stack = np.pad(stack, ((0, pad_v - v), (0, 0), (0, pad_b - b)))
        sharding = NamedSharding(self.mesh, P(self.axis, None, None))
        return jax.device_put(stack, sharding), v, b

    def encode_parity_stacked(self, stack) -> jax.Array:
        """stack [V, k, B] -> parity [V, m, B]: the V slabs ride ONE
        mesh-sharded dispatch. With V >= chips (and SWFS_EC_MESH_VSHARD
        on) the V axis itself shards — each chip encodes whole slabs,
        so a big stacked batch fans out with zero cross-chip traffic;
        otherwise columns are laid side by side ([k, V*B]) and split, as
        in ISSUE 3. Both are per-byte-column GF matmuls, so per-slab
        bytes are identical to V separate encode_parity calls either
        way (pinned by tests/test_mesh_dispatch.py)."""
        stack = np.asarray(stack, dtype=np.uint8)
        assert stack.ndim == 3 and stack.shape[1] == self.data_shards, \
            stack.shape
        v, k, b = stack.shape
        if self._vshard_wanted(v):
            arr, v0, b0 = self._vshard_put(stack)
            with _SUBMIT_MU:
                out = _apply_stacked_vsharded(
                    self._parity_op, arr, self.mesh, self.axis, self.kernel)
            return out[:v0, :, :b0]
        wide = np.ascontiguousarray(
            stack.transpose(1, 0, 2).reshape(k, v * b))
        parity = self.encode_parity(wide)
        return jnp.swapaxes(
            parity.reshape(self.parity_shards, v, b), 0, 1)

    def reconstruct_stacked_vsharded(self, present_ids, stack,
                                     data_only: bool = False, want=None):
        """Uniform-width survivor stacks [V, P, B] -> (missing_ids,
        [V, len(missing), B]) with the V axis sharded across chips —
        every chip reconstructs whole slabs through the same fused
        column-permuted matrix (same GF math as reconstruct_stacked,
        including the `want` minimal-read form, so bytes are identical
        slab for slab)."""
        present_ids = tuple(present_ids)
        stack = np.asarray(stack, dtype=np.uint8)
        assert stack.ndim == 3 and stack.shape[1] == len(present_ids), \
            stack.shape
        limit = self.data_shards if data_only else self.total_shards
        if want is not None or not self.geometry.is_rs:
            missing = geom_targets_for(self.geometry, present_ids,
                                       data_only, want)
            op_np = (geom_stacked_op(self.geometry, present_ids, missing,
                                     self.kernel) if missing else None)
        else:
            missing, op_np = fused_reconstruct_stacked_op(
                self.data_shards, self.parity_shards, present_ids, limit,
                self.kernel)
        if not missing:
            return (), jnp.zeros(
                (stack.shape[0], 0, stack.shape[2]), jnp.uint8)
        if stack.shape[0] == 0:  # V=0: nothing to shard, shape contract
            return missing, jnp.zeros(
                (0, len(missing), stack.shape[2]), jnp.uint8)
        arr, v0, b0 = self._vshard_put(stack)
        with _SUBMIT_MU:
            out = _apply_stacked_vsharded(
                jnp.asarray(op_np), arr, self.mesh, self.axis, self.kernel)
        return missing, out[:v0, :, :b0]

    # -- per-chip (device-affine) entry points ------------------------------
    #
    # The EC dispatch scheduler's per-chip lanes (ops/dispatch.py) flush
    # each chip's queued slabs as ONE single-device stacked dispatch
    # pinned to that chip — no shard_map, no rendezvous, every chip's
    # dispatch queue fills independently.

    def placement_devices(self) -> list:
        """The mesh's devices, in mesh order — the chips the dispatch
        scheduler round-robins encode slabs (and pins survivor sets) to."""
        return list(self.mesh.devices.flat)

    @property
    def prefers_vstack(self) -> bool:
        """Tells the dispatch scheduler (ISSUE 12) to keep [V, k, B]
        stacks for this coder's non-chip lanes: a multi-device mesh
        shards WHOLE slabs across chips (V-axis, ISSUE 5), which the
        column-compact wide packing would flatten away."""
        return self._n > 1

    def _chip_codec(self):
        # lazily-built single-device codec reused for every chip: jit
        # caches per (shape, device), so chips don't trample each other
        impl = self.__dict__.get("_chip_impl")
        if impl is None:
            from ..ops.rs_jax import RSCodecJax

            impl = self.__dict__["_chip_impl"] = RSCodecJax(
                self.data_shards, self.parity_shards,
                geometry=self.geometry)
        return impl

    def encode_parity_stacked_on(self, stack, device) -> jax.Array:
        """stack [V, k, B] encoded in one stacked dispatch pinned to
        `device` (bytes identical to encode_parity_stacked — columns are
        independent of where they're computed)."""
        return self._chip_codec().encode_parity_stacked(stack,
                                                        device=device)

    def encode_parity_on(self, data, device) -> jax.Array:
        """Wide/2-D [k, W] encode pinned to `device` — the arena-packed
        chip-lane form (ISSUE 12): the scheduler lays a whole flush's
        slabs side by side along the column axis and this dispatches
        them as ONE launch with no stacked [V, k, B] copy at all. The
        committed input buffer is donated to XLA (rs_jax donation
        plumbing), so per-flush device scratch is the payload bytes."""
        return self._chip_codec().encode_parity(data, device=device)

    def reconstruct_stacked_on(self, present_ids, stacked,
                               data_only: bool = False, device=None,
                               want=None):
        """Pre-stacked survivors [P, B] reconstructed on `device`; the
        survivor set's fused decode matrix is cached device-resident
        (ops/rs_jax._op_on_device, LRU)."""
        return self._chip_codec().reconstruct_stacked(
            present_ids, stacked, data_only=data_only, device=device,
            want=want)

    def encode(self, shards) -> jax.Array:
        """[k, B] data or [total, B] shards -> all [total, B] shards with
        parity rows (re)computed."""
        shards = np.asarray(shards, dtype=np.uint8)
        assert shards.shape[0] in (self.data_shards, self.total_shards), shards.shape
        parity = self.encode_parity(shards[: self.data_shards])
        return jnp.concatenate(
            [jnp.asarray(shards[: self.data_shards]), parity], axis=0
        )

    def reconstruct(self, shards) -> dict[int, jax.Array]:
        return self._reconstruct(shards, data_only=False)

    def reconstruct_data(self, shards) -> dict[int, jax.Array]:
        return self._reconstruct(shards, data_only=True)

    def _reconstruct(self, shards, data_only: bool) -> dict[int, jax.Array]:
        present = (
            dict(shards)
            if isinstance(shards, dict)
            else {i: s for i, s in enumerate(shards) if s is not None}
        )
        limit = self.data_shards if data_only else self.total_shards
        missing = tuple(i for i in range(limit) if i not in present)
        if not missing:
            return {}
        if not self.geometry.is_rs:
            pres = tuple(sorted(present.keys()))
            op_np = geom_stacked_op(self.geometry, pres, missing,
                                    self.kernel)
            used = pres
        else:
            # one fused [missing, k] matmul — parity rows are folded
            # through the decode matrix host-side
            # (rs_jax.fused_reconstruct_matrix), so no second mesh-wide
            # encode dispatch
            op_np, used = fused_reconstruct_op(
                self.data_shards, self.parity_shards,
                tuple(sorted(present.keys())), missing, self.kernel)
        fused_op = jnp.asarray(op_np)
        stacked = np.stack([np.asarray(present[i], np.uint8) for i in used])
        arr, b = self._shard(stacked)
        with _SUBMIT_MU:
            out_arr = _apply_sharded(fused_op, arr, self.mesh, self.axis,
                                     self.kernel)
        return {i: out_arr[j][:b] for j, i in enumerate(missing)}

    def reconstruct_stacked(self, present_ids, stacked,
                            data_only: bool = False, want=None):
        """Pre-stacked survivors [P, B] in caller row order ->
        (missing_ids, [missing, B]) — the column-permuted fused matmul
        sharded over the mesh, no re-stack/gather (same contract as
        RSCodecJax.reconstruct_stacked, including the ISSUE-11 `want`
        minimal-read form)."""
        present_ids = tuple(present_ids)
        assert stacked.shape[0] == len(present_ids), stacked.shape
        limit = self.data_shards if data_only else self.total_shards
        if want is not None or not self.geometry.is_rs:
            missing = geom_targets_for(self.geometry, present_ids,
                                       data_only, want)
            op_np = (geom_stacked_op(self.geometry, present_ids, missing,
                                     self.kernel) if missing else None)
        else:
            missing, op_np = fused_reconstruct_stacked_op(
                self.data_shards, self.parity_shards, present_ids, limit,
                self.kernel)
        if not missing:
            return (), jnp.zeros((0, stacked.shape[1]), jnp.uint8)
        # hand the buffer to _shard untouched: a device-resident,
        # correctly-sharded array must keep its fast path (np.asarray
        # here would be a device->host->device round trip)
        arr, b = self._shard(stacked)
        with _SUBMIT_MU:
            out_arr = _apply_sharded(jnp.asarray(op_np), arr, self.mesh,
                                     self.axis, self.kernel)
        return missing, out_arr[:, :b]

    def verify(self, shards) -> bool:
        shards = np.asarray(shards, dtype=np.uint8)
        return int(self.parity_probe(shards)) == 0

    # -- fleet integrity collective ---------------------------------------

    def parity_probe(self, shards) -> jax.Array:
        """Scalar 0 iff stored parity matches recomputed parity, else the max
        differing byte value — an all-chip integrity scrub using a pmax
        collective over ICI (analogue of volume.check.disk's replica digest
        comparison, SURVEY.md §5.3)."""
        shards = np.asarray(shards, dtype=np.uint8)
        assert shards.shape[0] == self.total_shards, shards.shape
        arr, _ = self._shard(shards)
        with _SUBMIT_MU:
            return _parity_probe(
                self._parity_op, arr, self.mesh, self.axis,
                self.data_shards, self.kernel
            )

    # kept as the historical name used by the dry-run driver
    parity_checksum = parity_probe
