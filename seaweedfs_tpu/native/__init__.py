"""Native (C++) runtime components: the volume-server HTTP data plane."""

from .dataplane import NativeDataPlane, native_available

__all__ = ["NativeDataPlane", "native_available"]
