"""Native (C++) runtime components: the volume-server HTTP data plane."""

from .dataplane import NativeDataPlane, NativeFilerPlane, native_available

__all__ = ["NativeDataPlane", "NativeFilerPlane", "native_available"]
