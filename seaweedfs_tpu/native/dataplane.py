"""ctypes loader + wrapper for the C++ volume data plane (dataplane.cpp).

The plane binds the volume server's public port and serves needle
GET/PUT/DELETE from C++ worker threads; everything else is 307-redirected
to the Python listener. Volumes are registered per-vid; all Python-side
mutations to a registered volume MUST funnel through append_record /
delete (one writer authority — the C++ lock) and reads through read_blob.

Built on first use with g++, mirroring ops/rs_native.py.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading

_NATIVE_DIR = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_NATIVE_DIR, "dataplane.cpp")
_SO = os.path.join(_NATIVE_DIR, "libswfs_dataplane.so")

_lib = None
_lib_lock = threading.Lock()


def _build() -> None:
    cmd = ["g++", "-O2", "-shared", "-fPIC", "-std=c++17", "-pthread",
           _SRC, "-o", _SO]
    subprocess.run(cmd, check=True, capture_output=True)


def load_library() -> ctypes.CDLL:
    global _lib
    with _lib_lock:
        if _lib is not None:
            return _lib
        if (not os.path.exists(_SO)
                or os.path.getmtime(_SO) < os.path.getmtime(_SRC)):
            _build()
        lib = ctypes.CDLL(_SO)
        u8p = ctypes.POINTER(ctypes.c_uint8)
        lib.swdp_start.argtypes = [ctypes.c_char_p, ctypes.c_int,
                                   ctypes.c_int, ctypes.c_int]
        lib.swdp_start.restype = ctypes.c_int
        lib.swdp_stop.argtypes = [ctypes.c_int]
        lib.swdp_stop.restype = None
        lib.swdp_add_volume.argtypes = [ctypes.c_int, ctypes.c_uint32,
                                        ctypes.c_char_p, ctypes.c_char_p,
                                        ctypes.c_int, ctypes.c_int]
        lib.swdp_add_volume.restype = ctypes.c_int
        lib.swdp_remove_volume.argtypes = [ctypes.c_int, ctypes.c_uint32]
        lib.swdp_remove_volume.restype = ctypes.c_int
        lib.swdp_reload_volume.argtypes = [ctypes.c_int, ctypes.c_uint32]
        lib.swdp_reload_volume.restype = ctypes.c_int
        lib.swdp_set_writable.argtypes = [ctypes.c_int, ctypes.c_uint32,
                                          ctypes.c_int]
        lib.swdp_set_writable.restype = ctypes.c_int
        lib.swdp_append_record.argtypes = [
            ctypes.c_int, ctypes.c_uint32, ctypes.c_uint64, u8p,
            ctypes.c_int64, ctypes.c_int32, ctypes.c_int64,
            ctypes.POINTER(ctypes.c_uint64)]
        lib.swdp_append_record.restype = ctypes.c_int64
        lib.swdp_read.argtypes = [ctypes.c_int, ctypes.c_uint32,
                                  ctypes.c_uint64, ctypes.POINTER(u8p)]
        lib.swdp_read.restype = ctypes.c_int64
        lib.swdp_free.argtypes = [u8p]
        lib.swdp_free.restype = None
        lib.swdp_volume_stats.argtypes = [ctypes.c_int, ctypes.c_uint32] + \
            [ctypes.POINTER(ctypes.c_int64)] * 4 + \
            [ctypes.POINTER(ctypes.c_uint64), ctypes.POINTER(ctypes.c_int64)]
        lib.swdp_volume_stats.restype = ctypes.c_int
        lib.swdp_request_count.argtypes = [ctypes.c_int]
        lib.swdp_request_count.restype = ctypes.c_uint64
        lib.swdp_sendfile_count.argtypes = [ctypes.c_int]
        lib.swdp_sendfile_count.restype = ctypes.c_uint64
        lib.swdp_set_zerocopy_min.argtypes = [ctypes.c_int,
                                              ctypes.c_int64]
        lib.swdp_set_zerocopy_min.restype = ctypes.c_int
        lib.swdp_bench.argtypes = [ctypes.c_char_p, ctypes.c_int,
                                   ctypes.c_int,
                                   ctypes.POINTER(ctypes.c_char_p),
                                   ctypes.c_int, u8p, ctypes.c_int64,
                                   ctypes.POINTER(ctypes.c_int64)]
        lib.swdp_bench.restype = ctypes.c_int64
        lib.swfp_start.argtypes = [ctypes.c_char_p, ctypes.c_int,
                                   ctypes.c_int, ctypes.c_int,
                                   ctypes.c_char_p, ctypes.c_char_p,
                                   ctypes.c_int64]
        lib.swfp_start.restype = ctypes.c_int
        lib.swfp_stop.argtypes = [ctypes.c_int]
        lib.swfp_stop.restype = None
        lib.swfp_add_lease.argtypes = [ctypes.c_int, ctypes.c_uint32,
                                       ctypes.c_uint64, ctypes.c_uint32,
                                       ctypes.c_uint32]
        lib.swfp_add_lease.restype = ctypes.c_int
        lib.swfp_lease_remaining.argtypes = [ctypes.c_int]
        lib.swfp_lease_remaining.restype = ctypes.c_uint64
        lib.swfp_invalidate.argtypes = [ctypes.c_int, ctypes.c_char_p]
        lib.swfp_invalidate.restype = ctypes.c_int
        lib.swfp_invalidate_prefix.argtypes = [ctypes.c_int, ctypes.c_char_p]
        lib.swfp_invalidate_prefix.restype = ctypes.c_int
        lib.swfp_stats.argtypes = [ctypes.c_int] + \
            [ctypes.POINTER(ctypes.c_uint64)] * 4
        lib.swfp_stats.restype = ctypes.c_int
        lib.swfp_disable_log.argtypes = [ctypes.c_int]
        lib.swfp_disable_log.restype = ctypes.c_int
        _lib = lib
        return _lib


def bench_loop(addr: str, fids: list[str], payload: bytes | None,
               lat_out=None) -> int:
    """Run the native keepalive PUT/GET loop over `fids` against addr
    ("host:port"). payload=None means GET. Returns the 2xx count; fills
    lat_out (ctypes int64 array) with per-request ns latencies. Releases
    the GIL for the whole loop."""
    lib = load_library()
    host, _, port = addr.partition(":")
    arr = (ctypes.c_char_p * len(fids))(*[f.encode() for f in fids])
    if payload is None:
        body, blen, is_put = None, 0, 0
    else:
        body = (ctypes.c_uint8 * len(payload)).from_buffer_copy(payload)
        blen, is_put = len(payload), 1
    ok = lib.swdp_bench(host.encode(), int(port), is_put, arr, len(fids),
                        body, blen, lat_out)
    if ok < 0:
        raise IOError(f"bench loop vs {addr}: errno {-ok}")
    return int(ok)


def native_available() -> bool:
    try:
        load_library()
        return True
    except Exception:
        return False


class NativeDataPlane:
    """One C++ HTTP plane instance (multiple may coexist per process)."""

    def __init__(self, bind_ip: str, port: int, redirect_port: int,
                 nthreads: int = 8):
        self.lib = load_library()
        self.port = port
        self.redirect_port = redirect_port
        self.plane_id = self.lib.swdp_start(bind_ip.encode(), port,
                                            redirect_port, nthreads)
        if self.plane_id <= 0:
            raise OSError(
                f"native data plane failed to start: {self.plane_id}")
        # zero-copy serving gate (ISSUE 9): SWFS_ZEROCOPY=0 disables the
        # sendfile path (A/B OFF arm); any other integer is the minimum
        # body size that rides it (default 4096 — below that, one pread
        # beats two preads + sendfile)
        zc = os.environ.get("SWFS_ZEROCOPY", "")
        if zc.lower() in ("0", "false", "off"):
            self.lib.swdp_set_zerocopy_min(self.plane_id, -1)
        elif zc.isdigit() and int(zc) > 1:
            self.lib.swdp_set_zerocopy_min(self.plane_id, int(zc))

    def stop(self) -> None:
        if self.plane_id > 0:
            self.lib.swdp_stop(self.plane_id)
            self.plane_id = 0

    # -- volume registry ---------------------------------------------------

    def add_volume(self, vid: int, dat_path: str, idx_path: str,
                   version: int, writable: bool) -> None:
        rc = self.lib.swdp_add_volume(self.plane_id, vid, dat_path.encode(),
                                      idx_path.encode(), version,
                                      1 if writable else 0)
        if rc != 0:
            raise OSError(f"add_volume {vid}: {rc}")

    def remove_volume(self, vid: int) -> None:
        self.lib.swdp_remove_volume(self.plane_id, vid)

    def reload_volume(self, vid: int) -> bool:
        """Reopen a volume's files after an external swap (vacuum
        commit). On failure the C++ side already dropped its handles and
        map; remove the volume from the plane too (requests 307 to
        python, which is correct, instead of 404ing on a cleared map)
        and report False so the caller detaches."""
        rc = self.lib.swdp_reload_volume(self.plane_id, vid)
        if rc >= 0:
            return True
        from ..utils import glog

        self.lib.swdp_remove_volume(self.plane_id, vid)
        glog.error(f"native plane reload of volume {vid} failed "
                   f"(errno {-rc}); volume served by python")
        return False

    def set_writable(self, vid: int, writable: bool) -> None:
        self.lib.swdp_set_writable(self.plane_id, vid, 1 if writable else 0)

    # -- mutation funnel ---------------------------------------------------

    def append_record(self, vid: int, key: int, blob: bytes, idx_size: int,
                      ns_off: int) -> tuple[int, int]:
        """Append a prebuilt record; C++ stamps appendAtNs at ns_off.
        -> (byte_offset, append_at_ns)."""
        buf = (ctypes.c_uint8 * len(blob)).from_buffer_copy(blob)
        ns = ctypes.c_uint64(0)
        off = self.lib.swdp_append_record(self.plane_id, vid, key, buf,
                                          len(blob), idx_size, ns_off,
                                          ctypes.byref(ns))
        if off < 0:
            raise IOError(f"native append vid={vid}: errno {-off}")
        return int(off), int(ns.value)

    def read_blob(self, vid: int, key: int) -> bytes | None:
        out = ctypes.POINTER(ctypes.c_uint8)()
        n = self.lib.swdp_read(self.plane_id, vid, key, ctypes.byref(out))
        if n < 0:
            raise IOError(f"native read vid={vid}: errno {-n}")
        if n == 0:
            return None
        try:
            return ctypes.string_at(out, n)
        finally:
            self.lib.swdp_free(out)

    def volume_stats(self, vid: int) -> dict | None:
        fc, fb, dc, db = (ctypes.c_int64() for _ in range(4))
        mk = ctypes.c_uint64()
        ds = ctypes.c_int64()
        rc = self.lib.swdp_volume_stats(
            self.plane_id, vid, ctypes.byref(fc), ctypes.byref(fb), ctypes.byref(dc),
            ctypes.byref(db), ctypes.byref(mk), ctypes.byref(ds))
        if rc != 0:
            return None
        return {"file_count": fc.value, "file_bytes": fb.value,
                "del_count": dc.value, "del_bytes": db.value,
                "max_key": mk.value, "dat_size": ds.value}

    def request_count(self) -> int:
        return int(self.lib.swdp_request_count(self.plane_id))

    def sendfile_count(self) -> int:
        """GETs served zero-copy via sendfile(2) since plane start."""
        return int(self.lib.swdp_sendfile_count(self.plane_id))

    def set_zerocopy_min(self, min_bytes: int) -> None:
        """Minimum body size for the sendfile path; -1 disables it."""
        self.lib.swdp_set_zerocopy_min(self.plane_id, min_bytes)


class NativeFilerPlane:
    """C++ filer hot plane: whole-object PUT/GET under `prefix` served
    straight off a co-located volume plane's registry; everything else
    307s to the python filer at redirect_port. Entry metadata lands in
    `log_path`, which FilerServer absorbs into the real store."""

    def __init__(self, bind_ip: str, port: int, redirect_port: int,
                 volume_plane_id: int, log_path: str,
                 prefix: str = "/buckets/", max_body: int = 4 << 20):
        self.lib = load_library()
        self.port = port
        self.redirect_port = redirect_port
        self.log_path = log_path
        self.prefix = prefix
        self.plane_id = self.lib.swfp_start(
            bind_ip.encode(), port, redirect_port, volume_plane_id,
            log_path.encode(), prefix.encode(), max_body)
        if self.plane_id <= 0:
            raise OSError(
                f"native filer plane failed to start: {self.plane_id}")

    def stop(self) -> None:
        if self.plane_id > 0:
            self.lib.swfp_stop(self.plane_id)
            self.plane_id = 0

    def add_lease(self, vid: int, base_key: int, cookie: int,
                  count: int) -> None:
        rc = self.lib.swfp_add_lease(self.plane_id, vid, base_key, cookie,
                                     count)
        if rc != 0:
            raise OSError(f"add_lease: {rc}")

    def lease_remaining(self) -> int:
        return int(self.lib.swfp_lease_remaining(self.plane_id))

    def disable_log(self) -> None:
        """Stop acking native PUTs (redirect them to python) — used when
        the absorber can no longer make hot-log metadata durable."""
        self.lib.swfp_disable_log(self.plane_id)

    def invalidate(self, path: str) -> None:
        self.lib.swfp_invalidate(self.plane_id, path.encode())

    def invalidate_prefix(self, path: str) -> None:
        self.lib.swfp_invalidate_prefix(self.plane_id, path.encode())

    def stats(self) -> dict:
        vals = [ctypes.c_uint64() for _ in range(4)]
        self.lib.swfp_stats(self.plane_id, *(ctypes.byref(v) for v in vals))
        return {"requests": vals[0].value, "native_puts": vals[1].value,
                "native_gets": vals[2].value, "redirects": vals[3].value}
