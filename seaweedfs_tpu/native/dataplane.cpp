// Native volume-server data plane: HTTP needle reads/writes in C++.
//
// The reference's data plane is Go's net/http + a compiled storage engine
// (/root/reference/weed/server/volume_server_handlers_read.go:31,
// volume_server_handlers_write.go:18, weed/storage/needle/needle_write.go:20).
// A Python per-request handler costs ~1-3ms of interpreter time; this plane
// serves the hot paths — GET/PUT/DELETE of /vid,fid — from a C++ thread pool
// with keepalive, and 307-redirects everything else (status pages, EC
// volumes, range/conditional/image requests, multipart) to the Python
// listener, which keeps full behavioral coverage.
//
// On-disk formats are bit-identical to the Python engine (and the
// reference): needle v1/v2/v3 records (needle.py, needle_write.go:20-113),
// append-only .idx entries id8+offset4+size4 big-endian in units of 8
// bytes, CRC32-Castagnoli data checksums. Python-side mutations funnel
// through swdp_append_record/swdp_delete so there is exactly one writer
// authority per volume (see native/dataplane.py).
//
// Exported C ABI (ctypes):
//   swdp_start / swdp_stop
//   swdp_add_volume / swdp_remove_volume / swdp_reload_volume
//   swdp_set_writable
//   swdp_append_record / swdp_delete / swdp_read  (+ swdp_free)
//   swdp_volume_stats
//   swdp_request_count

#include <arpa/inet.h>
#include <netdb.h>
#include <cerrno>
#include <cstdarg>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <ctime>
#include <fcntl.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <sys/sendfile.h>
#include <sys/socket.h>
#include <sys/stat.h>
#include <sys/types.h>
#include <unistd.h>

#include <atomic>
#include <chrono>
#include <condition_variable>
#include <deque>
#include <memory>
#include <mutex>
#include <shared_mutex>
#include <string>
#include <thread>
#include <unordered_map>
#include <vector>

namespace {

// ---------------------------------------------------------------- crc32c --

uint32_t crc_table[8][256];

void crc_init() {
  const uint32_t poly = 0x82F63B78u;  // reversed Castagnoli
  for (uint32_t i = 0; i < 256; i++) {
    uint32_t c = i;
    for (int k = 0; k < 8; k++) c = (c & 1) ? (c >> 1) ^ poly : c >> 1;
    crc_table[0][i] = c;
  }
  for (uint32_t i = 0; i < 256; i++)
    for (int t = 1; t < 8; t++)
      crc_table[t][i] =
          (crc_table[t - 1][i] >> 8) ^ crc_table[0][crc_table[t - 1][i] & 0xFF];
}

uint32_t crc32c(const uint8_t* p, size_t n, uint32_t crc = 0) {
  crc = ~crc;
  while (n >= 8) {
    crc ^= (uint32_t)p[0] | ((uint32_t)p[1] << 8) | ((uint32_t)p[2] << 16) |
           ((uint32_t)p[3] << 24);
    uint32_t hi = (uint32_t)p[4] | ((uint32_t)p[5] << 8) |
                  ((uint32_t)p[6] << 16) | ((uint32_t)p[7] << 24);
    crc = crc_table[7][crc & 0xFF] ^ crc_table[6][(crc >> 8) & 0xFF] ^
          crc_table[5][(crc >> 16) & 0xFF] ^ crc_table[4][crc >> 24] ^
          crc_table[3][hi & 0xFF] ^ crc_table[2][(hi >> 8) & 0xFF] ^
          crc_table[1][(hi >> 16) & 0xFF] ^ crc_table[0][hi >> 24];
    p += 8;
    n -= 8;
  }
  while (n--) crc = crc_table[0][(crc ^ *p++) & 0xFF] ^ (crc >> 8);
  return ~crc;
}

// legacy CRC.Value() transform accepted on reads (crc.py crc_value_legacy,
// reference crc.go:25-27): rotate + magic add, kept for old volumes
uint32_t crc_legacy(uint32_t v) {
  return (((v >> 15) | (v << 17)) + 0xA282EAD8u) & 0xFFFFFFFFu;
}

// ------------------------------------------------------------- constants --

constexpr int kHeaderSize = 16;    // cookie4 + id8 + size4
constexpr int kChecksumSize = 4;
constexpr int kTimestampSize = 8;  // v3 appendAtNs
constexpr int kPad = 8;
constexpr int32_t kTombstone = -1;
constexpr int64_t kMaxVolumeSize = 32LL * 1024 * 1024 * 1024;

constexpr uint8_t kFlagCompressed = 0x01;
constexpr uint8_t kFlagHasName = 0x02;
constexpr uint8_t kFlagHasMime = 0x04;
constexpr uint8_t kFlagHasLastModified = 0x08;
constexpr uint8_t kFlagHasTtl = 0x10;
constexpr uint8_t kFlagHasPairs = 0x20;

int pad_len(int32_t size, int version) {
  int64_t body = kHeaderSize + (int64_t)size + kChecksumSize;
  if (version == 3) body += kTimestampSize;
  return kPad - (int)(body % kPad);  // always 1..8 (types.py padding_length)
}

int64_t actual_size(int32_t size, int version) {
  int64_t body = kHeaderSize + (int64_t)size + kChecksumSize;
  if (version == 3) body += kTimestampSize;
  return body + pad_len(size, version);
}

void put_u32(uint8_t* p, uint32_t v) {
  p[0] = v >> 24; p[1] = v >> 16; p[2] = v >> 8; p[3] = v;
}
void put_u64(uint8_t* p, uint64_t v) {
  for (int i = 0; i < 8; i++) p[i] = (uint8_t)(v >> (56 - 8 * i));
}
uint32_t get_u32(const uint8_t* p) {
  return ((uint32_t)p[0] << 24) | ((uint32_t)p[1] << 16) |
         ((uint32_t)p[2] << 8) | p[3];
}
uint64_t get_u64(const uint8_t* p) {
  uint64_t v = 0;
  for (int i = 0; i < 8; i++) v = (v << 8) | p[i];
  return v;
}

// --------------------------------------------------------------- volumes --

struct NeedleValue {
  uint32_t stored_offset;  // units of 8 bytes
  int32_t size;            // body size field; <=0 invalid
};

// Shared descriptor ownership: readers copy the shared_ptr under the
// volume mutex (no syscall) and pread after unlocking; a reload
// (vacuum commit) swaps in a new owner while in-flight readers keep the
// pre-reload inode alive. This replaces the old dup()+close() pair that
// cost two syscalls on EVERY GET.
struct FdOwner {
  int fd = -1;
  explicit FdOwner(int f) : fd(f) {}
  FdOwner(const FdOwner&) = delete;
  FdOwner& operator=(const FdOwner&) = delete;
  ~FdOwner() {
    if (fd >= 0) close(fd);
  }
};

struct Volume {
  uint32_t vid = 0;
  std::string dat_path, idx_path;
  std::shared_ptr<FdOwner> dat, idx;
  int version = 3;
  bool writable = true;
  std::mutex mu;  // guards appends + map mutation + counters
  std::unordered_map<uint64_t, NeedleValue> map;
  int64_t idx_loaded = 0;  // bytes of .idx reflected in `map`
  int64_t dat_size = 0;
  int64_t idx_size = 0;  // append offset: tracked, not lseek'd per PUT
  uint64_t last_append_ns = 0;
  uint64_t max_key = 0;
  int64_t file_count = 0, file_bytes = 0;
  int64_t del_count = 0, del_bytes = 0;

  int dat_fd() const { return dat ? dat->fd : -1; }
  int idx_fd() const { return idx ? idx->fd : -1; }

  // Apply one idx entry to the in-memory map (NeedleMap._load semantics).
  void apply(uint64_t key, uint32_t off, int32_t size) {
    if (key > max_key) max_key = key;
    file_count++;
    auto it = map.find(key);
    // size == 0 is a live empty file (python Volume.write_needle keeps it
    // in its map); only off==0 / negative size (tombstone) delete
    if (off != 0 && size >= 0) {
      if (it != map.end() && it->second.stored_offset != 0 &&
          it->second.size >= 0) {
        del_count++;
        del_bytes += it->second.size;
      }
      map[key] = NeedleValue{off, size};
      if (size > 0) file_bytes += size;
    } else {
      del_count++;
      if (it != map.end()) {
        if (it->second.size > 0) del_bytes += it->second.size;
        map.erase(it);
      }
    }
  }

  // Read .idx entries in [idx_loaded, EOF) into the map. mu held.
  bool catchup() {
    struct stat st;
    if (fstat(idx_fd(), &st) != 0) return false;
    if (st.st_size > idx_size) idx_size = st.st_size;
    if (st.st_size <= idx_loaded) return true;
    int64_t want = st.st_size - idx_loaded;
    std::vector<uint8_t> buf(want);
    int64_t got = pread(idx_fd(), buf.data(), want, idx_loaded);
    if (got < 0) return false;
    got -= got % 16;
    for (int64_t i = 0; i + 16 <= got; i += 16)
      apply(get_u64(&buf[i]), get_u32(&buf[i + 8]),
            (int32_t)get_u32(&buf[i + 12]));
    idx_loaded += got;
    return true;
  }

  bool open_files() {
    int dfd = open(dat_path.c_str(), O_RDWR | O_CREAT, 0644);
    int ifd = open(idx_path.c_str(), O_RDWR | O_CREAT, 0644);
    if (dfd < 0 || ifd < 0) {
      if (dfd >= 0) close(dfd);
      if (ifd >= 0) close(ifd);
      return false;
    }
    // old owners (if any) release when the last in-flight reader drops
    dat = std::make_shared<FdOwner>(dfd);
    idx = std::make_shared<FdOwner>(ifd);
    struct stat st;
    if (fstat(dfd, &st) == 0) dat_size = st.st_size;
    if (fstat(ifd, &st) == 0) idx_size = st.st_size;
    map.clear();
    idx_loaded = 0;
    file_count = file_bytes = del_count = del_bytes = 0;
    max_key = 0;
    return catchup();
  }

  uint64_t next_append_ns() {
    struct timespec ts;
    clock_gettime(CLOCK_REALTIME, &ts);
    uint64_t now = (uint64_t)ts.tv_sec * 1000000000ull + ts.tv_nsec;
    if (now <= last_append_ns) now = last_append_ns + 1;
    last_append_ns = now;
    return now;
  }

  // Append a fully-built record; write the idx entry; update the map.
  // ns_off >= 0: stamp a fresh monotonic appendAtNs into blob[ns_off..+8).
  // idx_size: size field for the idx entry (kTombstone for deletes).
  // Returns byte offset in .dat, or -1. mu held.
  int64_t append(uint8_t* blob, int64_t len, uint64_t key, int32_t ent_size,
                 int64_t ns_off, uint64_t* ns_out) {
    // dat_size/idx_size are authoritative (single writer under mu, both
    // re-derived on open/reload): appends cost two pwrites, not the old
    // two lseeks + two pwrites — syscalls dominate this hot path on the
    // sandboxed kernels this serves.
    int64_t off = dat_size;
    if (off % kPad) {  // realign a torn tail (volume.py _append_record)
      off += kPad - (off % kPad);
      if (ftruncate(dat_fd(), off) != 0) return -1;
      dat_size = off;
    }
    if (off + len > kMaxVolumeSize) { errno = EFBIG; return -1; }
    if (ns_off >= 0) {
      uint64_t ns = next_append_ns();
      put_u64(blob + ns_off, ns);
      if (ns_out) *ns_out = ns;
    }
    int64_t wr = pwrite(dat_fd(), blob, len, off);
    if (wr != len) {
      (void)!ftruncate(dat_fd(), off);
      return -1;
    }
    dat_size = off + len;
    uint8_t ent[16];
    put_u64(ent, key);
    put_u32(ent + 8, (uint32_t)(off / kPad));
    put_u32(ent + 12, (uint32_t)ent_size);
    int64_t ioff = idx_size;
    if (pwrite(idx_fd(), ent, 16, ioff) != 16) {
      // an acknowledged-but-unindexed needle would 404 forever: undo the
      // .dat append and fail the request instead
      (void)!ftruncate(idx_fd(), ioff);
      (void)!ftruncate(dat_fd(), off);
      dat_size = off;
      return -1;
    }
    idx_size = ioff + 16;
    if (ioff == idx_loaded) {
      apply(key, (uint32_t)(off / kPad), ent_size);
      idx_loaded += 16;
    } else {
      catchup();
    }
    return off;
  }
};

struct Registry {
  std::shared_mutex mu;
  std::unordered_map<uint32_t, std::shared_ptr<Volume>> vols;

  std::shared_ptr<Volume> find(uint32_t vid) {
    std::shared_lock<std::shared_mutex> l(mu);
    auto it = vols.find(vid);
    return it == vols.end() ? nullptr : it->second;
  }
};

// ------------------------------------------------------ needle build/read --

struct ParsedNeedle {
  uint32_t cookie = 0;
  uint64_t id = 0;
  int32_t size = 0;
  const uint8_t* data = nullptr;
  uint32_t data_len = 0;
  uint8_t flags = 0;
  const uint8_t* mime = nullptr;
  uint8_t mime_len = 0;
  uint64_t last_modified = 0;
  uint32_t checksum = 0;
};

// Parse a v2/v3 record blob (needle.py from_bytes). Returns false on
// structural error.
bool parse_record(const uint8_t* b, int64_t len, int version,
                  ParsedNeedle* out) {
  if (len < kHeaderSize) return false;
  out->cookie = get_u32(b);
  out->id = get_u64(b + 4);
  out->size = (int32_t)get_u32(b + 12);
  int32_t size = out->size;
  if (size < 0 || kHeaderSize + (int64_t)size + kChecksumSize > len)
    return false;
  if (version == 1) {
    out->data = b + kHeaderSize;
    out->data_len = size;
  } else {
    const uint8_t* p = b + kHeaderSize;
    const uint8_t* end = p + size;
    if (p + 4 > end) { out->data_len = 0; }
    else {
      uint32_t dlen = get_u32(p);
      p += 4;
      if (p + dlen > end) return false;
      out->data = p;
      out->data_len = dlen;
      p += dlen;
      if (p < end) out->flags = *p++;
      if (p < end && (out->flags & kFlagHasName)) {
        uint8_t nl = *p++;
        p += nl;  // name skipped (not served in fast-path headers)
      }
      if (p < end && (out->flags & kFlagHasMime)) {
        out->mime_len = *p++;
        out->mime = p;
        p += out->mime_len;
      }
      if (p + 5 <= end && (out->flags & kFlagHasLastModified)) {
        uint64_t lm = 0;
        for (int i = 0; i < 5; i++) lm = (lm << 8) | p[i];
        out->last_modified = lm;
        p += 5;
      }
      if (p > end) return false;
    }
  }
  if (size > 0)
    out->checksum = get_u32(b + kHeaderSize + size);
  return true;
}

// ------------------------------------------------------------ HTTP plumb --

struct Plane {
  int id = 0;
  Registry reg;
  std::atomic<uint64_t> requests{0};
  // zero-copy GET serving (ISSUE 9): bodies at least zerocopy_min bytes
  // go disk->socket via sendfile(2); -1 disables the path entirely
  std::atomic<uint64_t> sendfiles{0};
  std::atomic<int64_t> zerocopy_min{4096};
  int listen_fd = -1;
  int port = 0;
  int redirect_port = 0;
  std::atomic<bool> stop{false};
  std::thread acceptor;
  std::atomic<int> live_conns{0};
};

std::mutex g_planes_mu;
std::unordered_map<int, std::shared_ptr<Plane>> g_planes;
int g_next_plane = 1;

std::shared_ptr<Plane> plane_of(int id) {
  std::lock_guard<std::mutex> l(g_planes_mu);
  auto it = g_planes.find(id);
  return it == g_planes.end() ? nullptr : it->second;
}

// Look a volume up across planes by (plane, vid).
std::shared_ptr<Volume> find_volume(int plane_id, uint32_t vid) {
  auto pl = plane_of(plane_id);
  return pl ? pl->reg.find(vid) : nullptr;
}

struct Request {
  std::string method, path, query, version;
  std::unordered_map<std::string, std::string> headers;  // lower-case keys
  std::vector<uint8_t> body;
  bool keepalive = true;
  // body arrived as Transfer-Encoding: chunked and was consumed off the
  // socket during parsing — the client CANNOT replay it after a 307
  // (requests raises UnrewindableBodyError on generator bodies), so
  // fall-back paths must proxy instead of redirect
  bool chunked = false;

  std::string header(const std::string& k) const {
    auto it = headers.find(k);
    return it == headers.end() ? "" : it->second;
  }
};

// recv with the 1s SO_RCVTIMEO tick: >0 bytes, 0 peer closed,
// -1 timeout tick (check stop / idle policy), -2 hard error.
ssize_t recv_step(int fd, char* tmp, size_t cap) {
  ssize_t n = recv(fd, tmp, cap, 0);
  if (n > 0) return n;
  if (n == 0) return 0;
  if (errno == EAGAIN || errno == EWOULDBLOCK || errno == EINTR) return -1;
  return -2;
}

bool read_exact(int fd, std::string& buf, size_t upto,
                const std::atomic<bool>& stop) {
  char tmp[8192];
  int idle_ticks = 0;
  while (buf.size() < upto) {
    ssize_t n = recv_step(fd, tmp, sizeof tmp);
    if (n > 0) { buf.append(tmp, n); idle_ticks = 0; continue; }
    if (n == -1) {  // mid-body stall: give a slow sender 30s
      if (stop.load(std::memory_order_relaxed) || ++idle_ticks > 30)
        return false;
      continue;
    }
    return false;
  }
  return true;
}

// Decode a chunked body starting at buf[body_start] (RFC 7230 §4.1),
// pulling more bytes from fd as needed. On success req->body holds the
// decoded payload and buf is trimmed past the final CRLF. Returns 0 ok,
// -1 connection lost, -2 bad framing. The python servers accept chunked
// uploads (server/filer.py _ChunkedReader), so the native planes must
// too — requests sends generator bodies this way (the S3 gateway's
// streaming unsigned PUT path).
int read_chunked(int fd, std::string& buf, size_t body_start, Request* req,
                 const std::atomic<bool>& stop) {
  req->body.clear();
  size_t pos = body_start;
  for (;;) {
    size_t eol;
    while ((eol = buf.find("\r\n", pos)) == std::string::npos) {
      if (buf.size() - pos > 1024) return -2;  // absurd chunk-size line
      if (!read_exact(fd, buf, buf.size() + 1, stop)) return -1;
    }
    std::string szline = buf.substr(pos, eol - pos);
    size_t semi = szline.find(';');  // drop chunk extensions
    if (semi != std::string::npos) szline.resize(semi);
    char* endp = nullptr;
    errno = 0;
    unsigned long long csz = strtoull(szline.c_str(), &endp, 16);
    if (endp == szline.c_str() || errno == ERANGE) return -2;
    // bound csz FIRST: body.size()+csz could wrap uint64 and a wrapped
    // data_start+csz would make read_exact trivially "succeed"
    if (csz > 256ull * 1024 * 1024 ||
        req->body.size() + csz > 256ull * 1024 * 1024)
      return -2;
    size_t data_start = eol + 2;
    if (csz == 0) {
      // optional trailers, then a blank line
      pos = data_start;
      for (;;) {
        size_t teol;
        while ((teol = buf.find("\r\n", pos)) == std::string::npos) {
          if (buf.size() - pos > 64 * 1024) return -2;
          if (!read_exact(fd, buf, buf.size() + 1, stop)) return -1;
        }
        bool blank = teol == pos;
        pos = teol + 2;
        if (blank) break;
      }
      buf.erase(0, pos);
      return 0;
    }
    if (!read_exact(fd, buf, data_start + csz + 2, stop)) return -1;
    req->body.insert(req->body.end(), buf.begin() + data_start,
                     buf.begin() + data_start + csz);
    if (buf.compare(data_start + csz, 2, "\r\n") != 0) return -2;
    pos = data_start + csz + 2;
    if (pos > (1u << 20)) {  // bound the staging buffer
      buf.erase(0, pos);
      pos = 0;
    }
  }
}

// Read one HTTP request. Returns 0 ok, -1 connection done, -2 bad request.
int read_request(int fd, std::string& buf, Request* req,
                 const std::atomic<bool>& stop) {
  size_t hdr_end;
  int idle_ticks = 0;
  while ((hdr_end = buf.find("\r\n\r\n")) == std::string::npos) {
    if (buf.size() > 64 * 1024) return -2;
    char tmp[8192];
    ssize_t n = recv_step(fd, tmp, sizeof tmp);
    if (n > 0) { buf.append(tmp, n); idle_ticks = 0; continue; }
    if (n == -1) {
      if (stop.load(std::memory_order_relaxed)) return -1;
      // idle keepalive connections may wait forever; a half-sent
      // request line gets 30s
      if (!buf.empty() && ++idle_ticks > 30) return -1;
      continue;
    }
    return -1;
  }
  std::string head = buf.substr(0, hdr_end);
  size_t line_end = head.find("\r\n");
  std::string reqline = head.substr(0, line_end);
  size_t sp1 = reqline.find(' '), sp2 = reqline.rfind(' ');
  if (sp1 == std::string::npos || sp2 == sp1) return -2;
  req->method = reqline.substr(0, sp1);
  std::string target = reqline.substr(sp1 + 1, sp2 - sp1 - 1);
  req->version = reqline.substr(sp2 + 1);
  size_t qpos = target.find('?');
  req->path = qpos == std::string::npos ? target : target.substr(0, qpos);
  req->query = qpos == std::string::npos ? "" : target.substr(qpos + 1);
  req->headers.clear();
  size_t pos = line_end + 2;
  while (pos < head.size()) {
    size_t eol = head.find("\r\n", pos);
    if (eol == std::string::npos) eol = head.size();
    std::string line = head.substr(pos, eol - pos);
    pos = eol + 2;
    size_t colon = line.find(':');
    if (colon == std::string::npos) continue;
    std::string k = line.substr(0, colon);
    for (auto& c : k) c = (char)tolower((unsigned char)c);
    size_t vstart = colon + 1;
    while (vstart < line.size() && line[vstart] == ' ') vstart++;
    req->headers[k] = line.substr(vstart);
  }
  req->keepalive = req->version != "HTTP/1.0";
  std::string conn = req->header("connection");
  for (auto& c : conn) c = (char)tolower((unsigned char)c);
  if (conn == "close") req->keepalive = false;
  if (conn == "keep-alive") req->keepalive = true;

  size_t body_start = hdr_end + 4;
  size_t clen = 0;
  std::string cl = req->header("content-length");
  if (!cl.empty()) clen = (size_t)strtoull(cl.c_str(), nullptr, 10);
  if (clen > 256u * 1024 * 1024) return -2;
  std::string te = req->header("transfer-encoding");
  req->chunked = false;
  if (!te.empty()) {
    for (auto& c : te) c = (char)tolower((unsigned char)c);
    if (te != "chunked") return -2;  // gzip/deflate TE: not supported
    req->chunked = true;
    return read_chunked(fd, buf, body_start, req, stop);
  }
  if (!read_exact(fd, buf, body_start + clen, stop)) return -1;
  req->body.assign(buf.begin() + body_start, buf.begin() + body_start + clen);
  buf.erase(0, body_start + clen);
  return 0;
}

void send_all(int fd, const void* p, size_t n) {
  const char* c = (const char*)p;
  while (n) {
    ssize_t w = send(fd, c, n, MSG_NOSIGNAL);
    if (w <= 0) return;
    c += w;
    n -= (size_t)w;
  }
}

const char* status_text(int code) {
  switch (code) {
    case 200: return "OK";
    case 201: return "Created";
    case 202: return "Accepted";
    case 206: return "Partial Content";
    case 304: return "Not Modified";
    case 307: return "Temporary Redirect";
    case 400: return "Bad Request";
    case 403: return "Forbidden";
    case 404: return "Not Found";
    case 411: return "Length Required";
    case 500: return "Internal Server Error";
    default: return "";
  }
}

void respond(int fd, const Request& req, int code, const std::string& ctype,
             const std::string& extra_headers, const uint8_t* body,
             size_t body_len) {
  if (req.method == "HEAD") body = nullptr;
  // single buffer -> single send(): no Nagle/delayed-ACK interaction.
  // Composed as a std::string: header size is unbounded (redirect
  // Locations echo the request path).
  std::string out;
  out.reserve(256 + extra_headers.size() + (body ? body_len : 0));
  out += "HTTP/1.1 ";
  out += std::to_string(code);
  out += ' ';
  out += status_text(code);
  out += "\r\nContent-Type: ";
  out += ctype;
  out += "\r\nContent-Length: ";
  out += std::to_string(body_len);
  out += "\r\n";
  out += extra_headers;
  if (!req.keepalive) out += "Connection: close\r\n";
  out += "\r\n";
  if (body && body_len) out.append((const char*)body, body_len);
  send_all(fd, out.data(), out.size());
}

void respond_json(int fd, const Request& req, int code,
                  const std::string& json) {
  respond(fd, req, code, "application/json", "", (const uint8_t*)json.data(),
          json.size());
}

// Forward an already-parsed request to the python server on loopback
// with Content-Length framing and relay the response verbatim. Used for
// chunked-TE requests, whose body the client cannot re-send after a 307.
void proxy_to_python(int fd, const Request& req, int backend_port) {
  int b = socket(AF_INET, SOCK_STREAM, 0);
  struct sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_port = htons((uint16_t)backend_port);
  addr.sin_addr.s_addr = htonl(INADDR_LOOPBACK);
  if (b < 0 || connect(b, (struct sockaddr*)&addr, sizeof addr) != 0) {
    if (b >= 0) close(b);
    return respond_json(fd, req, 500,
                        "{\"error\":\"python backend unreachable\"}");
  }
  struct timeval tv{60, 0};  // python writes can take a while
  setsockopt(b, SOL_SOCKET, SO_RCVTIMEO, &tv, sizeof tv);
  std::string out = req.method + " " + req.path +
                    (req.query.empty() ? "" : "?" + req.query) +
                    " HTTP/1.1\r\n";
  for (const auto& kv : req.headers) {
    if (kv.first == "transfer-encoding" || kv.first == "content-length" ||
        kv.first == "connection" || kv.first == "expect")
      continue;
    out += kv.first + ": " + kv.second + "\r\n";
  }
  out += "Content-Length: " + std::to_string(req.body.size()) + "\r\n";
  out += "Connection: close\r\n\r\n";
  send_all(b, out.data(), out.size());
  if (!req.body.empty()) send_all(b, req.body.data(), req.body.size());
  // relay until the backend closes (it honors Connection: close); the
  // relayed headers carry that close, so the client re-opens cleanly
  char tmp[16384];
  for (;;) {
    ssize_t n = recv(b, tmp, sizeof tmp, 0);
    if (n > 0) {
      send_all(fd, tmp, (size_t)n);
      continue;
    }
    if (n < 0 && errno == EINTR) continue;
    break;  // 0 = done; timeout/error = give up (client sees truncation)
  }
  close(b);
}

void redirect(int fd, const Request& req, int redirect_port) {
  if (req.chunked)  // consumed body is not replayable: forward instead
    return proxy_to_python(fd, req, redirect_port);
  std::string host = req.header("host");
  size_t colon = host.rfind(':');
  if (colon != std::string::npos) host = host.substr(0, colon);
  if (host.empty()) host = "127.0.0.1";
  std::string loc = "Location: http://" + host + ":" +
                    std::to_string(redirect_port) + req.path +
                    (req.query.empty() ? "" : "?" + req.query) + "\r\n";
  respond(fd, req, 307, "text/plain", loc, nullptr, 0);
}

// Parse a CLEAN "bytes=lo-hi" / "bytes=lo-" Range header. Anything
// unusual — suffix/multi ranges, non-digits, overflow-scale bounds —
// returns false and the caller defers to python, so edge semantics live
// in exactly one place per plane's python counterpart.
bool parse_clean_range(const std::string& rng, uint64_t* start,
                       uint64_t* hi, bool* has_hi) {
  if (rng.rfind("bytes=", 0) != 0) return false;
  std::string spec = rng.substr(6);
  size_t dash = spec.find('-');
  if (dash == std::string::npos || dash == 0 || dash > 15 ||
      spec.size() - dash - 1 > 15 ||
      spec.find(',') != std::string::npos)
    return false;
  for (size_t i = 0; i < spec.size(); i++)
    if (i != dash && !isdigit((unsigned char)spec[i])) return false;
  *start = strtoull(spec.c_str(), nullptr, 10);
  *has_hi = dash + 1 < spec.size();
  if (*has_hi) *hi = strtoull(spec.c_str() + dash + 1, nullptr, 10);
  return true;
}

// Parse "/vid,keyhex+cookiehex[.ext]". Returns false if not a fid path.
bool parse_fid_path(const std::string& path, uint32_t* vid, uint64_t* key,
                    uint32_t* cookie) {
  if (path.size() < 4 || path[0] != '/') return false;
  size_t comma = path.find(',');
  if (comma == std::string::npos || comma <= 1) return false;
  uint32_t v = 0;
  for (size_t i = 1; i < comma; i++) {
    if (!isdigit((unsigned char)path[i])) return false;
    v = v * 10 + (path[i] - '0');
  }
  std::string hex = path.substr(comma + 1);
  size_t dot = hex.find('.');
  if (dot != std::string::npos) hex = hex.substr(0, dot);
  uint64_t delta = 0;
  size_t us = hex.rfind('_');
  if (us != std::string::npos) {  // "key_delta" batched-assign suffix
    for (size_t i = us + 1; i < hex.size(); i++) {
      if (!isdigit((unsigned char)hex[i])) return false;
      delta = delta * 10 + (unsigned)(hex[i] - '0');
    }
    if (us + 1 >= hex.size()) return false;
    hex = hex.substr(0, us);
  }
  if (hex.size() <= 8 || hex.size() > 24) return false;
  uint64_t k = 0;
  uint32_t c = 0;
  size_t split = hex.size() - 8;
  for (size_t i = 0; i < hex.size(); i++) {
    char ch = (char)tolower((unsigned char)hex[i]);
    int d;
    if (ch >= '0' && ch <= '9') d = ch - '0';
    else if (ch >= 'a' && ch <= 'f') d = ch - 'a' + 10;
    else return false;
    if (i < split) k = (k << 4) | (unsigned)d;
    else c = (c << 4) | (unsigned)d;
  }
  *vid = v;
  *key = k + delta;
  *cookie = c;
  return true;
}

std::string etag_hex(uint32_t crc) {
  char b[16];
  snprintf(b, sizeof b, "%08x", crc);
  return std::string(b);
}

std::string http_date(uint64_t unix_secs) {
  char b[64];
  time_t t = (time_t)unix_secs;
  struct tm g;
  gmtime_r(&t, &g);
  strftime(b, sizeof b, "%a, %d %b %Y %H:%M:%S GMT", &g);
  return std::string(b);
}

// RFC 7232 §3.2 If-None-Match for GET/HEAD: WEAK comparison over the
// entity-tag list ("*" matches any representation) — mirrors
// utils/http.py parse_etag_list/weak_etag_match so both planes answer
// conditionals identically.
bool inm_matches(const std::string& inm, const std::string& etag) {
  std::string target = etag;
  if (target.rfind("W/", 0) == 0 || target.rfind("w/", 0) == 0)
    target = target.substr(2);
  size_t i = 0;
  while (i < inm.size()) {
    while (i < inm.size() &&
           (inm[i] == ',' || inm[i] == ' ' || inm[i] == '\t'))
      i++;
    if (i >= inm.size()) break;
    if (inm[i] == '*') return true;
    if (inm.compare(i, 2, "W/") == 0 || inm.compare(i, 2, "w/") == 0)
      i += 2;
    if (i < inm.size() && inm[i] == '"') {
      size_t end = inm.find('"', i + 1);
      if (end == std::string::npos) return false;
      if (inm.compare(i, end - i + 1, target) == 0) return true;
      i = end + 1;
    } else {  // lenient: bare token (some clients send unquoted md5s)
      size_t end = inm.find(',', i);
      if (end == std::string::npos) end = inm.size();
      std::string tok = inm.substr(i, end - i);
      while (!tok.empty() && (tok.back() == ' ' || tok.back() == '\t'))
        tok.pop_back();
      if (tok == target) return true;
      i = end;
    }
  }
  return false;
}

// ------------------------------------------------------------- handlers --

// Zero-copy GET (ISSUE 9 tentpole): serve the needle body straight off
// the .dat fd with sendfile(2) — the payload never crosses user space.
// Two bounded preads fetch the record ENVELOPE only (the prefix locating
// the data span; the post-data tail carrying flags/mime/mtime and the
// stored checksum), then the kernel moves data_len bytes disk->socket.
// The stored checksum becomes the ETag WITHOUT a verify pass — skipping
// the per-GET CRC is exactly the copy this path deletes; at-rest
// integrity is owned by the scrub plane (ISSUE 4), and every buffered
// read (python, small needles, compressed/TTL records) still verifies.
// Returns true when the response (or a deliberate redirect) was fully
// handled; false falls through to the buffered path.
bool try_sendfile_get(Plane& pl, int fd, const Request& req, Volume& vol,
                      const NeedleValue& nv,
                      const std::shared_ptr<FdOwner>& ref,
                      uint32_t cookie) {
  int64_t zmin = pl.zerocopy_min.load(std::memory_order_relaxed);
  if (zmin < 0 || req.method != "GET" || nv.size <= 0) return false;
  int64_t base = (int64_t)nv.stored_offset * kPad;
  int32_t size = nv.size;
  int64_t data_off, data_len;
  uint8_t prefix[kHeaderSize + 4];
  if (vol.version == 1) {
    if (pread(ref->fd, prefix, kHeaderSize, base) != kHeaderSize)
      return false;
    data_off = kHeaderSize;
    data_len = size;
  } else {
    if (pread(ref->fd, prefix, sizeof prefix, base) !=
        (ssize_t)sizeof prefix)
      return false;
    data_off = kHeaderSize + 4;
    data_len = get_u32(prefix + kHeaderSize);
  }
  if (get_u32(prefix) != cookie) return false;  // buffered path 404s
  if (data_len < zmin) return false;  // small body: one pread is cheaper
  // the envelope after the data: flags/name/mime/lm + stored checksum
  int64_t tail_off = base + data_off + data_len;
  int64_t tail_len = base + kHeaderSize + size + kChecksumSize - tail_off;
  if (tail_len < kChecksumSize || tail_len > 4096)
    return false;  // structurally off / huge meta: buffered path decides
  uint8_t tail[4096 + kChecksumSize];
  if (pread(ref->fd, tail, tail_len, tail_off) != (ssize_t)tail_len)
    return false;
  uint8_t flags = 0;
  const uint8_t* mime = nullptr;
  uint8_t mime_len = 0;
  uint64_t last_modified = 0;
  const uint8_t* p = tail;
  const uint8_t* end = tail + (tail_len - kChecksumSize);
  if (vol.version != 1) {
    if (p < end) flags = *p++;
    if (p < end && (flags & kFlagHasName)) {
      uint8_t nl = *p++;
      p += nl;
    }
    if (p < end && (flags & kFlagHasMime)) {
      mime_len = *p++;
      if (p + mime_len > end) return false;
      mime = p;
      p += mime_len;
    }
    if (p + 5 <= end && (flags & kFlagHasLastModified)) {
      for (int i = 0; i < 5; i++)
        last_modified = (last_modified << 8) | p[i];
      p += 5;
    }
    if (p > end) return false;
  }
  if (flags & (kFlagHasTtl | kFlagHasPairs | kFlagCompressed))
    return false;  // py semantics / AE negotiation: buffered path
  uint32_t checksum = get_u32(tail + (tail_len - kChecksumSize));
  std::string etag = "\"" + etag_hex(checksum) + "\"";
  std::string extra = "ETag: " + etag + "\r\n";
  if (last_modified)
    extra += "Last-Modified: " + http_date(last_modified) + "\r\n";
  std::string inm = req.header("if-none-match");
  if (!inm.empty() && inm_matches(inm, etag)) {
    respond(fd, req, 304, "text/plain", extra, nullptr, 0);
    return true;
  }
  std::string ctype = mime_len
                          ? std::string((const char*)mime, mime_len)
                          : "application/octet-stream";
  uint64_t start = 0, stop = (uint64_t)data_len;
  int code = 200;
  std::string rng = req.header("range");
  if (!rng.empty()) {
    uint64_t lo = 0, hi = 0;
    bool has_hi = false;
    // inverted/past-EOF spans redirect too: python's shared
    // parse_range answers them with a spec-shaped 416
    if (!parse_clean_range(rng, &lo, &hi, &has_hi) ||
        lo >= (uint64_t)data_len || (has_hi && hi < lo)) {
      redirect(fd, req, pl.redirect_port);
      return true;
    }
    start = lo;
    stop = has_hi ? hi + 1 : (uint64_t)data_len;
    if (stop > (uint64_t)data_len) stop = (uint64_t)data_len;
    extra += "Content-Range: bytes " + std::to_string(start) + "-" +
             std::to_string(stop - 1) + "/" +
             std::to_string(data_len) + "\r\n";
    code = 206;
  }
  uint64_t body_len = stop > start ? stop - start : 0;
  std::string head;
  head.reserve(256 + extra.size());
  head += "HTTP/1.1 ";
  head += std::to_string(code);
  head += ' ';
  head += status_text(code);
  head += "\r\nContent-Type: ";
  head += ctype;
  head += "\r\nContent-Length: ";
  head += std::to_string(body_len);
  head += "\r\n";
  head += extra;
  if (!req.keepalive) head += "Connection: close\r\n";
  head += "\r\n";
  send_all(fd, head.data(), head.size());
  off_t off = (off_t)(base + data_off + (int64_t)start);
  uint64_t remaining = body_len;
  bool zero_copy = true;
  while (remaining > 0) {
    ssize_t s = sendfile(fd, ref->fd, &off, remaining);
    if (s > 0) {
      remaining -= (uint64_t)s;
      continue;
    }
    if (s < 0 && errno == EINTR) continue;
    if (s < 0 && (errno == EINVAL || errno == ENOSYS)) {
      // fs without sendfile support: finish buffered — the status line
      // is already on the wire, so this must complete the same body
      zero_copy = false;
      std::vector<uint8_t> buf(64 * 1024);
      while (remaining > 0) {
        ssize_t got = pread(
            ref->fd, buf.data(),
            remaining < buf.size() ? remaining : buf.size(), off);
        if (got <= 0) break;
        send_all(fd, buf.data(), (size_t)got);
        off += got;
        remaining -= (uint64_t)got;
      }
    }
    break;  // client gone / hard error: Content-Length exposes the gap
  }
  if (remaining == 0 && zero_copy)
    pl.sendfiles.fetch_add(1, std::memory_order_relaxed);
  return true;
}

void handle_get(Plane& pl, int fd, const Request& req, uint32_t vid,
                uint64_t key, uint32_t cookie) {
  auto vol = pl.reg.find(vid);
  if (!vol) return redirect(fd, req, pl.redirect_port);
  NeedleValue nv{0, 0};
  bool no_dat = false;
  std::shared_ptr<FdOwner> ref;
  {
    std::lock_guard<std::mutex> l(vol->mu);
    no_dat = !vol->dat;
    auto it = vol->map.find(key);
    if (it == vol->map.end()) {
      vol->catchup();  // maybe written outside our map (reload races)
      it = vol->map.find(key);
    }
    if (it != vol->map.end()) nv = it->second;
    // pin the fd owner while the map snapshot is consistent with it:
    // swdp_reload_volume (vacuum commit) swaps in a new owner under mu,
    // so a bare pread after unlock could hit the post-compaction file
    // at a stale offset. The shared_ptr copy keeps the pre-reload inode
    // open, against which nv's offset is valid — no dup() syscall.
    if (nv.stored_offset != 0 && nv.size >= 0) ref = vol->dat;
  }
  if (no_dat)
    // failed reload cleared the handles and the map: an empty map must
    // NOT read as a definitive 404 (the filer's read ladder would stop
    // failing over) — python owns the truth for this volume now
    return redirect(fd, req, pl.redirect_port);
  if (nv.stored_offset == 0 || nv.size < 0)
    return respond(fd, req, 404, "text/plain", "", nullptr, 0);
  if (!ref || ref->fd < 0)
    return respond_json(fd, req, 500, "{\"error\":\"no dat file\"}");
  // conditional-request conformance (ISSUE 9): If-None-Match both fast
  // paths evaluate natively (weak list comparison); every OTHER
  // validator header (If-Range, If-(Un)Modified-Since, If-Match) is
  // decided in exactly one place — the python handler
  if (!req.header("if-range").empty() ||
      !req.header("if-modified-since").empty() ||
      !req.header("if-match").empty() ||
      !req.header("if-unmodified-since").empty())
    return redirect(fd, req, pl.redirect_port);
  if (try_sendfile_get(pl, fd, req, *vol, nv, ref, cookie)) return;
  int64_t total = actual_size(nv.size, vol->version);
  std::vector<uint8_t> blob(total);
  int64_t got = pread(ref->fd, blob.data(), total,
                      (int64_t)nv.stored_offset * kPad);
  if (got != total)
    return respond_json(fd, req, 500, "{\"error\":\"short read\"}");
  ParsedNeedle n;
  if (!parse_record(blob.data(), total, vol->version, &n) || n.size != nv.size)
    return respond_json(fd, req, 500, "{\"error\":\"corrupt record\"}");
  if (n.cookie != cookie)
    return respond(fd, req, 404, "text/plain", "", nullptr, 0);
  if (n.flags & (kFlagHasTtl | kFlagHasPairs))
    return redirect(fd, req, pl.redirect_port);  // rare: py semantics
  uint32_t actual = crc32c(n.data, n.data_len);
  if (n.size > 0 && n.checksum != actual && n.checksum != crc_legacy(actual))
    return respond_json(fd, req, 500,
                        "{\"error\":\"CRC error! Data On Disk Corrupted\"}");
  std::string etag = "\"" + etag_hex(actual) + "\"";
  std::string inm = req.header("if-none-match");
  std::string extra = "ETag: " + etag + "\r\n";
  if (n.last_modified)
    extra += "Last-Modified: " + http_date(n.last_modified) + "\r\n";
  if (!inm.empty() && inm_matches(inm, etag))
    return respond(fd, req, 304, "text/plain", extra, nullptr, 0);
  std::string ctype = n.mime_len
                          ? std::string((const char*)n.mime, n.mime_len)
                          : "application/octet-stream";
  std::string rng = req.header("range");
  if (n.flags & kFlagCompressed) {
    // py decompresses for non-gzip clients and for ranged reads
    std::string ae = req.header("accept-encoding");
    if (ae.find("gzip") == std::string::npos || !rng.empty())
      return redirect(fd, req, pl.redirect_port);
    extra += "Content-Encoding: gzip\r\n";
  }
  if (!rng.empty()) {
    // Common "bytes=lo-hi" / "bytes=lo-" ranges are served natively with
    // volume.py's clamp semantics; anything else (incl. start past EOF)
    // is delegated to the python handler.
    uint64_t start = 0, hi = 0;
    bool has_hi = false;
    bool clean = parse_clean_range(rng, &start, &hi, &has_hi);
    // inverted spans redirect like suffix/past-EOF ones: python's
    // shared parse_range answers them with a spec-shaped 416
    if (!clean || start >= n.data_len || (has_hi && hi < start))
      return redirect(fd, req, pl.redirect_port);
    uint64_t stop = has_hi ? hi + 1 : n.data_len;
    if (stop > n.data_len) stop = n.data_len;
    extra += "Content-Range: bytes " + std::to_string(start) + "-" +
             std::to_string(stop - 1) + "/" +
             std::to_string(n.data_len) + "\r\n";
    return respond(fd, req, 206, ctype, extra, n.data + start,
                   stop - start);
  }
  respond(fd, req, 200, ctype, extra, n.data, n.data_len);
}

void handle_put(Plane& pl, int fd, const Request& req, uint32_t vid,
                uint64_t key, uint32_t cookie) {
  auto vol = pl.reg.find(vid);
  if (!vol || !vol->writable)
    return redirect(fd, req, pl.redirect_port);
  bool put_no_dat;
  {
    std::lock_guard<std::mutex> l(vol->mu);
    put_no_dat = !vol->dat;
  }
  if (put_no_dat)  // handles cleared by a failed reload: python owns it
    return redirect(fd, req, pl.redirect_port);
  std::string ct = req.header("content-type");
  if (ct.rfind("multipart/", 0) == 0)
    return redirect(fd, req, pl.redirect_port);
  bool compressed = req.header("content-encoding") == "gzip";

  const uint8_t* data = req.body.data();
  uint32_t dlen = (uint32_t)req.body.size();
  uint8_t flags = kFlagHasLastModified;
  if (!ct.empty() && ct.size() < 256) flags |= kFlagHasMime;
  if (compressed) flags |= kFlagCompressed;
  uint64_t now_secs = (uint64_t)time(nullptr);

  int32_t size = dlen ? (int32_t)(4 + dlen + 1 +
                                  ((flags & kFlagHasMime) ? 1 + ct.size() : 0) +
                                  5)
                      : 0;
  uint32_t crc = crc32c(data, dlen);
  int64_t total = actual_size(size, vol->version);
  std::vector<uint8_t> blob(total, 0);
  uint8_t* p = blob.data();
  put_u32(p, cookie);
  put_u64(p + 4, key);
  put_u32(p + 12, (uint32_t)size);
  int64_t off = kHeaderSize;
  if (dlen) {
    put_u32(p + off, dlen);
    off += 4;
    memcpy(p + off, data, dlen);
    off += dlen;
    p[off++] = flags;
    if (flags & kFlagHasMime) {
      p[off++] = (uint8_t)ct.size();
      memcpy(p + off, ct.data(), ct.size());
      off += ct.size();
    }
    for (int i = 0; i < 5; i++)
      p[off + i] = (uint8_t)(now_secs >> (32 - 8 * i));
    off += 5;
  }
  put_u32(p + off, crc);
  off += 4;
  int64_t ns_off = vol->version == 3 ? off : -1;

  {
    std::lock_guard<std::mutex> l(vol->mu);
    if (!vol->writable) {  // frozen between our gate check and the lock
      // (commit_compact freeze: appending now would write the old inode)
      goto frozen;
    }
    // dedup identical rewrite (volume.py _is_file_unchanged)
    auto it = vol->map.find(key);
    if (it != vol->map.end() && it->second.size > 0) {
      int64_t old_total = actual_size(it->second.size, vol->version);
      std::vector<uint8_t> old(old_total);
      if (pread(vol->dat_fd(), old.data(), old_total,
                (int64_t)it->second.stored_offset * kPad) == old_total) {
        ParsedNeedle on;
        if (parse_record(old.data(), old_total, vol->version, &on)) {
          if (on.cookie != cookie) {
            return respond_json(fd, req, 403,
                                "{\"error\":\"mismatching cookie\"}");
          }
          if (on.checksum == crc && on.data_len == dlen &&
              memcmp(on.data, data, dlen) == 0) {
            char out[128];
            snprintf(out, sizeof out,
                     "{\"name\": \"\", \"size\": %u, \"eTag\": \"%s\"}", dlen,
                     etag_hex(crc).c_str());
            return respond_json(fd, req, 201, out);
          }
        }
      }
    }
    if (vol->append(blob.data(), total, key, size, ns_off, nullptr) < 0)
      return respond_json(fd, req, 500, "{\"error\":\"append failed\"}");
  }
  {
    char out[128];
    snprintf(out, sizeof out,
             "{\"name\": \"\", \"size\": %d, \"eTag\": \"%s\"}",
             size, etag_hex(crc).c_str());
    return respond_json(fd, req, 201, out);
  }
frozen:
  redirect(fd, req, pl.redirect_port);
}

void handle_delete(Plane& pl, int fd, const Request& req, uint32_t vid,
                   uint64_t key, uint32_t cookie) {
  auto vol = pl.reg.find(vid);
  if (!vol || !vol->writable)
    return redirect(fd, req, pl.redirect_port);
  bool del_no_dat;
  {
    std::lock_guard<std::mutex> l(vol->mu);
    del_no_dat = !vol->dat;
  }
  if (del_no_dat)  // handles cleared by a failed reload: python owns it
    return redirect(fd, req, pl.redirect_port);
  int32_t freed = 0;
  {
    std::lock_guard<std::mutex> l(vol->mu);
    if (!vol->writable)  // frozen between gate check and lock
      goto frozen;
    auto it = vol->map.find(key);
    if (it == vol->map.end() || it->second.size < 0)
      return respond_json(fd, req, 404, "{\"size\": 0}");
    // cookie check against the stored record (volume.py delete_needle)
    uint8_t hdr[kHeaderSize];
    if (pread(vol->dat_fd(), hdr, kHeaderSize,
              (int64_t)it->second.stored_offset * kPad) == kHeaderSize) {
      if (get_u32(hdr) != cookie)
        return respond_json(fd, req, 403,
                            "{\"error\":\"cookie mismatch on delete\"}");
    }
    freed = it->second.size;
    // zero-size deletion marker record (doDeleteRequest)
    int64_t total = actual_size(0, vol->version);
    std::vector<uint8_t> blob(total, 0);
    put_u32(blob.data(), cookie);
    put_u64(blob.data() + 4, key);
    int64_t ns_off = vol->version == 3 ? kHeaderSize + kChecksumSize : -1;
    if (vol->append(blob.data(), total, key, kTombstone, ns_off, nullptr) < 0)
      return respond_json(fd, req, 500, "{\"error\":\"append failed\"}");
  }
  {
    char out[64];
    snprintf(out, sizeof out, "{\"size\": %d}", freed);
    return respond_json(fd, req, 202, out);
  }
frozen:
  redirect(fd, req, pl.redirect_port);
}

void handle_request(Plane& pl, int fd, const Request& req) {
  pl.requests.fetch_add(1, std::memory_order_relaxed);
  uint32_t vid, cookie;
  uint64_t key;
  if (!parse_fid_path(req.path, &vid, &key, &cookie))
    return redirect(fd, req, pl.redirect_port);
  if (req.method == "GET" || req.method == "HEAD") {
    // queries (resize, readDeleted) and ims need python semantics;
    // plain "bytes=lo-hi" ranges are served natively (filer chunk views)
    if (!req.query.empty() || !req.header("if-modified-since").empty())
      return redirect(fd, req, pl.redirect_port);
    return handle_get(pl, fd, req, vid, key, cookie);
  }
  if (req.method == "PUT" || req.method == "POST") {
    if (!req.query.empty() && req.query != "type=replicate")
      return redirect(fd, req, pl.redirect_port);
    return handle_put(pl, fd, req, vid, key, cookie);
  }
  if (req.method == "DELETE") {
    if (!req.query.empty() && req.query != "type=replicate")
      return redirect(fd, req, pl.redirect_port);
    return handle_delete(pl, fd, req, vid, key, cookie);
  }
  redirect(fd, req, pl.redirect_port);
}

void conn_loop(Plane* srv, int fd) {
  int one = 1;
  setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof one);
  struct timeval tv{1, 0};  // 1s ticks so stop is noticed promptly
  setsockopt(fd, SOL_SOCKET, SO_RCVTIMEO, &tv, sizeof tv);
  std::string buf;
  Request req;
  while (!srv->stop.load(std::memory_order_relaxed)) {
    int rc = read_request(fd, buf, &req, srv->stop);
    if (rc == -1) break;
    if (rc == -2) {
      respond(fd, req, 400, "text/plain", "", nullptr, 0);
      break;
    }
    handle_request(*srv, fd, req);
    if (!req.keepalive) break;
  }
  close(fd);
  srv->live_conns.fetch_sub(1, std::memory_order_relaxed);
}

void acceptor_loop(Plane* srv) {
  while (!srv->stop.load(std::memory_order_relaxed)) {
    int fd = accept(srv->listen_fd, nullptr, nullptr);
    if (fd < 0) {
      if (srv->stop.load(std::memory_order_relaxed)) return;
      // a persistent failure (e.g. EMFILE under thread-per-conn load)
      // would otherwise busy-spin a full core
      if (errno != EINTR) usleep(20000);
      continue;
    }
    if (srv->live_conns.load(std::memory_order_relaxed) >= 1024) {
      close(fd);  // connection-flood backstop
      continue;
    }
    srv->live_conns.fetch_add(1, std::memory_order_relaxed);
    std::thread(conn_loop, srv, fd).detach();
  }
}

// ------------------------------------------------------- filer hot plane --
//
// C++ ownership of whole-object PUT/GET under a path prefix (default
// "/buckets/"), the filer analogue of the volume data plane above and the
// round-3 answer to the all-Python filer write path (~250 writes/s:
// 3 HTTP hops + store + event log per PUT). Design:
//
//   * Python leases fid blocks (batched master assigns) into the plane;
//     each native PUT mints one fid and appends the needle DIRECTLY into
//     the co-located volume plane's registry — zero HTTP hops when filer
//     and volume server share the process (`weed server`).
//   * Entry metadata is appended to a hot log + in-memory map; the
//     Python filer tails the log (FilerServer._absorb_hot_log) into the
//     real store, emitting metadata events on absorption. Listings /
//     metadata reads absorb-then-serve, so read-your-writes holds.
//   * GETs of hot objects are served from the map straight off the
//     volume plane; anything else (queries, ranges, conditionals,
//     multipart, oversized bodies, unknown paths) 307s to Python.
//   * Python-side mutations (S3 gateway, DELETE, rename) call
//     swfp_invalidate via the Filer.on_mutate hook so the map never
//     serves stale bytes.
//
// Reference counterpart: filer_server_handlers_write_autochunk.go:24
// (the per-request assign+upload+CreateEntry pipeline this replaces).

struct HotEntry {
  uint32_t vid = 0;
  uint64_t key = 0;
  uint32_t cookie = 0;
  uint64_t size = 0;
  uint32_t crc = 0;
  uint64_t mtime_ns = 0;
  std::string mime;
};

struct FidLease {
  uint32_t vid = 0;
  uint64_t base = 0;
  uint32_t cookie = 0;
  uint32_t next = 0;
  uint32_t count = 0;
};

struct FilerPlane {
  int id = 0;
  int listen_fd = -1;
  int port = 0, redirect_port = 0;
  int vol_plane_id = -1;
  size_t max_body = 4u << 20;
  std::string prefix = "/buckets/";
  std::atomic<bool> stop{false};
  std::thread acceptor;
  std::atomic<int> live_conns{0};
  std::atomic<uint64_t> requests{0}, native_puts{0}, native_gets{0},
      redirects{0};

  std::mutex mu;  // map + hot log + leases
  std::condition_variable lease_cv;  // signaled on swfp_add_lease
  std::unordered_map<std::string, HotEntry> map;
  std::deque<FidLease> leases;
  uint64_t lease_remaining = 0;
  int log_fd = -1;
  // set when a hot-log append failed (disk full / IO error): acked PUTs
  // could no longer be made durable, so the fast path stands down and
  // every PUT defers to the python filer until restart
  bool log_failed = false;

  ~FilerPlane() {
    if (log_fd >= 0) close(log_fd);
  }
};

std::mutex g_fplanes_mu;
std::unordered_map<int, std::shared_ptr<FilerPlane>> g_fplanes;
int g_next_fplane = 1;

std::shared_ptr<FilerPlane> fplane_of(int id) {
  std::lock_guard<std::mutex> l(g_fplanes_mu);
  auto it = g_fplanes.find(id);
  return it == g_fplanes.end() ? nullptr : it->second;
}

// Hot-log record, little-endian (tools read it with struct '<'):
// [u8 op=1][u16 plen][u16 mimelen][u32 vid][u64 key][u32 cookie]
// [u64 size][u32 crc][u64 mtime_ns][path][mime]
constexpr size_t kHotHdr = 1 + 2 + 2 + 4 + 8 + 4 + 8 + 4 + 8;

void put_le16(uint8_t* p, uint16_t v) { p[0] = v & 0xFF; p[1] = v >> 8; }
void put_le32(uint8_t* p, uint32_t v) {
  for (int i = 0; i < 4; i++) p[i] = (v >> (8 * i)) & 0xFF;
}
void put_le64(uint8_t* p, uint64_t v) {
  for (int i = 0; i < 8; i++) p[i] = (v >> (8 * i)) & 0xFF;
}

// Append one record; caller holds fp.mu. Returns false when the record
// could not be made fully durable — the caller must NOT ack the PUT
// (the acked entry would vanish on restart) and the plane stands down.
bool hotlog_append(FilerPlane& fp, const std::string& path,
                   const HotEntry& e) {
  if (fp.log_fd < 0 || fp.log_failed) return false;
  std::vector<uint8_t> rec(kHotHdr + path.size() + e.mime.size());
  uint8_t* p = rec.data();
  p[0] = 1;
  put_le16(p + 1, (uint16_t)path.size());
  put_le16(p + 3, (uint16_t)e.mime.size());
  put_le32(p + 5, e.vid);
  put_le64(p + 9, e.key);
  put_le32(p + 17, e.cookie);
  put_le64(p + 21, e.size);
  put_le32(p + 29, e.crc);
  put_le64(p + 33, e.mtime_ns);
  memcpy(p + kHotHdr, path.data(), path.size());
  memcpy(p + kHotHdr + path.size(), e.mime.data(), e.mime.size());
  // single write() so the python tailer never sees a torn record except
  // at a crash boundary (where it stops at the last complete record)
  off_t pre = lseek(fp.log_fd, 0, SEEK_CUR);
  ssize_t w = write(fp.log_fd, rec.data(), rec.size());
  if (w == (ssize_t)rec.size()) return true;
  // failed or short (disk full): remove the torn tail so the absorber
  // never stalls on it, and disable the fast path for good measure
  if (pre >= 0) {
    if (ftruncate(fp.log_fd, pre) == 0) lseek(fp.log_fd, pre, SEEK_SET);
  }
  fp.log_failed = true;
  return false;
}

std::string json_escape(const std::string& s) {
  std::string out;
  out.reserve(s.size() + 2);
  for (unsigned char c : s) {
    if (c == '"' || c == '\\') { out += '\\'; out += (char)c; }
    else if (c < 0x20) {
      char b[8];
      snprintf(b, sizeof b, "\\u%04x", c);
      out += b;
    } else out += (char)c;
  }
  return out;
}

// First-file-part extraction from multipart/form-data, mirroring the
// python filer's semantics (server/volume.py _extract_upload: first part
// with a payload wins, stored mime is empty). False defers to python
// (no/odd boundary, transfer-encoded or nested-multipart parts, framing
// surprises). Operates on a string_view over the body — no full copy.
bool parse_multipart(const std::string& ct, const std::vector<uint8_t>& body,
                     std::vector<uint8_t>* out) {
  size_t bp = ct.find("boundary=");
  if (bp == std::string::npos) return false;
  std::string b = ct.substr(bp + 9);
  if (!b.empty() && b.front() == '"') {
    size_t q = b.find('"', 1);
    if (q == std::string::npos) return false;
    b = b.substr(1, q - 1);
  } else {
    size_t sc = b.find(';');
    if (sc != std::string::npos) b = b.substr(0, sc);
  }
  if (b.empty()) return false;
  std::string delim = "--" + b;
  std::string_view data((const char*)body.data(), body.size());
  size_t p = data.find(delim);
  if (p == std::string::npos) return false;
  p += delim.size();
  if (data.substr(p, 2) != "\r\n") return false;
  p += 2;
  size_t hdr_end = data.find("\r\n\r\n", p);
  if (hdr_end == std::string::npos) return false;
  std::string hdrs(data.substr(p, hdr_end - p));
  for (auto& c : hdrs) c = (char)tolower((unsigned char)c);
  if (hdrs.find("content-transfer-encoding:") != std::string::npos)
    return false;  // base64/qp parts need python's email decoder
  if (hdrs.find("content-type: multipart/") != std::string::npos ||
      hdrs.find("content-type:multipart/") != std::string::npos)
    return false;  // nested container part: python skips to its children
  size_t body_start = hdr_end + 4;
  // the part ends at a TRUE delimiter LINE: CRLF + delim followed (after
  // optional linear whitespace padding) by CRLF or the closing "--".
  // RFC 2046 allows content containing CRLF + a PREFIX of the delimiter
  // ("\r\n--bonus" with boundary "b"), so a bare find() would truncate.
  std::string marker = "\r\n" + delim;
  size_t next = body_start > 0 ? body_start - 2 : 0;  // part may be empty
  for (;;) {
    next = data.find(marker, next);
    if (next == std::string::npos) return false;
    size_t after = next + marker.size();
    while (after < data.size() &&
           (data[after] == ' ' || data[after] == '\t'))
      after++;
    if (data.substr(after, 2) == "\r\n" || data.substr(after, 2) == "--")
      break;
    next += 1;  // prefix match inside content: keep scanning
  }
  if (next < body_start) return false;  // delimiter inside part headers
  out->assign(body.begin() + body_start, body.begin() + next);
  return true;
}

void handle_filer_put(FilerPlane& fp, int fd, const Request& req,
                      const std::string& path) {
  if (!req.query.empty() || req.body.size() > fp.max_body)
    return fp.redirects++, redirect(fd, req, fp.redirect_port);
  // the caller wants the whole-body md5 recorded as the entity-tag
  // (the S3 gateway's ETag contract) — only the python PUT path
  // computes it, so the absorbed entry would serve a different ETag
  // than the PUT returned
  if (!req.header("x-swfs-want-md5").empty() ||
      !req.header("content-md5").empty())
    return fp.redirects++, redirect(fd, req, fp.redirect_port);
  std::string ct = req.header("content-type");
  if (ct.size() >= 256 || !req.header("content-encoding").empty())
    return fp.redirects++, redirect(fd, req, fp.redirect_port);
  std::vector<uint8_t> part;
  bool is_multipart = ct.rfind("multipart/form-data", 0) == 0;
  if (is_multipart) {
    if (!parse_multipart(ct, req.body, &part))
      return fp.redirects++, redirect(fd, req, fp.redirect_port);
    ct.clear();  // python stores multipart uploads with empty mime
  } else if (ct.rfind("multipart/", 0) == 0) {
    return fp.redirects++, redirect(fd, req, fp.redirect_port);
  }
  if (path.empty() || path.size() >= 4096 || path.back() == '/')
    return fp.redirects++, redirect(fd, req, fp.redirect_port);
  bool log_down;
  {
    std::lock_guard<std::mutex> l(fp.mu);
    log_down = fp.log_failed;
  }
  if (log_down)  // can't make metadata durable: python owns PUTs
    return fp.redirects++, redirect(fd, req, fp.redirect_port);

  // mint a fid from the leased blocks; a dry pool briefly waits for the
  // python refill thread (bursts outrun it) before giving up to python
  uint32_t vid = 0, cookie = 0;
  uint64_t key = 0;
  {
    std::unique_lock<std::mutex> l(fp.mu);
    for (int attempt = 0; attempt < 2 && vid == 0; attempt++) {
      while (!fp.leases.empty()) {
        FidLease& ls = fp.leases.front();
        if (ls.next >= ls.count) { fp.leases.pop_front(); continue; }
        vid = ls.vid;
        key = ls.base + ls.next;
        cookie = ls.cookie;
        ls.next++;
        fp.lease_remaining--;
        break;
      }
      if (vid == 0 && attempt == 0)
        fp.lease_cv.wait_for(l, std::chrono::milliseconds(500),
                             [&] { return !fp.leases.empty(); });
    }
  }
  if (vid == 0)
    return fp.redirects++, redirect(fd, req, fp.redirect_port);
  auto vol = find_volume(fp.vol_plane_id, vid);
  if (!vol || !vol->writable)
    return fp.redirects++, redirect(fd, req, fp.redirect_port);

  // build + append the needle record (same wire as handle_put; fresh
  // keys never collide, so no dedup/cookie-check pass is needed)
  const uint8_t* data = is_multipart ? part.data() : req.body.data();
  uint32_t dlen =
      (uint32_t)(is_multipart ? part.size() : req.body.size());
  uint8_t flags = kFlagHasLastModified;
  if (!ct.empty()) flags |= kFlagHasMime;
  uint64_t now_secs = (uint64_t)time(nullptr);
  int32_t size = dlen ? (int32_t)(4 + dlen + 1 +
                                  ((flags & kFlagHasMime) ? 1 + ct.size() : 0) +
                                  5)
                      : 0;
  uint32_t crc = crc32c(data, dlen);
  int64_t total = actual_size(size, vol->version);
  std::vector<uint8_t> blob(total, 0);
  uint8_t* p = blob.data();
  put_u32(p, cookie);
  put_u64(p + 4, key);
  put_u32(p + 12, (uint32_t)size);
  int64_t off = kHeaderSize;
  if (dlen) {
    put_u32(p + off, dlen);
    off += 4;
    memcpy(p + off, data, dlen);
    off += dlen;
    p[off++] = flags;
    if (flags & kFlagHasMime) {
      p[off++] = (uint8_t)ct.size();
      memcpy(p + off, ct.data(), ct.size());
      off += ct.size();
    }
    for (int i = 0; i < 5; i++)
      p[off + i] = (uint8_t)(now_secs >> (32 - 8 * i));
    off += 5;
  }
  put_u32(p + off, crc);
  off += 4;
  int64_t ns_off = vol->version == 3 ? off : -1;
  uint64_t ns = 0;
  // socket writes (redirect/respond) happen OUTSIDE vol->mu: a slow
  // client must not stall the volume's whole IO (cf. handle_put's
  // goto-frozen structure)
  int append_rc = 1;  // 1 frozen, 0 ok, -1 failed
  {
    std::lock_guard<std::mutex> l(vol->mu);
    if (vol->writable)
      append_rc =
          vol->append(blob.data(), total, key, size, ns_off, &ns) < 0 ? -1
                                                                      : 0;
  }
  if (append_rc > 0)
    return fp.redirects++, redirect(fd, req, fp.redirect_port);
  if (append_rc < 0)
    return respond_json(fd, req, 500, "{\"error\":\"append failed\"}");
  if (!ns) ns = now_secs * 1000000000ull;

  HotEntry e;
  e.vid = vid;
  e.key = key;
  e.cookie = cookie;
  e.size = dlen;
  e.crc = crc;
  e.mtime_ns = ns;
  e.mime = ct;
  bool logged;
  {
    std::lock_guard<std::mutex> l(fp.mu);
    logged = hotlog_append(fp, path, e);
    if (logged) fp.map[path] = std::move(e);
  }
  if (!logged) {
    // never ack what the restart path can't recover; the needle becomes
    // an unreferenced orphan (vacuum reclaims it)
    return respond_json(fd, req, 500, "{\"error\":\"hot log write failed\"}");
  }
  fp.native_puts++;
  std::string name = path.substr(path.rfind('/') + 1);
  std::string out = "{\"name\": \"" + json_escape(name) +
                    "\", \"size\": " + std::to_string(dlen) + "}";
  respond_json(fd, req, 201, out);
}

void handle_filer_get(FilerPlane& fp, int fd, const Request& req,
                      const std::string& path) {
  // every validator except If-None-Match defers to python (the volume
  // plane's one-decision-point rule): If-Range especially — serving a
  // 206 against a stale validator would let a client splice new bytes
  // onto an old partial download
  if (!req.query.empty() || !req.header("if-modified-since").empty() ||
      !req.header("if-range").empty() || !req.header("if-match").empty() ||
      !req.header("if-unmodified-since").empty())
    return fp.redirects++, redirect(fd, req, fp.redirect_port);
  HotEntry e;
  {
    std::lock_guard<std::mutex> l(fp.mu);
    auto it = fp.map.find(path);
    if (it == fp.map.end())
      return fp.redirects++, redirect(fd, req, fp.redirect_port);
    e = it->second;
  }
  auto vol = find_volume(fp.vol_plane_id, e.vid);
  if (!vol)
    return fp.redirects++, redirect(fd, req, fp.redirect_port);
  NeedleValue nv{0, 0};
  std::shared_ptr<FdOwner> ref;
  {
    std::lock_guard<std::mutex> l(vol->mu);
    auto it = vol->map.find(e.key);
    if (it == vol->map.end()) {
      vol->catchup();
      it = vol->map.find(e.key);
    }
    if (it != vol->map.end()) nv = it->second;
    if (nv.stored_offset != 0 && nv.size >= 0) ref = vol->dat;
  }
  if (nv.stored_offset == 0 || nv.size < 0 || !ref || ref->fd < 0)
    return fp.redirects++, redirect(fd, req, fp.redirect_port);
  int64_t total = actual_size(nv.size, vol->version);
  std::vector<uint8_t> blob(total);
  int64_t got = pread(ref->fd, blob.data(), total,
                      (int64_t)nv.stored_offset * kPad);
  ParsedNeedle n;
  if (got != total ||
      !parse_record(blob.data(), total, vol->version, &n) ||
      n.cookie != e.cookie || crc32c(n.data, n.data_len) != e.crc)
    return fp.redirects++, redirect(fd, req, fp.redirect_port);
  std::string etag = "\"" + etag_hex(e.crc) + "\"";
  std::string extra = "ETag: " + etag + "\r\n";
  extra += "Last-Modified: " + http_date(e.mtime_ns / 1000000000ull) +
           "\r\n";
  std::string inm = req.header("if-none-match");
  if (!inm.empty() && inm_matches(inm, etag)) {
    fp.native_gets++;
    return respond(fd, req, 304, "text/plain", extra, nullptr, 0);
  }
  std::string ctype =
      e.mime.empty() ? "application/octet-stream" : e.mime;
  std::string rng = req.header("range");
  if (!rng.empty()) {
    // clean "bytes=lo-hi" / "bytes=lo-" only, mirroring the python
    // filer's _parse_range clamp exactly on this subset; suffix forms,
    // multi-ranges, malformed and unsatisfiable specs defer to python
    // (which owns the 416 / ServeContent-leniency edge semantics)
    uint64_t start = 0, hi = 0;
    bool has_hi = false;
    bool clean = parse_clean_range(rng, &start, &hi, &has_hi);
    uint64_t size = n.data_len;
    uint64_t stop = has_hi ? (hi + 1 < size ? hi + 1 : size) : size;
    if (!clean || start >= size || stop <= start)
      return fp.redirects++, redirect(fd, req, fp.redirect_port);
    extra += "Content-Range: bytes " + std::to_string(start) + "-" +
             std::to_string(stop - 1) + "/" + std::to_string(size) +
             "\r\n";
    fp.native_gets++;
    return respond(fd, req, 206, ctype, extra, n.data + start,
                   stop - start);
  }
  fp.native_gets++;
  respond(fd, req, 200, ctype, extra, n.data, n.data_len);
}

bool valid_utf8(const std::string& s) {
  size_t i = 0;
  while (i < s.size()) {
    unsigned char c = s[i];
    int follow;
    unsigned cp_min;
    if (c < 0x80) { i++; continue; }
    else if ((c & 0xE0) == 0xC0) { follow = 1; cp_min = 0x80; }
    else if ((c & 0xF0) == 0xE0) { follow = 2; cp_min = 0x800; }
    else if ((c & 0xF8) == 0xF0) { follow = 3; cp_min = 0x10000; }
    else return false;
    if (i + follow >= s.size()) return false;
    unsigned cp = c & (0x3F >> follow);
    for (int k = 1; k <= follow; k++) {
      unsigned char b = s[i + k];
      if ((b & 0xC0) != 0x80) return false;
      cp = (cp << 6) | (b & 0x3F);
    }
    if (cp < cp_min || cp > 0x10FFFF || (cp >= 0xD800 && cp <= 0xDFFF))
      return false;
    i += 1 + follow;
  }
  return true;
}

// Percent-decode a request path (RFC 3986; '+' stays literal, matching
// python's urllib.parse.unquote used by server/filer.py). False on a
// malformed escape OR a non-UTF8 result (escaped or raw) — the python
// absorber decodes logged paths with errors="replace", so keying the
// hot map by non-UTF8 bytes would diverge from the store path and
// python-side deletes could never invalidate the entry; those requests
// defer to python instead.
bool url_decode(const std::string& in, std::string* out) {
  out->clear();
  out->reserve(in.size());
  for (size_t i = 0; i < in.size(); i++) {
    if (in[i] != '%') {
      // raw bytes >= 0x80 decode as iso-8859-1 mojibake on the python
      // side (BaseHTTPRequestHandler), and a literal ';' is stripped
      // into urlparse's .params there — both canonicalize differently
      // from a byte-for-byte key, so defer them
      if ((unsigned char)in[i] >= 0x80 || in[i] == ';') return false;
      out->push_back(in[i]);
      continue;
    }
    if (i + 2 >= in.size() || !isxdigit((unsigned char)in[i + 1]) ||
        !isxdigit((unsigned char)in[i + 2]))
      return false;
    auto hex = [](char c) {
      return c <= '9' ? c - '0' : (c | 0x20) - 'a' + 10;
    };
    out->push_back((char)(hex(in[i + 1]) * 16 + hex(in[i + 2])));
    i += 2;
  }
  // C0 controls (esp. %00: swfp_invalidate takes NUL-terminated C
  // strings, so a key containing NUL could never be invalidated) and
  // non-UTF8 both defer to python
  for (unsigned char c : *out)
    if (c < 0x20) return false;
  return valid_utf8(*out);
}

void handle_filer_request(FilerPlane& fp, int fd, const Request& req) {
  fp.requests.fetch_add(1, std::memory_order_relaxed);
  // the python filer stores entries under the DECODED path
  // (server/filer.py unquote); hot-map keys, log records and
  // invalidations all use that same canonical form, so '/a%20b' and
  // '/a b' hit one entry rather than corrupting two. Paths the python
  // side would further normalize ('//' collapse, filer.py normalize)
  // defer to python — a hot-map key diverging from the store path could
  // never be invalidated.
  std::string path;
  if (url_decode(req.path, &path) &&
      path.find("//") == std::string::npos &&
      path.rfind(fp.prefix, 0) == 0) {
    if (req.method == "GET" || req.method == "HEAD")
      return handle_filer_get(fp, fd, req, path);
    if (req.method == "PUT" || req.method == "POST")
      return handle_filer_put(fp, fd, req, path);
  }
  fp.redirects++;
  redirect(fd, req, fp.redirect_port);
}

void filer_conn_loop(FilerPlane* srv, int fd) {
  int one = 1;
  setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof one);
  struct timeval tv{1, 0};
  setsockopt(fd, SOL_SOCKET, SO_RCVTIMEO, &tv, sizeof tv);
  std::string buf;
  Request req;
  while (!srv->stop.load(std::memory_order_relaxed)) {
    int rc = read_request(fd, buf, &req, srv->stop);
    if (rc == -1) break;
    if (rc == -2) {
      respond(fd, req, 400, "text/plain", "", nullptr, 0);
      break;
    }
    handle_filer_request(*srv, fd, req);
    if (!req.keepalive) break;
  }
  close(fd);
  srv->live_conns.fetch_sub(1, std::memory_order_relaxed);
}

void filer_acceptor_loop(FilerPlane* srv) {
  while (!srv->stop.load(std::memory_order_relaxed)) {
    int fd = accept(srv->listen_fd, nullptr, nullptr);
    if (fd < 0) {
      if (srv->stop.load(std::memory_order_relaxed)) return;
      if (errno != EINTR) usleep(20000);
      continue;
    }
    if (srv->live_conns.load(std::memory_order_relaxed) >= 1024) {
      close(fd);
      continue;
    }
    srv->live_conns.fetch_add(1, std::memory_order_relaxed);
    std::thread(filer_conn_loop, srv, fd).detach();
  }
}

}  // namespace

// ----------------------------------------------------------------- C ABI --

extern "C" {

// Starts a plane; returns its positive id, or a negative errno.
int swdp_start(const char* bind_ip, int port, int redirect_port,
               int nthreads) {
  static std::once_flag crc_once;
  std::call_once(crc_once, crc_init);
  int fd = socket(AF_INET, SOCK_STREAM, 0);
  if (fd < 0) return -errno;
  int one = 1;
  setsockopt(fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof one);
  struct sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_port = htons((uint16_t)port);
  addr.sin_addr.s_addr =
      bind_ip && *bind_ip ? inet_addr(bind_ip) : INADDR_ANY;
  if (bind(fd, (struct sockaddr*)&addr, sizeof addr) != 0 ||
      listen(fd, 256) != 0) {
    int e = errno;
    close(fd);
    return -e;
  }
  auto pl = std::make_shared<Plane>();
  pl->listen_fd = fd;
  pl->port = port;
  pl->redirect_port = redirect_port;
  (void)nthreads;  // per-connection threads; kept for ABI stability
  {
    std::lock_guard<std::mutex> l(g_planes_mu);
    pl->id = g_next_plane++;
    g_planes[pl->id] = pl;
  }
  pl->acceptor = std::thread(acceptor_loop, pl.get());
  return pl->id;
}

void swdp_stop(int plane_id) {
  std::shared_ptr<Plane> pl;
  {
    std::lock_guard<std::mutex> l(g_planes_mu);
    auto it = g_planes.find(plane_id);
    if (it == g_planes.end()) return;
    pl = it->second;
    g_planes.erase(it);
  }
  pl->stop.store(true);
  shutdown(pl->listen_fd, SHUT_RDWR);
  close(pl->listen_fd);
  pl->acceptor.join();
  // connection threads hold a raw Plane*; wait for them to notice stop
  // (1s recv ticks). If any straggle, park the plane in a graveyard so
  // the pointer stays valid for the thread's remaining lifetime.
  for (int i = 0; i < 50 && pl->live_conns.load() > 0; i++)
    usleep(100 * 1000);
  {
    std::unique_lock<std::shared_mutex> l(pl->reg.mu);
    pl->reg.vols.clear();
  }
  if (pl->live_conns.load() > 0) {
    static std::vector<std::shared_ptr<Plane>> graveyard;
    static std::mutex gm;
    std::lock_guard<std::mutex> l(gm);
    graveyard.push_back(pl);
  }
}

int swdp_add_volume(int plane_id, uint32_t vid, const char* dat_path,
                    const char* idx_path, int version, int writable) {
  auto pl = plane_of(plane_id);
  if (!pl) return -ENOENT;
  auto vol = std::make_shared<Volume>();
  vol->vid = vid;
  vol->dat_path = dat_path;
  vol->idx_path = idx_path;
  vol->version = version;
  vol->writable = writable != 0;
  if (!vol->open_files()) return -errno;
  std::unique_lock<std::shared_mutex> l(pl->reg.mu);
  pl->reg.vols[vid] = vol;
  return 0;
}

int swdp_remove_volume(int plane_id, uint32_t vid) {
  auto pl = plane_of(plane_id);
  if (!pl) return -ENOENT;
  std::unique_lock<std::shared_mutex> l(pl->reg.mu);
  return pl->reg.vols.erase(vid) ? 0 : -1;
}

int swdp_reload_volume(int plane_id, uint32_t vid) {
  auto vol = find_volume(plane_id, vid);
  if (!vol) return -1;
  std::lock_guard<std::mutex> l(vol->mu);
  // open_files swaps in fresh FdOwners; the old descriptors close when
  // the last in-flight reader releases its pinned shared_ptr
  if (!vol->open_files()) {
    int e = errno;
    // fail LOUDLY: a failed reopen after vacuum commit must not leave
    // the plane serving (and appending to) the pre-compaction inode —
    // dropping the holders + map turns every request into an explicit
    // error until a later reload succeeds
    vol->dat.reset();
    vol->idx.reset();
    vol->map.clear();
    vol->idx_loaded = 0;
    return -(e ? e : EIO);
  }
  return 0;
}

int swdp_set_writable(int plane_id, uint32_t vid, int writable) {
  auto vol = find_volume(plane_id, vid);
  if (!vol) return -1;
  std::lock_guard<std::mutex> l(vol->mu);
  vol->writable = writable != 0;
  return 0;
}

// Append a caller-built record (Python mutation funnel). Stamps a fresh
// monotonic appendAtNs at ns_off when ns_off >= 0. Returns the byte offset
// or a negative errno. idx_size: entry size field (-1 tombstone).
int64_t swdp_append_record(int plane_id, uint32_t vid, uint64_t key,
                           uint8_t* blob, int64_t len, int32_t idx_size,
                           int64_t ns_off, uint64_t* ns_out) {
  auto vol = find_volume(plane_id, vid);
  if (!vol) return -ENOENT;
  std::lock_guard<std::mutex> l(vol->mu);
  int64_t off = vol->append(blob, len, key, idx_size, ns_off, ns_out);
  return off < 0 ? -(int64_t)(errno ? errno : EIO) : off;
}

// Read the full record blob for a needle. *out is malloc'd; caller frees
// via swdp_free. Returns blob length, 0 if absent/deleted, negative errno.
int64_t swdp_read(int plane_id, uint32_t vid, uint64_t key, uint8_t** out) {
  auto vol = find_volume(plane_id, vid);
  if (!vol) return -ENOENT;
  NeedleValue nv{0, 0};
  std::shared_ptr<FdOwner> ref;
  {
    std::lock_guard<std::mutex> l(vol->mu);
    auto it = vol->map.find(key);
    if (it == vol->map.end()) {
      vol->catchup();
      it = vol->map.find(key);
    }
    if (it != vol->map.end()) nv = it->second;
    // see handle_get: pin the fd owner the snapshot refers to across
    // reloads (shared_ptr copy, no dup syscall)
    if (nv.stored_offset != 0 && nv.size >= 0) ref = vol->dat;
  }
  if (nv.stored_offset == 0 || nv.size < 0) return 0;
  if (!ref || ref->fd < 0) return -EIO;
  int64_t total = actual_size(nv.size, vol->version);
  uint8_t* buf = (uint8_t*)malloc(total);
  if (!buf) return -ENOMEM;
  int64_t got = pread(ref->fd, buf, total, (int64_t)nv.stored_offset * kPad);
  if (got != total) {
    free(buf);
    return -EIO;
  }
  *out = buf;
  return total;
}

void swdp_free(uint8_t* p) { free(p); }

int swdp_volume_stats(int plane_id, uint32_t vid, int64_t* file_count,
                      int64_t* file_bytes, int64_t* del_count,
                      int64_t* del_bytes, uint64_t* max_key,
                      int64_t* dat_size) {
  auto vol = find_volume(plane_id, vid);
  if (!vol) return -1;
  std::lock_guard<std::mutex> l(vol->mu);
  vol->catchup();
  if (file_count) *file_count = vol->file_count;
  if (file_bytes) *file_bytes = vol->file_bytes;
  if (del_count) *del_count = vol->del_count;
  if (del_bytes) *del_bytes = vol->del_bytes;
  if (max_key) *max_key = vol->max_key;
  if (dat_size) *dat_size = vol->dat_size;
  return 0;
}

// ---------------------------------------------------------- bench client --
// Native benchmark driver: one keepalive connection looping PUT or GET
// over a fid list (the compiled-client counterpart of the reference's Go
// `weed benchmark` loop, benchmark.go:73-111). Returns the number of
// 2xx responses; per-request latencies (ns) land in out_lat_ns.

static bool bench_connect(const char* host, int port, int* out_fd) {
  struct sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_port = htons((uint16_t)port);
  addr.sin_addr.s_addr = inet_addr(host);
  if (addr.sin_addr.s_addr == INADDR_NONE) {
    struct addrinfo hints{}, *res = nullptr;
    hints.ai_family = AF_INET;
    hints.ai_socktype = SOCK_STREAM;
    if (getaddrinfo(host, nullptr, &hints, &res) != 0 || !res) {
      if (res) freeaddrinfo(res);
      return false;
    }
    addr.sin_addr = ((struct sockaddr_in*)res->ai_addr)->sin_addr;
    freeaddrinfo(res);
  }
  int fd = socket(AF_INET, SOCK_STREAM, 0);
  if (fd < 0) return false;
  if (connect(fd, (struct sockaddr*)&addr, sizeof addr) != 0) {
    close(fd);
    return false;
  }
  int one = 1;
  setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof one);
  struct timeval tv{30, 0};
  setsockopt(fd, SOL_SOCKET, SO_RCVTIMEO, &tv, sizeof tv);
  *out_fd = fd;
  return true;
}

// Read one HTTP response (headers + content-length body); returns status
// or -1. `buf` carries leftover pipelined bytes between calls.
static int bench_read_response(int fd, std::string& buf) {
  size_t hdr_end;
  while ((hdr_end = buf.find("\r\n\r\n")) == std::string::npos) {
    char tmp[8192];
    ssize_t n = recv(fd, tmp, sizeof tmp, 0);
    if (n <= 0) return -1;
    buf.append(tmp, n);
  }
  if (buf.size() < 12) return -1;
  int status = atoi(buf.c_str() + 9);
  size_t clen = 0;
  size_t p = buf.find("ontent-Length:");
  if (p != std::string::npos && p < hdr_end)
    clen = (size_t)strtoull(buf.c_str() + p + 14, nullptr, 10);
  size_t total = hdr_end + 4 + clen;
  char tmp[8192];
  while (buf.size() < total) {
    ssize_t n = recv(fd, tmp, sizeof tmp, 0);
    if (n <= 0) return -1;
    buf.append(tmp, n);
  }
  buf.erase(0, total);
  return status;
}

extern "C" int64_t swdp_bench(const char* host, int port, int is_put,
                              const char** fids, int nfids,
                              const uint8_t* payload, int64_t plen,
                              int64_t* out_lat_ns) {
  int fd;
  if (!bench_connect(host, port, &fd)) return -errno;
  std::string head;
  head.reserve(512);
  std::string buf;
  int64_t ok = 0;
  for (int i = 0; i < nfids; i++) {
    struct timespec t0, t1;
    clock_gettime(CLOCK_MONOTONIC, &t0);
    head.clear();
    if (is_put) {
      head += "PUT /";
      head += fids[i];
      head += " HTTP/1.1\r\nHost: bench\r\nContent-Type: "
              "application/octet-stream\r\nContent-Length: ";
      head += std::to_string(plen);
      head += "\r\n\r\n";
      // head + body in ONE send: small-file PUTs are syscall-bound on
      // sandboxed kernels, and two sends also invite a delayed-ACK stall
      head.append((const char*)payload, (size_t)plen);
      send_all(fd, head.data(), head.size());
    } else {
      head += "GET /";
      head += fids[i];
      head += " HTTP/1.1\r\nHost: bench\r\n\r\n";
      send_all(fd, head.data(), head.size());
    }
    int status = bench_read_response(fd, buf);
    if (status < 0) {  // dropped keepalive: reconnect once
      close(fd);
      buf.clear();
      if (!bench_connect(host, port, &fd)) break;
      continue;
    }
    clock_gettime(CLOCK_MONOTONIC, &t1);
    if (out_lat_ns)
      out_lat_ns[i] = (t1.tv_sec - t0.tv_sec) * 1000000000LL +
                      (t1.tv_nsec - t0.tv_nsec);
    if (status >= 200 && status < 300) ok++;
  }
  close(fd);
  return ok;
}

uint64_t swdp_request_count(int plane_id) {
  auto pl = plane_of(plane_id);
  return pl ? pl->requests.load() : 0;
}

// GETs served zero-copy via sendfile(2) since plane start (ISSUE 9).
uint64_t swdp_sendfile_count(int plane_id) {
  auto pl = plane_of(plane_id);
  return pl ? pl->sendfiles.load() : 0;
}

// Minimum body size for the sendfile path; -1 disables it (the A/B OFF
// arm / SWFS_ZEROCOPY=0). Returns 0 on success.
int swdp_set_zerocopy_min(int plane_id, int64_t min_bytes) {
  auto pl = plane_of(plane_id);
  if (!pl) return -1;
  pl->zerocopy_min.store(min_bytes);
  return 0;
}

// ------------------------------------------------- filer hot plane ABI --

// Starts a filer hot plane bound to `port`; non-hot requests 307 to
// `redirect_port` (the python filer listener). `vol_plane_id` is the
// co-located volume plane whose registry serves the needle IO.
// `log_path` is the hot entry log the python filer absorbs.
int swfp_start(const char* bind_ip, int port, int redirect_port,
               int vol_plane_id, const char* log_path, const char* prefix,
               int64_t max_body) {
  static std::once_flag crc_once;
  std::call_once(crc_once, crc_init);
  int lfd = open(log_path, O_WRONLY | O_CREAT | O_APPEND, 0644);
  if (lfd < 0) return -errno;
  int fd = socket(AF_INET, SOCK_STREAM, 0);
  if (fd < 0) {
    close(lfd);
    return -errno;
  }
  int one = 1;
  setsockopt(fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof one);
  struct sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_port = htons((uint16_t)port);
  addr.sin_addr.s_addr =
      bind_ip && *bind_ip ? inet_addr(bind_ip) : INADDR_ANY;
  if (bind(fd, (struct sockaddr*)&addr, sizeof addr) != 0 ||
      listen(fd, 256) != 0) {
    int e = errno;
    close(fd);
    close(lfd);
    return -e;
  }
  auto fp = std::make_shared<FilerPlane>();
  fp->listen_fd = fd;
  fp->log_fd = lfd;
  fp->port = port;
  fp->redirect_port = redirect_port;
  fp->vol_plane_id = vol_plane_id;
  if (prefix && *prefix) fp->prefix = prefix;
  if (max_body > 0) fp->max_body = (size_t)max_body;
  {
    std::lock_guard<std::mutex> l(g_fplanes_mu);
    fp->id = g_next_fplane++;
    g_fplanes[fp->id] = fp;
  }
  fp->acceptor = std::thread(filer_acceptor_loop, fp.get());
  return fp->id;
}

void swfp_stop(int id) {
  std::shared_ptr<FilerPlane> fp;
  {
    std::lock_guard<std::mutex> l(g_fplanes_mu);
    auto it = g_fplanes.find(id);
    if (it == g_fplanes.end()) return;
    fp = it->second;
    g_fplanes.erase(it);
  }
  fp->stop.store(true);
  shutdown(fp->listen_fd, SHUT_RDWR);
  close(fp->listen_fd);
  fp->acceptor.join();
  for (int i = 0; i < 300 && fp->live_conns.load() > 0; i++)
    usleep(10000);
}

// Feed a block of `count` fids (vid, base_key..base_key+count-1, cookie)
// from a batched master assign.
int swfp_add_lease(int id, uint32_t vid, uint64_t base_key, uint32_t cookie,
                   uint32_t count) {
  auto fp = fplane_of(id);
  if (!fp) return -ENOENT;
  {
    std::lock_guard<std::mutex> l(fp->mu);
    fp->leases.push_back(FidLease{vid, base_key, cookie, 0, count});
    fp->lease_remaining += count;
  }
  fp->lease_cv.notify_all();
  return 0;
}

uint64_t swfp_lease_remaining(int id) {
  auto fp = fplane_of(id);
  if (!fp) return 0;
  std::lock_guard<std::mutex> l(fp->mu);
  return fp->lease_remaining;
}

// Stand the fast path down: stop acking native PUTs (they redirect to
// python instead). Called when the python absorber detects hot-log
// corruption — acking writes whose metadata can never be absorbed would
// silently lose them.
int swfp_disable_log(int id) {
  auto fp = fplane_of(id);
  if (!fp) return -ENOENT;
  std::lock_guard<std::mutex> l(fp->mu);
  fp->log_failed = true;
  return 0;
}

// Drop a path from the hot map (python-side mutation: delete, rename,
// S3 overwrite). Returns 1 when present.
int swfp_invalidate(int id, const char* path) {
  auto fp = fplane_of(id);
  if (!fp) return -ENOENT;
  std::lock_guard<std::mutex> l(fp->mu);
  return fp->map.erase(path) ? 1 : 0;
}

// Drop a path and everything beneath it (recursive delete / rename).
int swfp_invalidate_prefix(int id, const char* path) {
  auto fp = fplane_of(id);
  if (!fp) return -ENOENT;
  std::string p(path);
  while (p.size() > 1 && p.back() == '/') p.pop_back();
  std::string prefix = p + "/";
  int n = 0;
  std::lock_guard<std::mutex> l(fp->mu);
  n += (int)fp->map.erase(p);
  for (auto it = fp->map.begin(); it != fp->map.end();) {
    if (it->first.rfind(prefix, 0) == 0) {
      it = fp->map.erase(it);
      n++;
    } else {
      ++it;
    }
  }
  return n;
}

int swfp_stats(int id, uint64_t* requests, uint64_t* native_puts,
               uint64_t* native_gets, uint64_t* redirects) {
  auto fp = fplane_of(id);
  if (!fp) return -ENOENT;
  if (requests) *requests = fp->requests.load();
  if (native_puts) *native_puts = fp->native_puts.load();
  if (native_gets) *native_gets = fp->native_gets.load();
  if (redirects) *redirects = fp->redirects.load();
  return 0;
}

}  // extern "C"
