"""On-read image transforms: resize/crop + EXIF orientation fix.

Rebuild of /root/reference/weed/images/ (resizing.go `Resized`, hooked in
volume_server_handlers_read.go:294; orientation.go). PIL replaces Go's
image packages; absent PIL the functions pass bytes through untouched.
"""

from __future__ import annotations

import io

try:
    from PIL import Image, ImageOps

    _HAS_PIL = True
except ImportError:  # pragma: no cover
    _HAS_PIL = False


IMAGE_MIMES = {"image/jpeg", "image/png", "image/gif", "image/webp"}


def is_image(mime: str, name: str = "") -> bool:
    if mime in IMAGE_MIMES:
        return True
    return name.lower().endswith((".jpg", ".jpeg", ".png", ".gif", ".webp"))


def fix_jpg_orientation(data: bytes) -> bytes:
    """Apply the EXIF orientation tag and strip it (orientation.go)."""
    if not _HAS_PIL:
        return data
    try:
        img = Image.open(io.BytesIO(data))
        if img.format != "JPEG":
            return data
        fixed = ImageOps.exif_transpose(img)
        if fixed is img:
            return data
        out = io.BytesIO()
        fixed.save(out, format="JPEG", quality=95)
        return out.getvalue()
    except Exception:  # noqa: BLE001 - never fail a read over EXIF
        return data


def resized(data: bytes, width: int = 0, height: int = 0,
            mode: str = "") -> tuple[bytes, int, int]:
    """Resize/crop on read (resizing.go Resized):
    mode "" = proportional fit, "fit" = letterboxed fit, "fill" = center crop.
    -> (bytes, w, h); passthrough when no resize applies."""
    if not _HAS_PIL or (not width and not height):
        return data, width, height
    try:
        img = Image.open(io.BytesIO(data))
        fmt = img.format or "PNG"
        ow, oh = img.size
        if width == 0:
            width = ow * height // oh
        if height == 0:
            height = oh * width // ow
        if mode == "fill":
            out_img = ImageOps.fit(img, (width, height))
        elif mode == "fit":
            out_img = ImageOps.pad(img.convert("RGB"), (width, height))
        else:
            img.thumbnail((width, height))
            out_img = img
        out = io.BytesIO()
        out_img.save(out, format=fmt)
        return out.getvalue(), out_img.width, out_img.height
    except Exception:  # noqa: BLE001
        return data, width, height
