"""S3 gateway circuit breaker.

Rebuild of /root/reference/weed/s3api/s3api_circuit_breaker.go: per-action
concurrency limits (request count and in-flight bytes), globally and per
bucket, loaded from the filer at /etc/s3/circuit_breaker.json (the
s3_pb.S3CircuitBreakerConfig shape) and hot-reloadable. A request past any
enabled limit is rejected with 503 TooManyRequests before it touches the
filer, exactly like the reference's Limit() wrapper.

Limit keys are "<Action>:Count" and "<Action>:MB" (the reference's
LimitTypeCount / LimitTypeMB).
"""

from __future__ import annotations

import json
import threading

CB_CONFIG_DIR = "/etc/s3"
CB_CONFIG_FILE = "circuit_breaker.json"


class TooManyRequests(Exception):
    pass


def load_filer_config(stub) -> dict | None:
    """Read /etc/s3/circuit_breaker.json from the filer (None if absent)."""
    from ..pb import filer_pb2

    try:
        resp = stub.LookupDirectoryEntry(
            filer_pb2.LookupDirectoryEntryRequest(
                directory=CB_CONFIG_DIR, name=CB_CONFIG_FILE), timeout=5)
    except Exception:
        return None
    if not resp.entry.content:
        return None
    try:
        return json.loads(resp.entry.content)
    except json.JSONDecodeError:
        return None


def _limits(options: dict) -> dict[str, int]:
    """{"Read:Count": 10, "Write:MB": 64, ...} -> normalized int map."""
    out = {}
    for k, v in (options or {}).items():
        action, _, kind = k.partition(":")
        kind = kind or "Count"
        mult = (1 << 20) if kind.upper() == "MB" else 1
        out[f"{action}:{'MB' if mult > 1 else 'Count'}"] = int(v) * mult
    return out


class CircuitBreaker:
    def __init__(self, config: dict | None = None):
        self._lock = threading.Lock()
        self._counts: dict[str, int] = {}  # scope key -> in-flight requests
        self._bytes: dict[str, int] = {}  # scope key -> in-flight bytes
        self.enabled = False
        self.global_limits: dict[str, int] = {}
        self.bucket_limits: dict[str, dict[str, int]] = {}
        if config:
            self.load(config)

    def load(self, config: dict) -> None:
        """Accepts the s3_pb.S3CircuitBreakerConfig JSON shape."""
        glob = config.get("global", {}) or {}
        with self._lock:
            self.enabled = bool(glob.get("enabled", False))
            self.global_limits = _limits(glob.get("actions"))
            self.bucket_limits = {}
            for bucket, opts in (config.get("buckets") or {}).items():
                if opts.get("enabled", True):
                    self.bucket_limits[bucket] = _limits(opts.get("actions"))

    def to_config(self) -> dict:
        def denorm(limits):
            return {k: (v >> 20 if k.endswith(":MB") else v)
                    for k, v in limits.items()}

        return {
            "global": {"enabled": self.enabled,
                       "actions": denorm(self.global_limits)},
            "buckets": {b: {"enabled": True, "actions": denorm(l)}
                        for b, l in self.bucket_limits.items()},
        }

    # -- request gate ------------------------------------------------------

    def acquire(self, action: str, bucket: str, nbytes: int = 0):
        """Admit one request; raises TooManyRequests past any enabled limit.
        Returns a release() callable (use in a finally)."""
        if not self.enabled:
            return lambda: None
        scopes = [("", self.global_limits)]
        if bucket in self.bucket_limits:
            scopes.append((bucket, self.bucket_limits[bucket]))
        taken: list[tuple[str, str, int]] = []  # (count_key, bytes_key, n)
        with self._lock:
            for scope, limits in scopes:
                ck, bk = f"{scope}/{action}:Count", f"{scope}/{action}:MB"
                climit = limits.get(f"{action}:Count")
                blimit = limits.get(f"{action}:MB")
                if climit is not None and self._counts.get(ck, 0) >= climit:
                    self._rollback(taken)
                    raise TooManyRequests(
                        f"too many {action} requests"
                        + (f" for bucket {scope}" if scope else ""))
                if blimit is not None and nbytes and \
                        self._bytes.get(bk, 0) + nbytes > blimit:
                    self._rollback(taken)
                    raise TooManyRequests(
                        f"too many {action} bytes in flight"
                        + (f" for bucket {scope}" if scope else ""))
                self._counts[ck] = self._counts.get(ck, 0) + 1
                self._bytes[bk] = self._bytes.get(bk, 0) + nbytes
                taken.append((ck, bk, nbytes))

        released = False

        def release():
            nonlocal released
            if released:
                return
            released = True
            with self._lock:
                self._rollback(taken)

        return release

    def _rollback(self, taken) -> None:
        """Caller holds self._lock."""
        for ck, bk, nbytes in taken:
            self._counts[ck] = self._counts.get(ck, 0) - 1
            self._bytes[bk] = self._bytes.get(bk, 0) - nbytes
