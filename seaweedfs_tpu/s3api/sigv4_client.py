"""Client-side AWS Signature V4 signer.

Counterpart of the gateway-side verifier in auth.py (reference:
/root/reference/weed/s3api/auth_signature_v4.go). Used by the replication
S3 sink and by tests to produce authenticated requests against any S3
endpoint, including this framework's own gateway.
"""

from __future__ import annotations

import hashlib
import hmac
import time
import urllib.parse


def _uri_encode(s: str, keep_slash: bool = False) -> str:
    safe = "-_.~" + ("/" if keep_slash else "")
    return urllib.parse.quote(s, safe=safe)


def sign_request(method: str, url: str, payload: bytes, access_key: str,
                 secret_key: str, region: str = "us-east-1",
                 service: str = "s3", amz_now: time.struct_time | None = None
                 ) -> dict[str, str]:
    """-> headers dict (Host, X-Amz-Date, X-Amz-Content-Sha256,
    Authorization) for the given request."""
    u = urllib.parse.urlparse(url)
    now = amz_now or time.gmtime()
    amz_date = time.strftime("%Y%m%dT%H%M%SZ", now)
    date = amz_date[:8]
    payload_hash = hashlib.sha256(payload).hexdigest()
    host = u.netloc

    headers = {
        "Host": host,
        "X-Amz-Date": amz_date,
        "X-Amz-Content-Sha256": payload_hash,
    }
    signed_names = sorted(h.lower() for h in headers)
    canonical_headers = "".join(
        f"{name}:{headers[next(h for h in headers if h.lower() == name)].strip()}\n"
        for name in signed_names)
    signed_headers = ";".join(signed_names)

    query_pairs = urllib.parse.parse_qsl(u.query, keep_blank_values=True)
    canonical_query = "&".join(
        f"{_uri_encode(k)}={_uri_encode(v)}"
        for k, v in sorted(query_pairs))

    canonical_request = "\n".join([
        method,
        _uri_encode(urllib.parse.unquote(u.path) or "/", keep_slash=True),
        canonical_query,
        canonical_headers,
        signed_headers,
        payload_hash,
    ])
    scope = f"{date}/{region}/{service}/aws4_request"
    string_to_sign = "\n".join([
        "AWS4-HMAC-SHA256", amz_date, scope,
        hashlib.sha256(canonical_request.encode()).hexdigest()])

    def h(key: bytes, msg: str) -> bytes:
        return hmac.new(key, msg.encode(), hashlib.sha256).digest()

    k = h(("AWS4" + secret_key).encode(), date)
    k = h(k, region)
    k = h(k, service)
    k = h(k, "aws4_request")
    signature = hmac.new(k, string_to_sign.encode(),
                         hashlib.sha256).hexdigest()

    headers["Authorization"] = (
        f"AWS4-HMAC-SHA256 Credential={access_key}/{scope}, "
        f"SignedHeaders={signed_headers}, Signature={signature}")
    return headers


def presign_url(method: str, url: str, access_key: str, secret_key: str,
                *, expires: int = 3600, region: str = "us-east-1",
                service: str = "s3",
                amz_now: time.struct_time | None = None) -> str:
    """Generate a presigned URL (query-string auth, auth_signature_v4.go's
    presigned flow): anyone holding the URL can perform `method` until
    X-Amz-Date + X-Amz-Expires."""
    u = urllib.parse.urlparse(url)
    now = amz_now or time.gmtime()
    amz_date = time.strftime("%Y%m%dT%H%M%SZ", now)
    date = amz_date[:8]
    scope = f"{date}/{region}/{service}/aws4_request"
    qs = dict(urllib.parse.parse_qsl(u.query, keep_blank_values=True))
    qs.update({
        "X-Amz-Algorithm": "AWS4-HMAC-SHA256",
        "X-Amz-Credential": f"{access_key}/{scope}",
        "X-Amz-Date": amz_date,
        "X-Amz-Expires": str(expires),
        "X-Amz-SignedHeaders": "host",
    })
    canonical_query = "&".join(f"{_uri_encode(k)}={_uri_encode(v)}"
                               for k, v in sorted(qs.items()))
    canonical_request = "\n".join([
        method,
        _uri_encode(urllib.parse.unquote(u.path) or "/", keep_slash=True),
        canonical_query,
        f"host:{u.netloc}\n",
        "host",
        "UNSIGNED-PAYLOAD",
    ])
    string_to_sign = "\n".join([
        "AWS4-HMAC-SHA256", amz_date, scope,
        hashlib.sha256(canonical_request.encode()).hexdigest()])

    def h(key: bytes, msg: str) -> bytes:
        return hmac.new(key, msg.encode(), hashlib.sha256).digest()

    k = h(("AWS4" + secret_key).encode(), date)
    k = h(k, region)
    k = h(k, service)
    k = h(k, "aws4_request")
    sig = hmac.new(k, string_to_sign.encode(), hashlib.sha256).hexdigest()
    qs["X-Amz-Signature"] = sig
    return u._replace(query=urllib.parse.urlencode(qs)).geturl()


def _v2_sign(secret_key: str, string_to_sign: str) -> str:
    import base64

    return base64.b64encode(hmac.new(
        secret_key.encode(), string_to_sign.encode(),
        hashlib.sha1).digest()).decode()


def _v2_subresource(query: str) -> str:
    """Signed subresource portion of the query, in the verifier's order."""
    from .auth import IdentityAccessManagement as _IAM

    qs = urllib.parse.parse_qs(query, keep_blank_values=True)
    sub = []
    for key in _IAM._V2_SUBRESOURCES:
        if key in qs:
            v = qs[key][0]
            sub.append(f"{key}={v}" if v else key)
    return "&".join(sub)


def _v2_string_to_sign(method: str, path: str, query: str, date: str,
                       content_type: str = "", content_md5: str = "",
                       amz_headers: dict | None = None) -> str:
    canonical_amz = "".join(
        f"{k.lower()}:{v}\n" for k, v in sorted((amz_headers or {}).items()))
    resource = urllib.parse.quote(urllib.parse.unquote(path), safe="/-_.~")
    sub = _v2_subresource(query)
    if sub:
        resource += "?" + sub
    return "\n".join([method, content_md5, content_type, date,
                      canonical_amz + resource])


def sign_request_v2(method: str, url: str, access_key: str, secret_key: str,
                    content_type: str = "") -> dict[str, str]:
    """Legacy AWS signature v2 headers (counterpart of the gateway's
    _verify_v2; auth_signature_v2.go signatureV2)."""
    u = urllib.parse.urlparse(url)
    date = time.strftime("%a, %d %b %Y %H:%M:%S GMT", time.gmtime())
    sts = _v2_string_to_sign(method, u.path or "/", u.query, date,
                             content_type)
    sig = _v2_sign(secret_key, sts)
    headers = {"Date": date, "Authorization": f"AWS {access_key}:{sig}"}
    if content_type:
        headers["Content-Type"] = content_type
    return headers


def presign_url_v2(method: str, url: str, access_key: str, secret_key: str,
                   *, expires: int = 3600) -> str:
    """Legacy presigned URL: ?AWSAccessKeyId&Expires&Signature."""
    u = urllib.parse.urlparse(url)
    exp = str(int(time.time()) + expires)
    sts = _v2_string_to_sign(method, u.path or "/", u.query, exp)
    sig = _v2_sign(secret_key, sts)
    qs = dict(urllib.parse.parse_qsl(u.query, keep_blank_values=True))
    qs.update({"AWSAccessKeyId": access_key, "Expires": exp,
               "Signature": sig})
    return u._replace(query=urllib.parse.urlencode(qs)).geturl()
