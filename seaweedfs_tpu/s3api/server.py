"""S3-compatible gateway over the filer.

Rebuild of /root/reference/weed/s3api/ (s3api_server.go router,
s3api_bucket_handlers.go, s3api_object_handlers.go, filer_multipart.go,
s3api_object_tagging_handlers.go). Buckets are filer directories under
/buckets/<name>; object bytes are filer entries. Multipart parts are staged
under /buckets/.uploads/<uploadId> and merged into one chunk list at
complete time — chunk fids are re-based, bytes are never copied (the same
trick filer_multipart.go:COMPLETEMULTIPARTUPLOAD uses).
"""

from __future__ import annotations

import hashlib
import json
import threading
import time
import urllib.parse
import uuid
import xml.etree.ElementTree as ET
from http.server import BaseHTTPRequestHandler

from ..operation import thread_session as _session

from ..utils.httpd import TunedThreadingHTTPServer

import grpc

from ..cluster.metaring import (
    EPOCH_HEADER,
    WRONG_SHARD_STATUS,
    wrong_shard_of,
)
from ..pb import filer_pb2, rpc
from ..utils import glog, trace
from ..utils.http import url_for
from ..utils.stats import (
    S3_REQUEST_HISTOGRAM,
    gather,
    metrics_content_type,
    status_base,
)
from .auth import AuthError, Identity, IdentityAccessManagement
from .circuit_breaker import CircuitBreaker, TooManyRequests, load_filer_config
from .policy import BucketPolicy, PolicyError

# extended-attr keys (s3_constants in the reference)
ACL_KEY = "Seaweed-X-Amz-Acl"
POLICY_KEY = "Seaweed-X-Amz-Policy"
READONLY_KEY = "Seaweed-Read-Only"
CANNED_ACLS = ("private", "public-read", "public-read-write",
               "authenticated-read")

BUCKETS_DIR = "/buckets"
UPLOADS_DIR = "/buckets/.uploads"
S3_NS = "http://s3.amazonaws.com/doc/2006-03-01/"


class S3Error(Exception):
    def __init__(self, status: int, code: str, message: str,
                 retry_after_s: float = 0.0):
        super().__init__(message)
        self.status = status
        self.code = code
        # 503s carry an honest Retry-After so SDK clients back off with
        # jitter instead of hammering or failing hard (ISSUE 8)
        self.retry_after_s = retry_after_s


class S3Server:
    def __init__(self, *, port: int = 8333, filer: str = "localhost:8888",
                 identities: list[Identity] | None = None):
        self.port = port
        self.filer = filer
        self.filer_grpc = rpc.grpc_address(filer)
        self.iam = IdentityAccessManagement(identities)
        self.circuit_breaker = CircuitBreaker()
        # QoS plane (ISSUE 8): per-tenant (access key / bucket /
        # anonymous) token-bucket admission ahead of every other check;
        # unconfigured env = observe-only, never rejects
        from ..qos import TenantAdmission

        self.qos_admission = TenantAdmission("s3")
        # metadata ring (ISSUE 19): when the filer namespace is sharded,
        # route every metadata op to the shard owning its parent
        # directory; unsharded deployments see a 1-entry ring and the
        # seed filer answers everything (zero behavior change)
        from ..wdclient import MetaRingClient

        self.ring_client = MetaRingClient(filer_grpc=self.filer_grpc)
        self._cb_loaded_at = 0.0
        self._http_server = None
        self._started_at = time.time()

    @property
    def address(self) -> str:
        return f"localhost:{self.port}"

    def start(self) -> None:
        trace.set_identity("s3", self.address)
        # HTTPS public ingress (ISSUE 9): same gate as the volume/filer
        # planes, so SWFS_HTTPS moves all four harness shapes onto TLS
        from ..security.tls import load_http_server_context

        https_ctx = load_http_server_context("s3")
        self._http_server = TunedThreadingHTTPServer(
            ("", self.port), _make_handler(self), ssl_context=https_ctx)
        threading.Thread(target=self._http_server.serve_forever,
                         daemon=True).start()
        # control plane (s3.proto SeaweedS3.Configure; s3api_server.go
        # registers the same service beside the HTTP handlers). With
        # [grpc.s3] in security.toml the port requires mTLS like the
        # reference's LoadServerTLS gate and binds all interfaces;
        # plaintext deployments stay LOOPBACK-ONLY — Configure replaces
        # the whole identity set and must not be reachable off-host
        # unauthenticated.
        self._grpc_server = rpc.new_server()
        creds = rpc.add_servicer(self._grpc_server, rpc.S3_SERVICE,
                                 _S3Control(self), component="s3",
                                 address=self.address)
        bind_ip = "[::]" if creds is not None else "127.0.0.1"
        rpc.serve_port(self._grpc_server,
                       f"{bind_ip}:{rpc.derived_grpc_port(self.port)}",
                       "s3", creds=creds)
        self._grpc_server.start()
        glog.info(f"s3 gateway on :{self.port} -> filer {self.filer}")

    def stop(self) -> None:
        if self._http_server:
            self._http_server.shutdown()
        if getattr(self, "_grpc_server", None):
            self._grpc_server.stop(grace=0.5)

    def configure_from_bytes(self, content: bytes) -> None:
        """Hot-swap identities from identity.json bytes (the reference's
        ParseS3ConfigurationFromBytes -> onIamConfigUpdate path), validated
        through the iam_pb S3ApiConfiguration schema."""
        from google.protobuf import json_format

        from ..pb import iam_pb2

        conf = json_format.Parse(
            content.decode(), iam_pb2.S3ApiConfiguration(),
            ignore_unknown_fields=True)
        ids = []
        for ident in conf.identities:
            cred = ident.credentials[0] if ident.credentials else None
            # empty actions mean NO permissions (identity.canDo returns
            # false on an empty list in the reference) — never default up
            ids.append(Identity(
                name=ident.name,
                access_key=cred.access_key if cred else "",
                secret_key=cred.secret_key if cred else "",
                actions=list(ident.actions)))
        self.iam = IdentityAccessManagement(ids)

    # -- filer plumbing ----------------------------------------------------

    def stub(self):
        return rpc.filer_stub(self.filer_grpc)

    def meta_call(self, path: str, fn, *, directory: bool = False):
        """Run `fn(stub)` against the filer shard owning `path` (the
        entry's parent dir, or the dir itself when directory=True), with
        the ring client's one stale-ring retry: a shard answering
        FAILED_PRECONDITION "wrong metadata shard" refreshes the cached
        ring exactly once and the call re-routes."""
        def leg(addr):
            stub = (self.stub() if not addr or addr == self.filer
                    else rpc.filer_stub(rpc.grpc_address(addr)))
            try:
                return fn(stub)
            except grpc.RpcError as e:
                ws = wrong_shard_of(e)
                if ws is not None:
                    raise ws from e
                raise

        return self.ring_client.call_routed(
            path, leg, directory=directory, default=self.filer)

    def maybe_reload_circuit_breaker(self) -> None:
        """Refresh limits from /etc/s3/circuit_breaker.json (10s TTL — the
        reference reloads on filer metadata events; a short poll keeps the
        same convergence without a standing subscription)."""
        now = time.time()
        if now - self._cb_loaded_at < 10:
            return
        self._cb_loaded_at = now
        try:
            conf = load_filer_config(self.stub())
        except Exception:
            return
        if conf is not None:
            self.circuit_breaker.load(conf)

    def bucket_entry(self, bucket: str) -> filer_pb2.Entry | None:
        return self.find_entry(BUCKETS_DIR, bucket)

    def find_entry(self, directory: str, name: str) -> filer_pb2.Entry | None:
        def lookup(stub):
            try:
                return stub.LookupDirectoryEntry(
                    filer_pb2.LookupDirectoryEntryRequest(
                        directory=directory, name=name), timeout=10).entry
            except grpc.RpcError as e:
                if e.code() == grpc.StatusCode.NOT_FOUND:
                    return None
                raise

        return self.meta_call(f"{directory}/{name}", lookup)

    def list_dir(self, directory: str, start: str = "", limit: int = 1000,
                 prefix: str = "", include_start=False):
        def listing(stub):
            # materialized inside the routed leg: a generator escaping
            # meta_call would stream from the wrong shard after a retry
            try:
                return [resp.entry for resp in stub.ListEntries(
                    filer_pb2.ListEntriesRequest(
                        directory=directory, prefix=prefix,
                        start_from_file_name=start,
                        inclusive_start_from=include_start,
                        limit=limit), timeout=30)]
            except grpc.RpcError as e:
                if e.code() != grpc.StatusCode.NOT_FOUND:
                    raise
                return []

        yield from self.meta_call(directory, listing, directory=True)

    def _meta_url(self, full_path: str, refresh: bool = False) -> str:
        """Filer-HTTP URL for `full_path`, aimed at the shard owning its
        parent directory (the seed filer on a 1-entry/unreachable ring)."""
        if refresh:
            self.ring_client.ring(refresh=True, trigger="stale")
        shard = self.ring_client.route_entry(full_path, self.filer)
        dir_, _, name = full_path.rpartition("/")
        return url_for(shard, dir_ + "/") + urllib.parse.quote(name)

    def _note_stale_ring(self, resp) -> None:
        """Absorb the epoch a 410 wrong-shard answer carries so the next
        `_meta_url` re-resolves against a fresh ring."""
        try:
            self.ring_client.note_epoch(int(resp.headers.get(
                EPOCH_HEADER, "0")))
        except (TypeError, ValueError):
            pass

    def put_object(self, bucket: str, key: str, body,
                   content_type: str = "") -> str:
        """-> etag. `body` is bytes or a chunk iterator; either way the
        bytes stream straight through the filer HTTP autochunker."""
        full_path = f"{BUCKETS_DIR}/{bucket}/{key}"
        md5 = hashlib.md5()
        if isinstance(body, (bytes, bytearray)):
            md5.update(body)
            data = body
        else:
            # spooled (mem <= 8MB, disk beyond), never a raw generator:
            # the native filer hot plane 307s md5-wanting PUTs to the
            # python listener and requests can only replay a SEEKABLE
            # body across that redirect
            data = _spool(body, md5)
        headers = trace.inject_headers(
            {"Content-Type":
             content_type or "application/octet-stream",
             # tenant budget already charged at the S3 ingress —
             # the filer must not bill this internal leg twice
             "X-Swfs-Qos-Charged": "1",
             # the S3 ETag contract is the whole-body md5: only
             # the python PUT path records it (the C++ hot plane
             # defers these), so PUT/GET/HEAD/If-None-Match agree
             "X-Swfs-Want-Md5": "1"})
        try:
            r = _session().put(self._meta_url(full_path), data=data,
                               headers=headers, timeout=600)
            if r.status_code == WRONG_SHARD_STATUS:
                # stale ring: absorb the shard's epoch, refresh once,
                # rewind the body and retry against the real owner
                self._note_stale_ring(r)
                if hasattr(data, "seek"):
                    data.seek(0)
                r = _session().put(
                    self._meta_url(full_path, refresh=True), data=data,
                    headers=headers, timeout=600)
        finally:
            if hasattr(data, "close"):
                data.close()  # reclaim a disk-rolled spool promptly
        if r.status_code in (429, 503):
            # the backend throttled anyway (direct-traffic budget,
            # pressure shed): surface it as throttling, not a bug
            raise _backend_throttled(r, "filer PUT")
        if r.status_code == 400:
            # the filer refused a short body (ISSUE 14 ShortBodyError):
            # the client died mid-upload — S3's IncompleteBody, not an
            # InternalError (nothing was committed; chunks were GC'd)
            raise S3Error(400, "IncompleteBody",
                          "You did not provide the number of bytes "
                          "specified by the Content-Length HTTP header")
        if r.status_code >= 300:
            raise S3Error(500, "InternalError", f"filer PUT: {r.status_code}")
        return md5.hexdigest()

    def get_object(self, bucket: str, key: str, range_header: str = "",
                   stream: bool = False,
                   conditional: dict | None = None):
        """`conditional` forwards the caller's validator headers
        (If-None-Match / If-Modified-Since / If-Range) to the filer,
        whose RFC 7232/7233 evaluation (utils.http) then answers the
        S3 conditional GET — a 304 passes back through untouched
        (ISSUE 9 conformance satellite)."""
        full_path = f"{BUCKETS_DIR}/{bucket}/{key}"
        headers = trace.inject_headers(
            {**({"Range": range_header} if range_header else {}),
             **(conditional or {}),
             "X-Swfs-Qos-Charged": "1"})
        r = _session().get(self._meta_url(full_path), headers=headers,
                           timeout=600, stream=stream)
        if r.status_code == WRONG_SHARD_STATUS:
            self._note_stale_ring(r)
            r.close()
            r = _session().get(self._meta_url(full_path, refresh=True),
                               headers=headers, timeout=600, stream=stream)
        if r.status_code == 304:
            return r
        if r.status_code == 404:
            r.close()
            raise S3Error(404, "NoSuchKey", "The specified key does not exist.")
        if r.status_code == 416:
            r.close()
            raise S3Error(416, "InvalidRange",
                          "The requested range is not satisfiable")
        if r.status_code in (429, 503):
            r.close()
            raise _backend_throttled(r, "filer GET")
        if r.status_code >= 300:
            r.close()
            raise S3Error(500, "InternalError", f"filer GET: {r.status_code}")
        return r

    def delete_object(self, bucket: str, key: str) -> None:
        dir_, _, name = f"{BUCKETS_DIR}/{bucket}/{key}".rpartition("/")
        self.delete_entry(dir_, name, is_delete_data=True,
                          is_recursive=True)

    # routed single-entry mutations: every handler path funnels through
    # these so the whole gateway speaks to the owning shard

    def create_entry(self, directory: str, entry, timeout: int = 10):
        return self.meta_call(
            f"{directory}/{entry.name}",
            lambda stub: stub.CreateEntry(filer_pb2.CreateEntryRequest(
                directory=directory, entry=entry), timeout=timeout))

    def update_entry(self, directory: str, entry, timeout: int = 10):
        return self.meta_call(
            f"{directory}/{entry.name}",
            lambda stub: stub.UpdateEntry(filer_pb2.UpdateEntryRequest(
                directory=directory, entry=entry), timeout=timeout))

    def delete_entry(self, directory: str, name: str, *,
                     is_delete_data: bool, is_recursive: bool,
                     timeout: int = 60):
        return self.meta_call(
            f"{directory}/{name}",
            lambda stub: stub.DeleteEntry(filer_pb2.DeleteEntryRequest(
                directory=directory, name=name,
                is_delete_data=is_delete_data,
                is_recursive=is_recursive), timeout=timeout))


# -- XML helpers -----------------------------------------------------------

def _spool(chunks, md5):
    """Drain a chunk iterator into a rewindable file (memory up to 8MB,
    disk beyond), updating `md5` along the way. The filer PUT legs need
    a SEEKABLE body: the native hot plane 307s md5-wanting (and
    over-max-body) PUTs to the python listener, and requests can only
    replay a body across that redirect if it can seek back to 0."""
    import tempfile

    spool = tempfile.SpooledTemporaryFile(max_size=8 << 20)
    total = 0
    for piece in chunks:
        md5.update(piece)
        spool.write(piece)
        total += len(piece)
    spool.seek(0)
    # requests' super_len() consults this BEFORE fileno() — without it,
    # fileno() forces the spool to roll over to disk for every body,
    # making the in-memory tier dead weight
    spool.len = total
    return spool


def _el(parent, tag, text=None):
    e = ET.SubElement(parent, tag)
    if text is not None:
        e.text = str(text)
    return e


def _xml_bytes(root: ET.Element) -> bytes:
    return (b'<?xml version="1.0" encoding="UTF-8"?>'
            + ET.tostring(root))


def _iso(ts: int) -> str:
    return time.strftime("%Y-%m-%dT%H:%M:%S.000Z", time.gmtime(ts or 0))


# -- request handler -------------------------------------------------------

class _S3Control:
    """s3_pb.SeaweedS3 servicer — configuration push."""

    def __init__(self, srv: S3Server):
        self.srv = srv

    def Configure(self, request, context):
        from ..pb import s3_pb2

        try:
            self.srv.configure_from_bytes(request.s3_configuration_file_content)
        except Exception as e:
            context.abort(grpc.StatusCode.INVALID_ARGUMENT, f"bad config: {e}")
        return s3_pb2.S3ConfigureResponse()


def _backend_throttled(r, what: str) -> S3Error:
    """A 429/503 from the backing filer IS throttling (its own ingress
    budget or a pressure shed): pass it through as spec-shaped SlowDown
    with the backend's retry hint — never a 500 InternalError that SDKs
    classify as a server fault and fail hard on."""
    try:
        ra = float(r.headers.get("Retry-After") or 1.0)
    except (TypeError, ValueError):
        ra = 1.0
    return S3Error(503, "SlowDown",
                   f"Please reduce your request rate. ({what} throttled)",
                   retry_after_s=ra)


def _backend_unavailable(e: Exception) -> S3Error | None:
    """Map backend-transport failures to a spec-shaped 503
    ServiceUnavailable (ISSUE 8 satellite); None for everything else
    (those stay 500 InternalError)."""
    import requests as _rq

    if isinstance(e, grpc.RpcError):
        code = e.code() if callable(getattr(e, "code", None)) else None
        if code in (grpc.StatusCode.UNAVAILABLE,
                    grpc.StatusCode.DEADLINE_EXCEEDED):
            return S3Error(503, "ServiceUnavailable",
                           f"backend filer unavailable ({code})",
                           retry_after_s=1.0)
        return None
    if isinstance(e, (_rq.ConnectionError, _rq.Timeout)):
        return S3Error(503, "ServiceUnavailable",
                       "backend filer unreachable", retry_after_s=1.0)
    return None


def _iter_exact(rfile, length: int):
    """Yield exactly `length` bytes from the socket in 1MB pieces; a short
    body is an error (AWS IncompleteBody), never a silent truncation."""
    remaining = length
    while remaining > 0:
        piece = rfile.read(min(1 << 20, remaining))
        if not piece:
            raise S3Error(400, "IncompleteBody",
                          "Request body ended before Content-Length")
        remaining -= len(piece)
        yield piece


def _action_for(verb: str, bucket: str, key: str, q) -> str:
    """HTTP request -> gateway action verb (s3_constants/header.go mapping)."""
    if "acl" in q:
        return "ReadAcp" if verb in ("GET", "HEAD") else "WriteAcp"
    if "policy" in q:
        return "Admin"
    if "tagging" in q:
        return "Read" if verb in ("GET", "HEAD") else "Tagging"
    if not bucket:
        return "List"
    if not key:
        if verb == "PUT":
            return "Admin"  # create bucket
        if verb == "DELETE":
            return "Admin"  # delete bucket
        if verb == "POST":
            return "Write"  # multi-delete
        return "List"
    if verb in ("GET", "HEAD"):
        return "Read"
    return "Write"


def _make_handler(srv: S3Server):
    class Handler(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"

        def log_message(self, fmt, *args):
            glog.v(2, f"s3 http: {fmt % args}")

        # ---- plumbing

        def _send(self, status: int, body: bytes = b"",
                  ctype: str = "application/xml", headers=None):
            headers = dict(headers or {})
            self.send_response(status)
            self.send_header("Content-Type", ctype)
            if "Content-Length" not in headers:
                headers["Content-Length"] = str(len(body))
            tid = getattr(self, "_trace_id", "")
            # the request id IS the trace id when one exists — header
            # and error-body RequestId agree, and both resolve through
            # /debug/traces (ISSUE 8)
            self.send_header("x-amz-request-id",
                             tid or uuid.uuid4().hex[:16])
            if tid:
                self.send_header("X-Trace-Id", tid)
            for k, v in headers.items():
                self.send_header(k, v)
            self.end_headers()
            if body and self.command != "HEAD":
                self.wfile.write(body)

        def _error(self, err: S3Error):
            # spec-shaped error body (ISSUE 8 satellite): Code, Message,
            # Resource, RequestId — the fields AWS SDK error parsers
            # read to classify and back off. RequestId is the TRACE id
            # when the request has one, so an error in a client log is
            # one `trace.dump` away from its per-plane breakdown.
            root = ET.Element("Error")
            _el(root, "Code", err.code)
            _el(root, "Message", str(err))
            _el(root, "Resource",
                urllib.parse.urlparse(self.path).path)
            _el(root, "RequestId",
                getattr(self, "_trace_id", "") or uuid.uuid4().hex[:16])
            headers = {}
            if err.status == 503:
                headers["Retry-After"] = str(
                    max(int(err.retry_after_s + 0.999), 1))
                if int(self.headers.get("Content-Length") or 0):
                    # shed before the body was read (QoS admission /
                    # breaker fire ahead of any body consumption): the
                    # unread bytes would desync keep-alive parsing for
                    # the NEXT request on this connection — same guard
                    # as the filer's 429 path. Costs the throttled
                    # client one reconnect, which is the point.
                    self.close_connection = True
            self._send(err.status, _xml_bytes(root), headers=headers)

        def _route(self):
            u = urllib.parse.urlparse(self.path)
            path = urllib.parse.unquote(u.path)
            q = urllib.parse.parse_qs(u.query, keep_blank_values=True)
            parts = path.lstrip("/").split("/", 1)
            bucket = parts[0]
            key = parts[1] if len(parts) > 1 else ""
            return bucket, key, q, u

        def _raw_body(self) -> bytes:
            if not hasattr(self, "_raw_body_cache"):
                length = int(self.headers.get("Content-Length") or 0)
                body = self.rfile.read(length) if length else b""
                if len(body) < length:
                    # the client died mid-body (ISSUE 14): committing
                    # the short read would store a silently TRUNCATED
                    # object — the filer-side ShortBodyError's gateway
                    # analogue. The socket is desynced; close it.
                    self.close_connection = True
                    raise S3Error(
                        400, "IncompleteBody",
                        "You did not provide the number of bytes "
                        "specified by the Content-Length HTTP header")
                self._raw_body_cache = body
            return self._raw_body_cache

        def _body(self) -> bytes:
            body = self._raw_body()
            if self.headers.get("x-amz-content-sha256") == \
                    "STREAMING-AWS4-HMAC-SHA256-PAYLOAD":
                body = _decode_chunked_signing(body)
            return body

        def _auth(self, u) -> Identity | None:
            claimed = self.headers.get("x-amz-content-sha256",
                                       "UNSIGNED-PAYLOAD")
            if srv.iam.enabled and claimed not in (
                    "UNSIGNED-PAYLOAD",
                    "STREAMING-AWS4-HMAC-SHA256-PAYLOAD"):
                # the signature covers the client's claimed hash; the claim
                # must match the actual body or a captured signed request
                actual = hashlib.sha256(self._raw_body()).hexdigest()
                if actual != claimed:
                    raise S3Error(400, "XAmzContentSHA256Mismatch",
                                  "payload hash does not match body")
            try:
                return srv.iam.authenticate(self.command, u.path, u.query,
                                            self.headers, claimed)
            except AuthError as e:
                raise S3Error(403, e.code, str(e))

        def _authorize(self, ident: Identity | None, action: str,
                       bucket: str, key: str,
                       entry: filer_pb2.Entry | None) -> None:
            """Identity actions + bucket policy + canned ACL, Deny-wins
            (auth_credentials.go canDo + policy evaluation). `entry` is the
            bucket entry fetched once by the dispatcher."""
            if not srv.iam.enabled:
                return
            policy = None
            if entry is not None and POLICY_KEY in entry.extended:
                try:
                    policy = BucketPolicy.parse(entry.extended[POLICY_KEY])
                except PolicyError:
                    policy = None
            verdict = policy.decide(
                principal=ident.access_key if ident else None,
                action=action, bucket=bucket, key=key) if policy else None
            if verdict == "Deny":
                raise S3Error(403, "AccessDenied", "denied by bucket policy")
            if verdict == "Allow":
                return
            if ident is not None:
                if ident.allows(action, bucket):
                    return
                raise S3Error(403, "AccessDenied",
                              f"no permission for {action} on {bucket}")
            # anonymous: only a public canned ACL (or policy, above) admits
            acl = (entry.extended.get(ACL_KEY, b"") if entry else b"").decode()
            if acl == "public-read-write" and action in ("Read", "List",
                                                         "Write"):
                return
            if acl == "public-read" and action in ("Read", "List"):
                return
            raise S3Error(403, "AccessDenied", "anonymous access denied")

        # ---- verbs

        def do_GET(self):
            self._dispatch("GET")

        def do_HEAD(self):
            self._dispatch("HEAD")

        def do_PUT(self):
            self._dispatch("PUT")

        def do_POST(self):
            self._dispatch("POST")

        def do_DELETE(self):
            self._dispatch("DELETE")

        def _admin_plane_ok(self, u) -> bool:
            # /debug/traces and /status expose request-level data (object
            # keys, internal server addresses, error strings) — unlike the
            # aggregate-only /metrics, they must not be anonymous-readable
            # on the public gateway when IAM is on
            if not srv.iam.enabled:
                return True
            try:
                ident = self._auth(u)
            except S3Error:
                return False
            return ident is not None and ident.allows("Admin")

        def _dispatch(self, verb: str):
            self._trace_id = ""  # never leak across keep-alive requests
            # admin endpoints match the exact PATH and admit ONLY their
            # own query params — a bucket literally named "metrics" or
            # "status" keeps its S3 query routes (GET /metrics with no
            # query was always the admin endpoint, ?list-type=2 etc.
            # must still reach bucket listing)
            admin_u = urllib.parse.urlparse(self.path)
            admin_q = {k: v[0] for k, v in
                       urllib.parse.parse_qs(admin_u.query).items()}
            if verb == "GET" and admin_u.path == "/metrics" \
                    and set(admin_q) <= {"exemplars"}:
                exemplars = "exemplars" in admin_q
                body = gather(exemplars=exemplars).encode()
                self.send_response(200)
                self.send_header("Content-Type",
                                 metrics_content_type(exemplars))
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)
                return
            if verb == "GET" and admin_u.path == "/debug/traces" \
                    and set(admin_q) <= {"trace"}:
                if not self._admin_plane_ok(admin_u):
                    return self._send(403, b'{"error": "AccessDenied"}',
                                      "application/json")
                body = json.dumps(
                    trace.debug_traces_payload(admin_q)).encode()
                return self._send(200, body, "application/json")
            if verb == "GET" and admin_u.path == "/status" \
                    and not admin_q:
                if not self._admin_plane_ok(admin_u):
                    return self._send(403, b'{"error": "AccessDenied"}',
                                      "application/json")
                from ..utils.stats import http_pool_stats, qos_stats

                body = json.dumps({
                    **status_base(srv._started_at),
                    "Filer": srv.filer,
                    # TLS handshakes accepted on the public ingress +
                    # this process's pooled client legs (ISSUE 9)
                    "HttpPool": http_pool_stats(),
                    "Trace": trace.STORE.stats(),
                    # QoS plane (ISSUE 8): tenant buckets + rejections
                    "Qos": {
                        **qos_stats(),
                        "tenantAdmission": srv.qos_admission.status(),
                    },
                }).encode()
                return self._send(200, body, "application/json")
            bucket, key, q, u = self._route()
            action = _action_for(verb, bucket, key, q)
            release = lambda: None  # noqa: E731
            with trace.span("s3.request", carrier=self.headers,
                            component="s3", server=srv.address,
                            action=f"{verb.lower()}", bucket=bucket,
                            key=key) as tsp:
                self._trace_id = tsp.trace_id
                self._dispatch_traced(verb, bucket, key, q, u, action,
                                      release, tsp)

        def _dispatch_traced(self, verb, bucket, key, q, u, action,
                             release, tsp):
            try:
                with S3_REQUEST_HISTOGRAM.time(action=f"{verb.lower()}"):
                    # admission first: a tenant over budget (or a
                    # tripped breaker) must shed load before any filer
                    # lookups (authz reads bucket state). 503 SlowDown
                    # is the spec code SDKs back off on.
                    from ..qos import s3_tenant

                    d = srv.qos_admission.admit(
                        s3_tenant(self.headers, u.query, bucket),
                        trace_id=tsp.trace_id,
                        detail=f"{verb} {u.path}")
                    if not d.admitted:
                        tsp.set_attr(qosRejected=d.reason,
                                     tenant=d.tenant)
                        raise S3Error(
                            503, "SlowDown",
                            "Please reduce your request rate.",
                            retry_after_s=d.retry_after_s)
                    srv.maybe_reload_circuit_breaker()
                    try:
                        release = srv.circuit_breaker.acquire(
                            action, bucket,
                            int(self.headers.get("Content-Length") or 0))
                    except TooManyRequests as e:
                        # SlowDown, not a bare 500/TooManyRequests: the
                        # spec-shaped code is what SDK retry policies
                        # classify as throttling (ISSUE 8 satellite)
                        raise S3Error(503, "SlowDown",
                                      f"Please reduce your request "
                                      f"rate. ({e})", retry_after_s=1.0)
                    bucket_entry = srv.bucket_entry(bucket) if bucket else None
                    ident = self._auth(u)
                    self._authorize(ident, action, bucket, key, bucket_entry)
                    if not bucket:
                        return self._service(verb)
                    if not key:
                        return self._bucket(verb, bucket, q, bucket_entry)
                    return self._object(verb, bucket, key, q, bucket_entry)
            except S3Error as e:
                if e.status >= 500 and e.status != 503:
                    # 5xx pins the trace (keep-if-error); expected 4xx
                    # (404 polls, auth rejections) and 503 shedding
                    # (SlowDown floods at hundreds/s are the QoS plane
                    # WORKING — the filer/master overload policy) must
                    # not churn the retained set
                    tsp.set_error(f"{e.code}: {e}")
                else:
                    tsp.set_attr(s3Error=e.code, status=e.status)
                self._error(e)
            except Exception as e:  # noqa: BLE001
                tsp.set_error(f"{type(e).__name__}: {e}")
                glog.error(f"s3 {verb} {self.path}: {e}")
                # transport failures to the backend filer are OUTAGES,
                # not internal bugs: answer 503 ServiceUnavailable with
                # a retry hint so SDK clients back off instead of
                # failing hard on a generic 500 (ISSUE 8 satellite)
                self._error(_backend_unavailable(e)
                            or S3Error(500, "InternalError", str(e)))
            finally:
                release()

        # ---- service level

        def _service(self, verb: str):
            if verb != "GET":
                raise S3Error(405, "MethodNotAllowed", "unsupported")
            root = ET.Element("ListAllMyBucketsResult", xmlns=S3_NS)
            owner = _el(root, "Owner")
            _el(owner, "ID", "seaweedfs-tpu")
            buckets = _el(root, "Buckets")
            for e in srv.list_dir(BUCKETS_DIR):
                if not e.is_directory or e.name.startswith("."):
                    continue
                b = _el(buckets, "Bucket")
                _el(b, "Name", e.name)
                _el(b, "CreationDate", _iso(e.attributes.crtime))
            self._send(200, _xml_bytes(root))

        # ---- bucket level

        def _bucket(self, verb: str, bucket: str, q,
                    bucket_entry: filer_pb2.Entry | None = None):
            if "acl" in q:
                return self._acl(verb, bucket, "")
            if "policy" in q:
                return self._policy(verb, bucket)
            if verb == "PUT":
                if bucket_entry is not None:
                    # CreateEntry upserts; recreating would wipe the
                    # existing bucket's ACL/policy/quota attributes
                    return self._send(200,
                                      headers={"Location": f"/{bucket}"})
                entry = _dir_entry(bucket)
                acl = self.headers.get("x-amz-acl", "")
                if acl:
                    if acl not in CANNED_ACLS:
                        raise S3Error(400, "InvalidArgument",
                                      f"unsupported canned acl {acl}")
                    entry.extended[ACL_KEY] = acl.encode()
                srv.create_entry(BUCKETS_DIR, entry)
                return self._send(200, headers={"Location": f"/{bucket}"})
            if verb in ("GET", "HEAD"):
                entry = bucket_entry
                if entry is None:
                    raise S3Error(404, "NoSuchBucket",
                                  "The specified bucket does not exist")
                if verb == "HEAD":
                    return self._send(200)
                if "uploads" in q:
                    return self._list_multipart_uploads(bucket)
                return self._list_objects(bucket, q)
            if verb == "DELETE":
                resp = srv.delete_entry(BUCKETS_DIR, bucket,
                                        is_delete_data=True,
                                        is_recursive=True)
                if resp.error:
                    raise S3Error(409, "BucketNotEmpty", resp.error)
                return self._send(204)
            if verb == "POST" and "delete" in q:
                return self._multi_delete(bucket)
            raise S3Error(405, "MethodNotAllowed", "unsupported bucket op")

        def _list_objects(self, bucket: str, q):
            prefix = q.get("prefix", [""])[0]
            delimiter = q.get("delimiter", [""])[0]
            max_keys = int(q.get("max-keys", ["1000"])[0])
            v2 = q.get("list-type", [""])[0] == "2"
            marker = (q.get("continuation-token", [""])[0] if v2
                      else q.get("marker", [""])[0])
            start_after = q.get("start-after", [""])[0]
            if start_after > marker:
                marker = start_after

            contents, common = [], set()
            truncated, next_marker = _walk(
                srv, f"{BUCKETS_DIR}/{bucket}", "", prefix, delimiter,
                marker, max_keys, contents, common)

            tag = "ListBucketResult"
            root = ET.Element(tag, xmlns=S3_NS)
            _el(root, "Name", bucket)
            _el(root, "Prefix", prefix)
            _el(root, "MaxKeys", max_keys)
            if delimiter:
                _el(root, "Delimiter", delimiter)
            _el(root, "IsTruncated", "true" if truncated else "false")
            if v2:
                _el(root, "KeyCount", len(contents))
                if truncated:
                    _el(root, "NextContinuationToken", next_marker)
            elif truncated:
                _el(root, "NextMarker", next_marker)
            for key, entry in contents:
                c = _el(root, "Contents")
                _el(c, "Key", key)
                _el(c, "LastModified", _iso(entry.attributes.mtime))
                _el(c, "ETag", f'"{_entry_etag(entry)}"')
                _el(c, "Size", entry.attributes.file_size)
                _el(c, "StorageClass", "STANDARD")
            for p in sorted(common):
                cp = _el(root, "CommonPrefixes")
                _el(cp, "Prefix", p)
            self._send(200, _xml_bytes(root))

        def _multi_delete(self, bucket: str):
            body = self._body()
            root = ET.fromstring(body)
            ns = root.tag.partition("}")[0] + "}" if "}" in root.tag else ""
            result = ET.Element("DeleteResult", xmlns=S3_NS)
            for obj in root.findall(f"{ns}Object"):
                key = obj.find(f"{ns}Key").text
                try:
                    srv.delete_object(bucket, key)
                    d = _el(result, "Deleted")
                    _el(d, "Key", key)
                except Exception as e:  # noqa: BLE001
                    er = _el(result, "Error")
                    _el(er, "Key", key)
                    _el(er, "Code", "InternalError")
                    _el(er, "Message", str(e))
            self._send(200, _xml_bytes(result))

        # ---- object level

        # ---- ACL (s3acl/ + s3api_object_handlers_acl.go): canned ACLs
        # stored on the entry, rendered as AccessControlPolicy XML

        def _acl(self, verb: str, bucket: str, key: str):
            if key:
                dir_, _, name = f"{BUCKETS_DIR}/{bucket}/{key}".rpartition("/")
            else:
                dir_, name = BUCKETS_DIR, bucket
            entry = srv.find_entry(dir_, name)
            if entry is None:
                raise S3Error(404, "NoSuchKey" if key else "NoSuchBucket",
                              "not found")
            if verb in ("GET", "HEAD"):
                acl = entry.extended.get(ACL_KEY, b"private").decode()
                root = ET.Element("AccessControlPolicy", xmlns=S3_NS)
                owner = _el(root, "Owner")
                _el(owner, "ID", "seaweedfs-tpu")
                grants = _el(root, "AccessControlList")
                g = _el(grants, "Grant")
                ge = _el(g, "Grantee")
                ge.set("xmlns:xsi", "http://www.w3.org/2001/XMLSchema-instance")
                ge.set("xsi:type", "CanonicalUser")
                _el(ge, "ID", "seaweedfs-tpu")
                _el(g, "Permission", "FULL_CONTROL")
                if acl in ("public-read", "public-read-write"):
                    g2 = _el(grants, "Grant")
                    ge2 = _el(g2, "Grantee")
                    ge2.set("xmlns:xsi",
                            "http://www.w3.org/2001/XMLSchema-instance")
                    ge2.set("xsi:type", "Group")
                    _el(ge2, "URI",
                        "http://acs.amazonaws.com/groups/global/AllUsers")
                    _el(g2, "Permission",
                        "READ" if acl == "public-read" else "FULL_CONTROL")
                return self._send(200, _xml_bytes(root))
            if verb == "PUT":
                acl = self.headers.get("x-amz-acl", "")
                if not acl:  # grant-by-XML-body unsupported, like many S3s
                    raise S3Error(400, "MissingSecurityHeader",
                                  "x-amz-acl canned header required")
                if acl not in CANNED_ACLS:
                    raise S3Error(400, "InvalidArgument",
                                  f"unsupported canned acl {acl}")
                entry.extended[ACL_KEY] = acl.encode()
                srv.update_entry(dir_, entry)
                return self._send(200)
            raise S3Error(405, "MethodNotAllowed", "unsupported acl op")

        # ---- bucket policy (policy/ + s3api_bucket_policy_handlers.go)

        def _policy(self, verb: str, bucket: str):
            entry = srv.find_entry(BUCKETS_DIR, bucket)
            if entry is None:
                raise S3Error(404, "NoSuchBucket", "no such bucket")
            if verb == "GET":
                blob = entry.extended.get(POLICY_KEY)
                if not blob:
                    raise S3Error(404, "NoSuchBucketPolicy",
                                  "the bucket policy does not exist")
                return self._send(200, blob, "application/json")
            if verb == "PUT":
                try:
                    pol = BucketPolicy.parse(self._body())
                except PolicyError as e:
                    raise S3Error(400, "MalformedPolicy", str(e))
                entry.extended[POLICY_KEY] = pol.to_bytes()
                srv.update_entry(BUCKETS_DIR, entry)
                return self._send(204)
            if verb == "DELETE":
                if POLICY_KEY in entry.extended:
                    del entry.extended[POLICY_KEY]
                    srv.update_entry(BUCKETS_DIR, entry)
                return self._send(204)
            raise S3Error(405, "MethodNotAllowed", "unsupported policy op")

        def _object(self, verb: str, bucket: str, key: str, q,
                    bucket_entry: filer_pb2.Entry | None = None):
            if bucket_entry is None:
                bucket_entry = srv.find_entry(BUCKETS_DIR, bucket)
            if bucket_entry is None:
                raise S3Error(404, "NoSuchBucket",
                              "The specified bucket does not exist")
            if verb in ("PUT", "POST") and \
                    bucket_entry.extended.get(READONLY_KEY) == b"true":
                # quota outcome (command_s3_bucket_quota_check): block only
                # data-adding verbs — DELETE stays allowed so an over-quota
                # bucket can be drained back under its limit
                raise S3Error(403, "AccessDenied",
                              f"bucket {bucket} is read-only (quota)")
            if "acl" in q:
                return self._acl(verb, bucket, key)
            if "tagging" in q:
                return self._tagging(verb, bucket, key)
            if "uploads" in q and verb == "POST":
                return self._initiate_multipart(bucket, key)
            if "uploadId" in q:
                upload_id = q["uploadId"][0]
                if verb == "PUT" and "partNumber" in q:
                    src = self.headers.get("x-amz-copy-source")
                    if src:
                        return self._upload_part_copy(
                            bucket, key, upload_id,
                            int(q["partNumber"][0]), src)
                    return self._upload_part(bucket, key, upload_id,
                                             int(q["partNumber"][0]))
                if verb == "POST":
                    return self._complete_multipart(bucket, key, upload_id)
                if verb == "DELETE":
                    return self._abort_multipart(bucket, key, upload_id)
                if verb == "GET":
                    return self._list_parts(bucket, key, upload_id)

            if verb == "PUT":
                src = self.headers.get("x-amz-copy-source")
                if src:
                    return self._copy_object(bucket, key, src)
                # When the signature binds no payload hash (anonymous or
                # UNSIGNED-PAYLOAD), the body can stream straight through —
                # gateway memory stays one piece deep. Signed payload
                # hashes and aws-chunked signing need the whole body (the
                # hash/frame check in _auth/_body already consumed it).
                claimed = self.headers.get("x-amz-content-sha256",
                                           "UNSIGNED-PAYLOAD")
                length = int(self.headers.get("Content-Length") or 0)
                streamed = (claimed == "UNSIGNED-PAYLOAD"
                            and not hasattr(self, "_raw_body_cache"))
                chunked_te = "chunked" in (
                    self.headers.get("Transfer-Encoding") or "").lower()
                if chunked_te:
                    if not streamed:
                        # signed payloads need Content-Length semantics
                        raise S3Error(411, "MissingContentLength",
                                      "chunked transfer requires an "
                                      "unsigned payload here")
                    from ..server.filer import _ChunkedReader

                    reader = _ChunkedReader(self.rfile)
                    body = iter(lambda: reader.read(1 << 20), b"")
                elif streamed:
                    body = _iter_exact(self.rfile, length)
                else:
                    body = self._body()
                try:
                    etag = srv.put_object(
                        bucket, key, body,
                        self.headers.get("Content-Type", ""))
                except Exception:
                    if streamed:
                        # body may be partially unread: keep-alive desync
                        self.close_connection = True
                    raise
                acl = self.headers.get("x-amz-acl", "")
                if acl in CANNED_ACLS:
                    dir_, _, name = \
                        f"{BUCKETS_DIR}/{bucket}/{key}".rpartition("/")
                    entry = srv.find_entry(dir_, name)
                    if entry is not None:
                        entry.extended[ACL_KEY] = acl.encode()
                        srv.update_entry(dir_, entry)
                return self._send(200, headers={"ETag": f'"{etag}"'})
            if verb in ("GET", "HEAD"):
                if verb == "HEAD":
                    dir_, _, name = f"{BUCKETS_DIR}/{bucket}/{key}".rpartition("/")
                    entry = srv.find_entry(dir_, name)
                    if entry is None or entry.is_directory:
                        raise S3Error(404, "NoSuchKey", "not found")
                    return self._send(200, headers={
                        "Content-Length": str(entry.attributes.file_size),
                        "ETag": f'"{_entry_etag(entry)}"',
                        "Last-Modified": time.strftime(
                            "%a, %d %b %Y %H:%M:%S GMT",
                            time.gmtime(entry.attributes.mtime)),
                    })
                conditional = {
                    h: self.headers[h]
                    for h in ("If-None-Match", "If-Modified-Since",
                              "If-Range")
                    if self.headers.get(h) is not None}
                r = srv.get_object(bucket, key,
                                   self.headers.get("Range", ""),
                                   stream=True,
                                   conditional=conditional or None)
                headers = {}
                for h in ("Content-Range", "ETag", "Last-Modified"):
                    if h in r.headers:
                        headers[h] = r.headers[h]
                if r.status_code == 304:
                    r.close()
                    return self._send(304, headers=headers)
                # pass the filer's stream straight through: gateway memory
                # stays one chunk deep for any object size
                try:
                    self.send_response(r.status_code)
                    self.send_header("x-amz-request-id", uuid.uuid4().hex[:16])
                    tid = getattr(self, "_trace_id", "")
                    if tid:
                        self.send_header("X-Trace-Id", tid)
                    self.send_header(
                        "Content-Type",
                        r.headers.get("Content-Type",
                                      "application/octet-stream"))
                    self.send_header("Content-Length",
                                     r.headers.get("Content-Length", "0"))
                    for k, v in headers.items():
                        self.send_header(k, v)
                    self.end_headers()
                    # HEAD never reaches here (fast-path above returns)
                    for piece in r.iter_content(1 << 20):
                        if piece:
                            self.wfile.write(piece)
                except IOError:  # client went away mid-stream
                    self.close_connection = True
                finally:
                    r.close()
                return
            if verb == "DELETE":
                srv.delete_object(bucket, key)
                return self._send(204)
            raise S3Error(405, "MethodNotAllowed", "unsupported object op")

        def _parse_copy_source(self, src: str) -> tuple[str, str]:
            # "?versionId=..." may qualify the source (we keep a single
            # version; the suffix must not leak into the key)
            src = src.partition("?")[0]
            src = urllib.parse.unquote(src.lstrip("/"))
            sbucket, _, skey = src.partition("/")
            if not sbucket or not skey:
                raise S3Error(400, "InvalidArgument", "bad copy source")
            return sbucket, skey

        def _copy_object(self, bucket: str, key: str, src: str):
            sbucket, skey = self._parse_copy_source(src)
            # STREAMED copy (ISSUE 14): the filer serves the source GET
            # through its pipelined readahead and the PUT leg re-chunks
            # through the overlapped autochunker — the gateway spools
            # (mem <= 8MB, disk beyond) instead of materializing the
            # whole object in RAM as r.content did
            r = srv.get_object(sbucket, skey, stream=True)
            try:
                etag = srv.put_object(bucket, key,
                                      r.iter_content(1 << 20),
                                      r.headers.get("Content-Type", ""))
            finally:
                r.close()
            root = ET.Element("CopyObjectResult", xmlns=S3_NS)
            _el(root, "ETag", f'"{etag}"')
            _el(root, "LastModified", _iso(int(time.time())))
            self._send(200, _xml_bytes(root))

        # ---- tagging (stored as extended attrs, s3api_object_tagging)

        def _tagging(self, verb: str, bucket: str, key: str):
            dir_, _, name = f"{BUCKETS_DIR}/{bucket}/{key}".rpartition("/")
            entry = srv.find_entry(dir_, name)
            if entry is None:
                raise S3Error(404, "NoSuchKey", "not found")
            if verb == "GET":
                root = ET.Element("Tagging", xmlns=S3_NS)
                ts = _el(root, "TagSet")
                for k, v in sorted(entry.extended.items()):
                    if k.startswith("x-amz-tag-"):
                        t = _el(ts, "Tag")
                        _el(t, "Key", k[len("x-amz-tag-"):])
                        _el(t, "Value", v.decode())
                return self._send(200, _xml_bytes(root))
            if verb == "PUT":
                body = self._body()
                root = ET.fromstring(body)
                ns = root.tag.partition("}")[0] + "}" if "}" in root.tag else ""
                for k in [k for k in entry.extended
                          if k.startswith("x-amz-tag-")]:
                    del entry.extended[k]
                for tag in root.iter(f"{ns}Tag"):
                    k = tag.find(f"{ns}Key").text
                    v = tag.find(f"{ns}Value").text or ""
                    entry.extended[f"x-amz-tag-{k}"] = v.encode()
                srv.update_entry(dir_, entry)
                return self._send(200)
            if verb == "DELETE":
                for k in [k for k in entry.extended
                          if k.startswith("x-amz-tag-")]:
                    del entry.extended[k]
                srv.update_entry(dir_, entry)
                return self._send(204)
            raise S3Error(405, "MethodNotAllowed", "unsupported tagging op")

        # ---- multipart (filer_multipart.go)

        def _initiate_multipart(self, bucket: str, key: str):
            upload_id = uuid.uuid4().hex
            meta = json.dumps({"bucket": bucket, "key": key,
                               "content_type":
                               self.headers.get("Content-Type", "")}).encode()
            e = _dir_entry(upload_id)
            e.extended["upload-meta"] = meta
            srv.create_entry(UPLOADS_DIR, e)
            root = ET.Element("InitiateMultipartUploadResult", xmlns=S3_NS)
            _el(root, "Bucket", bucket)
            _el(root, "Key", key)
            _el(root, "UploadId", upload_id)
            self._send(200, _xml_bytes(root))

        def _upload_part(self, bucket: str, key: str, upload_id: str,
                         part_number: int):
            if srv.find_entry(UPLOADS_DIR, upload_id) is None:
                raise S3Error(404, "NoSuchUpload", "upload not found")
            body = self._body()
            part_path = (f"{UPLOADS_DIR}/{upload_id}/"
                         f"{part_number:04d}.part")
            r = _session().put(srv._meta_url(part_path), data=body,
                               timeout=600,
                               headers={"X-Swfs-Want-Md5": "1"})
            if r.status_code == WRONG_SHARD_STATUS:
                srv._note_stale_ring(r)
                r = _session().put(srv._meta_url(part_path, refresh=True),
                                   data=body, timeout=600,
                                   headers={"X-Swfs-Want-Md5": "1"})
            if r.status_code >= 300:
                raise S3Error(500, "InternalError", "part upload failed")
            self._send(200, headers={
                "ETag": f'"{hashlib.md5(body).hexdigest()}"'})

        def _upload_part_copy(self, bucket: str, key: str, upload_id: str,
                              part_number: int, src: str):
            """UploadPartCopy: a part sourced from an existing object,
            optionally a byte range — streamed, never fully buffered
            (CopyObjectPartHandler, s3api_object_copy_handlers.go:135-183;
            bad ranges map to 400 InvalidArgument like the reference)."""
            if srv.find_entry(UPLOADS_DIR, upload_id) is None:
                raise S3Error(404, "NoSuchUpload", "upload not found")
            sbucket, skey = self._parse_copy_source(src)
            sdir, _, sname = f"{BUCKETS_DIR}/{sbucket}/{skey}".rpartition("/")
            sentry = srv.find_entry(sdir, sname)
            if sentry is None:
                raise S3Error(404, "NoSuchKey", "copy source not found")
            src_size = sentry.attributes.file_size
            range_header = ""
            rng = self.headers.get("x-amz-copy-source-range", "")
            if rng:
                bad = S3Error(
                    400, "InvalidArgument",
                    "Range specified is not valid for source object "
                    f"of size: {src_size}")
                if not rng.startswith("bytes="):
                    raise bad
                try:
                    lo, _, hi = rng[6:].partition("-")
                    start = int(lo)
                    stop = int(hi) + 1 if hi else src_size
                except ValueError:
                    raise bad
                if start >= src_size or stop > src_size or start >= stop:
                    raise bad
                range_header = f"bytes={start}-{stop - 1}"
            r = srv.get_object(sbucket, skey, range_header=range_header,
                               stream=True)
            part_path = (f"{UPLOADS_DIR}/{upload_id}/"
                         f"{part_number:04d}.part")
            md5 = hashlib.md5()
            spool = _spool(r.iter_content(1 << 20), md5)
            try:
                pr = _session().put(srv._meta_url(part_path), data=spool,
                                    timeout=600,
                                    headers={"X-Swfs-Want-Md5": "1"})
                if pr.status_code == WRONG_SHARD_STATUS:
                    srv._note_stale_ring(pr)
                    spool.seek(0)
                    pr = _session().put(
                        srv._meta_url(part_path, refresh=True), data=spool,
                        timeout=600, headers={"X-Swfs-Want-Md5": "1"})
            finally:
                spool.close()
            if pr.status_code >= 300:
                raise S3Error(500, "InternalError", "part copy failed")
            root = ET.Element("CopyPartResult", xmlns=S3_NS)
            _el(root, "ETag", f'"{md5.hexdigest()}"')
            _el(root, "LastModified", _iso(int(time.time())))
            self._send(200, _xml_bytes(root))

        def _complete_multipart(self, bucket: str, key: str, upload_id: str):
            updir = f"{UPLOADS_DIR}/{upload_id}"
            meta_entry = srv.find_entry(UPLOADS_DIR, upload_id)
            if meta_entry is None:
                raise S3Error(404, "NoSuchUpload", "upload not found")
            meta = json.loads(meta_entry.extended.get("upload-meta", b"{}"))
            # numeric sort: '10000.part' must follow '9999.part'; S3
            # allows 10000 parts, above list_dir's default 1000 cap
            parts = sorted(
                (e for e in srv.list_dir(updir, limit=10001)
                 if e.name.endswith(".part")),
                key=lambda e: int(e.name.split(".")[0]))
            chunks, offset = [], 0
            for p in parts:
                for c in p.chunks:
                    nc = filer_pb2.FileChunk()
                    nc.CopyFrom(c)
                    nc.offset = offset + c.offset
                    chunks.append(nc)
                offset += p.attributes.file_size
            final = filer_pb2.Entry(name=key.rsplit("/", 1)[-1])
            final.chunks.extend(chunks)
            final.attributes.mtime = int(time.time())
            final.attributes.file_size = offset
            final.attributes.mime = meta.get("content_type", "")
            dir_ = f"{BUCKETS_DIR}/{bucket}/{key}".rpartition("/")[0]
            resp = srv.create_entry(dir_, final, timeout=30)
            if resp.error:
                raise S3Error(500, "InternalError", resp.error)
            # drop the staging dir but keep the chunks (owned by the object now)
            srv.delete_entry(UPLOADS_DIR, upload_id,
                             is_delete_data=False, is_recursive=True)
            root = ET.Element("CompleteMultipartUploadResult", xmlns=S3_NS)
            _el(root, "Location", f"/{bucket}/{key}")
            _el(root, "Bucket", bucket)
            _el(root, "Key", key)
            _el(root, "ETag", f'"{hashlib.md5(str(offset).encode()).hexdigest()}-{len(parts)}"')
            self._send(200, _xml_bytes(root))

        def _abort_multipart(self, bucket: str, key: str, upload_id: str):
            srv.delete_entry(UPLOADS_DIR, upload_id,
                             is_delete_data=True, is_recursive=True)
            self._send(204)

        def _list_parts(self, bucket: str, key: str, upload_id: str):
            updir = f"{UPLOADS_DIR}/{upload_id}"
            root = ET.Element("ListPartsResult", xmlns=S3_NS)
            _el(root, "Bucket", bucket)
            _el(root, "Key", key)
            _el(root, "UploadId", upload_id)
            for e in sorted(
                    (e for e in srv.list_dir(updir, limit=10001)
                     if e.name.endswith(".part")),
                    key=lambda e: int(e.name.split(".")[0])):
                p = _el(root, "Part")
                _el(p, "PartNumber", int(e.name.split(".")[0]))
                _el(p, "Size", e.attributes.file_size)
                _el(p, "LastModified", _iso(e.attributes.mtime))
            self._send(200, _xml_bytes(root))

        def _list_multipart_uploads(self, bucket: str):
            root = ET.Element("ListMultipartUploadsResult", xmlns=S3_NS)
            _el(root, "Bucket", bucket)
            for e in srv.list_dir(UPLOADS_DIR):
                meta = json.loads(e.extended.get("upload-meta", b"{}"))
                if meta.get("bucket") != bucket:
                    continue
                u = _el(root, "Upload")
                _el(u, "Key", meta.get("key", ""))
                _el(u, "UploadId", e.name)
                _el(u, "Initiated", _iso(e.attributes.crtime))
            self._send(200, _xml_bytes(root))

    return Handler


def _dir_entry(name: str) -> filer_pb2.Entry:
    e = filer_pb2.Entry(name=name, is_directory=True)
    now = int(time.time())
    e.attributes.crtime = now
    e.attributes.mtime = now
    e.attributes.file_mode = 0o770 | 0o40000
    return e


def _entry_etag(entry: filer_pb2.Entry) -> str:
    if entry.attributes.md5:
        return entry.attributes.md5.hex()
    if len(entry.chunks) == 1:
        return entry.chunks[0].e_tag or entry.chunks[0].file_id
    return hashlib.md5(
        b"".join((c.e_tag or c.file_id).encode() for c in entry.chunks)
    ).hexdigest()


def _walk(srv: S3Server, base_dir: str, rel: str, prefix: str,
          delimiter: str, marker: str, max_keys: int,
          contents: list, common: set) -> tuple[bool, str]:
    """Depth-first object listing with prefix/delimiter semantics
    (s3api_objects_list_handlers.go doListFilerEntries)."""
    truncated = False
    next_marker = ""
    for entry in srv.list_dir(base_dir, limit=10_000):
        key = f"{rel}{entry.name}"
        if entry.is_directory:
            sub = key + "/"
            if prefix and not (sub.startswith(prefix) or prefix.startswith(sub)):
                continue
            if delimiter == "/" and sub.startswith(prefix):
                # collapse at the first delimiter after the prefix
                tail = sub[len(prefix):]
                if "/" in tail[:-1] or tail:
                    common.add(prefix + tail.split("/")[0] + "/")
                    continue
            t, m = _walk(srv, f"{base_dir}/{entry.name}", sub, prefix,
                         delimiter, marker, max_keys, contents, common)
            if t:
                return True, m
            continue
        if prefix and not key.startswith(prefix):
            continue
        if marker and key <= marker:
            continue
        if delimiter == "/":
            tail = key[len(prefix):]
            if "/" in tail:
                common.add(prefix + tail.split("/")[0] + "/")
                continue
        if len(contents) >= max_keys:
            return True, next_marker
        contents.append((key, entry))
        next_marker = key
    return truncated, next_marker


def _decode_chunked_signing(body: bytes) -> bytes:
    """Strip aws-chunked transfer encoding (sigv4 streaming uploads)."""
    out = bytearray()
    i = 0
    while i < len(body):
        j = body.find(b"\r\n", i)
        if j < 0:
            break
        header = body[i:j].split(b";")[0]
        try:
            n = int(header, 16)
        except ValueError:
            break
        if n == 0:
            break
        out += body[j + 2:j + 2 + n]
        i = j + 2 + n + 2
    return bytes(out)
