"""AWS Signature V4 verification for the S3 gateway.

Rebuild of /root/reference/weed/s3api/auth_signature_v4.go +
auth_credentials.go: identities hold (accessKey, secretKey, actions);
requests are verified by recomputing the V4 signature over the canonical
request. Anonymous access is allowed when no identities are configured
(the reference behaves the same with an empty s3 config).
"""

from __future__ import annotations

import hashlib
import hmac
import urllib.parse
from dataclasses import dataclass, field


@dataclass
class Identity:
    name: str
    access_key: str
    secret_key: str
    actions: list[str] = field(default_factory=lambda: ["Admin"])

    def allows(self, action: str, bucket: str = "") -> bool:
        for a in self.actions:
            if a == "Admin":
                return True
            a_name, _, a_bucket = a.partition(":")
            if a_name != action:
                continue
            if not a_bucket or a_bucket == bucket or (
                    a_bucket.endswith("*") and bucket.startswith(a_bucket[:-1])):
                return True
        return False


class AuthError(Exception):
    def __init__(self, code: str, message: str):
        super().__init__(message)
        self.code = code


class IdentityAccessManagement:
    def __init__(self, identities: list[Identity] | None = None):
        self.identities = {i.access_key: i for i in (identities or [])}

    @property
    def enabled(self) -> bool:
        return bool(self.identities)

    def lookup(self, access_key: str) -> Identity:
        ident = self.identities.get(access_key)
        if ident is None:
            raise AuthError("InvalidAccessKeyId",
                            "The access key Id you provided does not exist")
        return ident

    def authenticate(self, method: str, path: str, query: str,
                     headers, payload_hash: str) -> Identity | None:
        """-> Identity, or None when the request carries no credentials
        (anonymous). Whether anonymous may proceed is an authorization
        question (bucket ACL / policy) decided by the caller — the
        reference splits authenticate/authorize the same way."""
        if not self.enabled:
            return None
        auth = headers.get("Authorization", "")
        if auth.startswith("AWS4-HMAC-SHA256 "):
            return self._verify_v4(auth, method, path, query, headers,
                                   payload_hash)
        qs = urllib.parse.parse_qs(query)
        if "X-Amz-Signature" in qs:
            return self._verify_presigned(method, path, qs, headers)
        if auth:
            raise AuthError("AccessDenied", "Unsupported Authorization type")
        return None  # anonymous

    # -- header auth -------------------------------------------------------

    def _verify_v4(self, auth: str, method: str, path: str, query: str,
                   headers, payload_hash: str) -> Identity:
        fields = {}
        for part in auth[len("AWS4-HMAC-SHA256 "):].split(","):
            k, _, v = part.strip().partition("=")
            fields[k] = v
        try:
            cred = fields["Credential"]
            signed_headers = fields["SignedHeaders"].split(";")
            given_sig = fields["Signature"]
        except KeyError as e:
            raise AuthError("AuthorizationHeaderMalformed", f"missing {e}")
        access_key, date, region, service, _ = _split_credential(cred)
        ident = self.lookup(access_key)
        amz_date = headers.get("x-amz-date") or headers.get("X-Amz-Date") or ""
        creq = _canonical_request(method, path, query, headers,
                                  signed_headers, payload_hash)
        sig = _signature(ident.secret_key, amz_date, date, region, service, creq)
        if not hmac.compare_digest(sig, given_sig):
            raise AuthError("SignatureDoesNotMatch",
                            "The request signature we calculated does not "
                            "match the signature you provided")
        return ident

    # -- presigned URLs ----------------------------------------------------

    def _verify_presigned(self, method: str, path: str, qs: dict,
                          headers) -> Identity:
        cred = qs["X-Amz-Credential"][0]
        access_key, date, region, service, _ = _split_credential(cred)
        ident = self.lookup(access_key)
        # expiry window (auth_signature_v4.go doesPresignedSignatureMatch:
        # X-Amz-Expires is mandatory — a presigned URL without it would
        # otherwise validate forever)
        if "X-Amz-Expires" not in qs:
            raise AuthError("AuthorizationQueryParametersError",
                            "X-Amz-Expires is required")
        import datetime as _dt

        try:
            expires = int(qs["X-Amz-Expires"][0])
        except ValueError:
            raise AuthError("AuthorizationQueryParametersError",
                            "X-Amz-Expires must be an integer")
        if not 1 <= expires <= 604800:
            raise AuthError("AuthorizationQueryParametersError",
                            "X-Amz-Expires must be between 1 and 604800")
        try:
            t0 = _dt.datetime.strptime(
                qs.get("X-Amz-Date", [""])[0], "%Y%m%dT%H%M%SZ"
            ).replace(tzinfo=_dt.timezone.utc)
        except ValueError:
            raise AuthError("AccessDenied", "bad X-Amz-Date")
        if _dt.datetime.now(_dt.timezone.utc) > t0 + _dt.timedelta(
                seconds=expires):
            raise AuthError("AccessDenied", "Request has expired")
        signed_headers = qs["X-Amz-SignedHeaders"][0].split(";")
        given_sig = qs["X-Amz-Signature"][0]
        amz_date = qs["X-Amz-Date"][0]
        # canonical query excludes the signature itself
        pairs = []
        for k in sorted(qs):
            if k == "X-Amz-Signature":
                continue
            for v in qs[k]:
                pairs.append(f"{_uri_encode(k)}={_uri_encode(v)}")
        creq = _canonical_request(method, path, "&".join(pairs), headers,
                                  signed_headers, "UNSIGNED-PAYLOAD",
                                  query_is_canonical=True)
        sig = _signature(ident.secret_key, amz_date, date, region, service, creq)
        if not hmac.compare_digest(sig, given_sig):
            raise AuthError("SignatureDoesNotMatch", "presigned signature mismatch")
        return ident


def _split_credential(cred: str):
    parts = cred.split("/")
    if len(parts) != 5:
        raise AuthError("AuthorizationHeaderMalformed", f"bad credential {cred}")
    return parts  # access_key, date, region, service, aws4_request


def _uri_encode(s: str, keep_slash: bool = False) -> str:
    safe = "-_.~" + ("/" if keep_slash else "")
    return urllib.parse.quote(s, safe=safe)


def _canonical_request(method: str, path: str, query: str, headers,
                       signed_headers: list[str], payload_hash: str,
                       query_is_canonical: bool = False) -> str:
    if query_is_canonical:
        cq = query
    else:
        qs = urllib.parse.parse_qs(query, keep_blank_values=True)
        pairs = []
        for k in sorted(qs):
            for v in sorted(qs[k]):
                pairs.append(f"{_uri_encode(k)}={_uri_encode(v)}")
        cq = "&".join(pairs)
    chdrs = ""
    for h in signed_headers:
        v = headers.get(h, "")
        chdrs += f"{h}:{' '.join(v.split())}\n"
    return "\n".join([
        method,
        # decode then encode once: the wire path is already percent-encoded
        # and clients sign the singly-encoded form (S3-style SigV4)
        _uri_encode(urllib.parse.unquote(path), keep_slash=True),
        cq,
        chdrs,
        ";".join(signed_headers),
        payload_hash,
    ])


def _signature(secret: str, amz_date: str, date: str, region: str,
               service: str, canonical_request: str) -> str:
    scope = f"{date}/{region}/{service}/aws4_request"
    string_to_sign = "\n".join([
        "AWS4-HMAC-SHA256", amz_date, scope,
        hashlib.sha256(canonical_request.encode()).hexdigest(),
    ])
    def h(key, msg):
        return hmac.new(key, msg.encode(), hashlib.sha256).digest()
    k = h(("AWS4" + secret).encode(), date)
    k = h(k, region)
    k = h(k, service)
    k = h(k, "aws4_request")
    return hmac.new(k, string_to_sign.encode(), hashlib.sha256).hexdigest()
