"""AWS Signature V4 verification for the S3 gateway.

Rebuild of /root/reference/weed/s3api/auth_signature_v4.go +
auth_credentials.go: identities hold (accessKey, secretKey, actions);
requests are verified by recomputing the V4 signature over the canonical
request. Anonymous access is allowed when no identities are configured
(the reference behaves the same with an empty s3 config).
"""

from __future__ import annotations

import hashlib
import hmac
import urllib.parse
from dataclasses import dataclass, field


@dataclass
class Identity:
    name: str
    access_key: str
    secret_key: str
    actions: list[str] = field(default_factory=lambda: ["Admin"])

    def allows(self, action: str, bucket: str = "") -> bool:
        for a in self.actions:
            if a == "Admin":
                return True
            a_name, _, a_bucket = a.partition(":")
            if a_name != action:
                continue
            if not a_bucket or a_bucket == bucket or (
                    a_bucket.endswith("*") and bucket.startswith(a_bucket[:-1])):
                return True
        return False


class AuthError(Exception):
    def __init__(self, code: str, message: str):
        super().__init__(message)
        self.code = code


class IdentityAccessManagement:
    def __init__(self, identities: list[Identity] | None = None):
        self.identities = {i.access_key: i for i in (identities or [])}

    @property
    def enabled(self) -> bool:
        return bool(self.identities)

    def lookup(self, access_key: str) -> Identity:
        ident = self.identities.get(access_key)
        if ident is None:
            raise AuthError("InvalidAccessKeyId",
                            "The access key Id you provided does not exist")
        return ident

    def authenticate(self, method: str, path: str, query: str,
                     headers, payload_hash: str) -> Identity | None:
        """-> Identity, or None when the request carries no credentials
        (anonymous). Whether anonymous may proceed is an authorization
        question (bucket ACL / policy) decided by the caller — the
        reference splits authenticate/authorize the same way."""
        if not self.enabled:
            return None
        auth = headers.get("Authorization", "")
        if auth.startswith("AWS4-HMAC-SHA256 "):
            return self._verify_v4(auth, method, path, query, headers,
                                   payload_hash)
        if auth.startswith("AWS "):  # legacy signature v2
            return self._verify_v2(auth, method, path, query, headers)
        qs = urllib.parse.parse_qs(query)
        if "X-Amz-Signature" in qs:
            return self._verify_presigned(method, path, qs, headers)
        if "Signature" in qs and "AWSAccessKeyId" in qs:
            return self._verify_presigned_v2(method, path, query, qs,
                                             headers)
        if auth:
            raise AuthError("AccessDenied", "Unsupported Authorization type")
        return None  # anonymous

    # -- header auth -------------------------------------------------------

    def _verify_v4(self, auth: str, method: str, path: str, query: str,
                   headers, payload_hash: str) -> Identity:
        fields = {}
        for part in auth[len("AWS4-HMAC-SHA256 "):].split(","):
            k, _, v = part.strip().partition("=")
            fields[k] = v
        try:
            cred = fields["Credential"]
            signed_headers = fields["SignedHeaders"].split(";")
            given_sig = fields["Signature"]
        except KeyError as e:
            raise AuthError("AuthorizationHeaderMalformed", f"missing {e}")
        access_key, date, region, service, _ = _split_credential(cred)
        ident = self.lookup(access_key)
        amz_date = headers.get("x-amz-date") or headers.get("X-Amz-Date") or ""
        creq = _canonical_request(method, path, query, headers,
                                  signed_headers, payload_hash)
        sig = _signature(ident.secret_key, amz_date, date, region, service, creq)
        if not hmac.compare_digest(sig, given_sig):
            raise AuthError("SignatureDoesNotMatch",
                            "The request signature we calculated does not "
                            "match the signature you provided")
        return ident

    # -- legacy signature v2 (auth_signature_v2.go) ------------------------

    # subresources included in the canonicalized resource (resourceList)
    _V2_SUBRESOURCES = (
        "acl", "delete", "lifecycle", "location", "logging", "notification",
        "partNumber", "policy", "requestPayment", "response-cache-control",
        "response-content-disposition", "response-content-encoding",
        "response-content-language", "response-content-type",
        "response-expires", "torrent", "uploadId", "uploads", "versionId",
        "versioning", "versions", "website",
    )

    def _v2_string_to_sign(self, method: str, path: str, query: str,
                           headers, date: str) -> str:
        """getStringToSignV2: Verb\\nContent-MD5\\nContent-Type\\nDate\\n
        CanonicalizedAmzHeaders + CanonicalizedResource."""
        amz: dict[str, list[str]] = {}
        for k in headers.keys():
            lk = k.lower()
            if not lk.startswith("x-amz-") or lk in amz:
                continue
            if hasattr(headers, "get_all"):  # email.message.Message
                vals = headers.get_all(k) or []
            else:
                vals = [headers.get(k, "")]
            amz[lk] = [" ".join(str(v).split()) for v in vals]
        canonical_amz = "".join(f"{k}:{','.join(v)}\n"
                                for k, v in sorted(amz.items()))
        qs = urllib.parse.parse_qs(query, keep_blank_values=True)
        sub = []
        for key in self._V2_SUBRESOURCES:
            if key in qs:
                v = qs[key][0]
                sub.append(f"{key}={v}" if v else key)
        resource = urllib.parse.quote(urllib.parse.unquote(path), safe="/-_.~")
        if sub:
            resource += "?" + "&".join(sub)
        return "\n".join([method,
                          headers.get("Content-MD5", "") or "",
                          headers.get("Content-Type", "") or "",
                          date,
                          canonical_amz + resource])

    def _v2_signature(self, secret: str, string_to_sign: str) -> str:
        import base64
        import hashlib as _hashlib

        return base64.b64encode(hmac.new(
            secret.encode(), string_to_sign.encode(),
            _hashlib.sha1).digest()).decode()

    def _verify_v2(self, auth: str, method: str, path: str, query: str,
                   headers) -> Identity:
        """Authorization: AWS AccessKeyId:Signature (doesSignV2Match)."""
        access_key, _, given = auth[len("AWS "):].strip().partition(":")
        if not given:
            raise AuthError("AuthorizationHeaderMalformed", "bad v2 header")
        ident = self.lookup(access_key)
        date = headers.get("Date", "") or headers.get("x-amz-date", "")
        self._check_v2_freshness(date)
        sts = self._v2_string_to_sign(method, path, query, headers, date)
        want = self._v2_signature(ident.secret_key, sts)
        if not hmac.compare_digest(want, given):
            raise AuthError("SignatureDoesNotMatch",
                            "v2 signature mismatch")
        return ident

    @staticmethod
    def _check_v2_freshness(date: str) -> None:
        """v2 signatures carry no payload-hash claim, so bound their replay
        window by the signed Date (AWS's 15-minute skew rule)."""
        import email.utils
        import time as _time

        try:
            signed_at = email.utils.parsedate_to_datetime(date).timestamp()
        except (TypeError, ValueError):
            raise AuthError("AccessDenied", "missing or bad Date header")
        if abs(_time.time() - signed_at) > 900:
            raise AuthError("AccessDenied", "Request has expired")

    def _verify_presigned_v2(self, method: str, path: str, raw_query: str,
                             qs: dict, headers) -> Identity:
        """?AWSAccessKeyId=..&Expires=..&Signature=..
        (doesPresignV2SignatureMatch)."""
        import time as _time

        ident = self.lookup(qs["AWSAccessKeyId"][0])
        expires = qs.get("Expires", [""])[0]
        try:
            if int(expires) < _time.time():
                raise AuthError("AccessDenied", "Request has expired")
        except ValueError:
            raise AuthError("AccessDenied", "bad Expires")
        # strip the auth params from the RAW query (re-encoding decoded
        # values would corrupt '+', '&' or '=' inside them)
        rest = "&".join(
            p for p in raw_query.split("&")
            if p.split("=", 1)[0] not in ("AWSAccessKeyId", "Expires",
                                          "Signature"))
        sts = self._v2_string_to_sign(method, path, rest, headers, expires)
        want = self._v2_signature(ident.secret_key, sts)
        given = qs["Signature"][0]
        if not hmac.compare_digest(want, given):
            raise AuthError("SignatureDoesNotMatch",
                            "presigned v2 signature mismatch")
        return ident

    # -- presigned URLs ----------------------------------------------------

    def _verify_presigned(self, method: str, path: str, qs: dict,
                          headers) -> Identity:
        cred = qs["X-Amz-Credential"][0]
        access_key, date, region, service, _ = _split_credential(cred)
        ident = self.lookup(access_key)
        # expiry window (auth_signature_v4.go doesPresignedSignatureMatch:
        # X-Amz-Expires is mandatory — a presigned URL without it would
        # otherwise validate forever)
        if "X-Amz-Expires" not in qs:
            raise AuthError("AuthorizationQueryParametersError",
                            "X-Amz-Expires is required")
        import datetime as _dt

        try:
            expires = int(qs["X-Amz-Expires"][0])
        except ValueError:
            raise AuthError("AuthorizationQueryParametersError",
                            "X-Amz-Expires must be an integer")
        if not 1 <= expires <= 604800:
            raise AuthError("AuthorizationQueryParametersError",
                            "X-Amz-Expires must be between 1 and 604800")
        try:
            t0 = _dt.datetime.strptime(
                qs.get("X-Amz-Date", [""])[0], "%Y%m%dT%H%M%SZ"
            ).replace(tzinfo=_dt.timezone.utc)
        except ValueError:
            raise AuthError("AccessDenied", "bad X-Amz-Date")
        if _dt.datetime.now(_dt.timezone.utc) > t0 + _dt.timedelta(
                seconds=expires):
            raise AuthError("AccessDenied", "Request has expired")
        signed_headers = qs["X-Amz-SignedHeaders"][0].split(";")
        given_sig = qs["X-Amz-Signature"][0]
        amz_date = qs["X-Amz-Date"][0]
        # canonical query excludes the signature itself
        pairs = []
        for k in sorted(qs):
            if k == "X-Amz-Signature":
                continue
            for v in qs[k]:
                pairs.append(f"{_uri_encode(k)}={_uri_encode(v)}")
        creq = _canonical_request(method, path, "&".join(pairs), headers,
                                  signed_headers, "UNSIGNED-PAYLOAD",
                                  query_is_canonical=True)
        sig = _signature(ident.secret_key, amz_date, date, region, service, creq)
        if not hmac.compare_digest(sig, given_sig):
            raise AuthError("SignatureDoesNotMatch", "presigned signature mismatch")
        return ident


def _split_credential(cred: str):
    parts = cred.split("/")
    if len(parts) != 5:
        raise AuthError("AuthorizationHeaderMalformed", f"bad credential {cred}")
    return parts  # access_key, date, region, service, aws4_request


def _uri_encode(s: str, keep_slash: bool = False) -> str:
    safe = "-_.~" + ("/" if keep_slash else "")
    return urllib.parse.quote(s, safe=safe)


def _canonical_request(method: str, path: str, query: str, headers,
                       signed_headers: list[str], payload_hash: str,
                       query_is_canonical: bool = False) -> str:
    if query_is_canonical:
        cq = query
    else:
        qs = urllib.parse.parse_qs(query, keep_blank_values=True)
        pairs = []
        for k in sorted(qs):
            for v in sorted(qs[k]):
                pairs.append(f"{_uri_encode(k)}={_uri_encode(v)}")
        cq = "&".join(pairs)
    chdrs = ""
    for h in signed_headers:
        v = headers.get(h, "")
        chdrs += f"{h}:{' '.join(v.split())}\n"
    return "\n".join([
        method,
        # decode then encode once: the wire path is already percent-encoded
        # and clients sign the singly-encoded form (S3-style SigV4)
        _uri_encode(urllib.parse.unquote(path), keep_slash=True),
        cq,
        chdrs,
        ";".join(signed_headers),
        payload_hash,
    ])


def _signature(secret: str, amz_date: str, date: str, region: str,
               service: str, canonical_request: str) -> str:
    scope = f"{date}/{region}/{service}/aws4_request"
    string_to_sign = "\n".join([
        "AWS4-HMAC-SHA256", amz_date, scope,
        hashlib.sha256(canonical_request.encode()).hexdigest(),
    ])
    def h(key, msg):
        return hmac.new(key, msg.encode(), hashlib.sha256).digest()
    k = h(("AWS4" + secret).encode(), date)
    k = h(k, region)
    k = h(k, service)
    k = h(k, "aws4_request")
    return hmac.new(k, string_to_sign.encode(), hashlib.sha256).hexdigest()
