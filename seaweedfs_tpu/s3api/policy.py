"""Bucket policy storage + evaluation.

Rebuild of /root/reference/weed/s3api/policy/ (policy.go) and the bucket
policy handlers (s3api_bucket_policy_handlers.go): an AWS-style JSON policy
document attached to a bucket, evaluated per request alongside identity
actions. Supported subset (what the reference's own evaluator covers):

  * Effect Allow / Deny (explicit Deny wins)
  * Principal "*" / {"AWS": "*"} / {"AWS": [arns or access keys]}
  * Action "s3:*" or concrete names, mapped onto this gateway's verbs
  * Resource "arn:aws:s3:::bucket", "arn:aws:s3:::bucket/*" and
    key-prefix wildcards
"""

from __future__ import annotations

import fnmatch
import json

# s3 policy action name -> gateway action verb (same table the IAM API uses)
_ACTION_VERBS = {
    "s3:GetObject": "Read",
    "s3:GetObjectVersion": "Read",
    "s3:ListBucket": "List",
    "s3:ListBucketVersions": "List",
    "s3:PutObject": "Write",
    "s3:DeleteObject": "Write",
    "s3:DeleteObjectVersion": "Write",
    # tag reads ride the Read action (matching the gateway's _action_for);
    # tag writes are the distinct Tagging action
    "s3:GetObjectTagging": "Read",
    "s3:PutObjectTagging": "Tagging",
    "s3:DeleteObjectTagging": "Tagging",
    "s3:GetBucketAcl": "ReadAcp",
    "s3:PutBucketAcl": "WriteAcp",
    "s3:GetObjectAcl": "ReadAcp",
    "s3:PutObjectAcl": "WriteAcp",
    "s3:*": "*",
    "*": "*",
}


class PolicyError(ValueError):
    pass


class BucketPolicy:
    def __init__(self, doc: dict):
        if not isinstance(doc, dict) or not isinstance(
                doc.get("Statement"), list):
            raise PolicyError("policy must carry a Statement list")
        self.doc = doc
        for st in doc["Statement"]:
            if st.get("Effect") not in ("Allow", "Deny"):
                raise PolicyError(f"bad Effect {st.get('Effect')!r}")

    @classmethod
    def parse(cls, blob: bytes) -> "BucketPolicy":
        try:
            return cls(json.loads(blob))
        except json.JSONDecodeError as e:
            raise PolicyError(f"invalid policy JSON: {e}")

    def to_bytes(self) -> bytes:
        return json.dumps(self.doc).encode()

    # -- evaluation --------------------------------------------------------

    def decide(self, *, principal: str | None, action: str, bucket: str,
               key: str = "") -> str | None:
        """-> "Allow", "Deny", or None (policy silent). `principal` is the
        caller's access key, or None for anonymous."""
        verdict: str | None = None
        for st in self.doc["Statement"]:
            if not self._principal_matches(st.get("Principal"), principal):
                continue
            if not self._action_matches(st.get("Action"), action):
                continue
            if not self._resource_matches(st.get("Resource"), bucket, key):
                continue
            if st["Effect"] == "Deny":
                return "Deny"  # explicit deny short-circuits
            verdict = "Allow"
        return verdict

    @staticmethod
    def _principal_matches(principal, caller: str | None) -> bool:
        if principal is None:
            return False
        if principal == "*":
            return True
        if isinstance(principal, dict):
            aws = principal.get("AWS", [])
            ids = [aws] if isinstance(aws, str) else list(aws)
            if "*" in ids:
                return True
            return caller is not None and any(
                caller == i or i.endswith(f":user/{caller}") for i in ids)
        return False

    @staticmethod
    def _action_matches(actions, verb: str) -> bool:
        if actions is None:
            return False
        names = [actions] if isinstance(actions, str) else list(actions)
        for name in names:
            mapped = _ACTION_VERBS.get(name)
            if mapped == "*" or mapped == verb:
                return True
        return False

    @staticmethod
    def _resource_matches(resources, bucket: str, key: str) -> bool:
        if resources is None:
            return False
        arns = [resources] if isinstance(resources, str) else list(resources)
        bucket_arn = f"arn:aws:s3:::{bucket}"
        object_arn = f"arn:aws:s3:::{bucket}/{key}" if key else bucket_arn
        for arn in arns:
            if arn in ("*", "arn:aws:s3:::*"):
                return True
            if fnmatch.fnmatchcase(bucket_arn, arn) or fnmatch.fnmatchcase(
                    object_arn, arn):
                return True
        return False
