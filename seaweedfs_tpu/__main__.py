import sys

from .command import main

sys.exit(main())
