"""Client-side verbs: assign, upload, delete, submit.

Rebuild of /root/reference/weed/operation/ — `Assign`
(assign_file_id.go:37), `Upload`/`UploadData` with gzip + retry
(upload_content.go:85,134-160), `DeleteFiles` (delete_content.go), and
`SubmitFiles` (submit.go:45).
"""

from __future__ import annotations

import gzip
import threading
import time
from dataclasses import dataclass, field

import grpc
import requests

from ..pb import master_pb2, rpc
from ..utils import glog, trace
from ..utils.retry import Backoff, guarded_attempt
from ..utils.stats import (
    CLIENT_ASSIGN_COUNTER,
    CLIENT_ASSIGN_SECONDS,
    CLIENT_UPLOAD_SECONDS,
)

_tl = threading.local()


def thread_session() -> requests.Session:
    """Default per-thread keepalive session for volume-server uploads.
    requests.Session is not safe for concurrent use, so each worker
    thread gets its own (filer autochunker, S3 gateway, replication sinks
    all upload from thread pools)."""
    s = getattr(_tl, "session", None)
    if s is None:
        s = _tl.session = requests.Session()
        s.trust_env = False  # skip per-request proxy-env scans
    # refreshed per call: under SWFS_HTTPS every internal leg verifies
    # the cluster CA (or skips verification on self-signed dev setups)
    from ..utils.http import requests_verify

    s.verify = requests_verify()
    return s

COMPRESS_MIN = 128  # don't bother gzipping tiny payloads


@dataclass
class AssignResult:
    fid: str = ""
    url: str = ""
    public_url: str = ""
    count: int = 0
    error: str = ""
    auth: str = ""  # write JWT minted by the master (jwt.go:30)
    replicas: list = field(default_factory=list)


# Master replies that describe topology churn or a momentarily-full
# cluster, not a bad request: a node mid-(re)registration after a
# heartbeat-stream break empties the writable set for a second or two,
# so these are worth re-asking after backoff (the reference's
# assign_file_id retries its whole lookup the same way). Placement
# SHAPE errors ("not enough racks", "not enough other data centers")
# are deliberately absent: retrying cannot conjure a rack, and the
# caller should see the config error immediately.
_TRANSIENT_ASSIGN = ("no writable volumes", "no free volume slot",
                     "not enough servers",
                     "no data center with enough free slots",
                     "volume growth rpc failed",
                     # QoS pressure shed (ISSUE 8): an explicit
                     # early rejection with a retry hint — pressure
                     # drains in seconds, exactly what backoff is for
                     "overloaded")


def assign(master: str, *, count: int = 1, collection: str = "",
           replication: str = "", ttl: str = "",
           data_center: str = "") -> AssignResult:
    """Instrumented wrapper over the failover assign loop: latency and
    outcome counters make the bench's per-PUT master cost attributable
    (fid-lease batching shows up as fewer assign ops per 1k writes);
    inside a trace the whole master round-trip is a `client.assign`
    child span."""
    with trace.span("client.assign", child_only=True, count=count) as tsp:
        with CLIENT_ASSIGN_SECONDS.time():
            result = _assign(master, count=count, collection=collection,
                             replication=replication, ttl=ttl,
                             data_center=data_center)
        if result.error:
            CLIENT_ASSIGN_COUNTER.inc(outcome="error")
            # attr, not set_error: a cluster-full burst hits every traced
            # write's lease refill, and keep-if-error retention on each
            # would flush the bounded retained set (the master's
            # /dir/assign handler makes the same call)
            tsp.set_attr(assignError=str(result.error)[:120])
        else:
            CLIENT_ASSIGN_COUNTER.inc(outcome="ok")
            CLIENT_ASSIGN_COUNTER.inc(max(1, int(result.count or 1)),
                                      outcome="fids")
            tsp.set_attr(fid=result.fid, leased=int(result.count or 1))
    return result


def _assign(master: str, *, count: int = 1, collection: str = "",
            replication: str = "", ttl: str = "",
            data_center: str = "") -> AssignResult:
    """Assign a file id, surviving master faults (assign_file_id.go's
    retried LookupJwt path + masterclient failover): `master` may be a
    comma-separated list; transient gRPC failures rotate to the next
    master, a follower's "not the leader; ask <addr>" reply redirects
    to (and remembers) the named leader, and capacity errors during
    topology churn are re-asked after backoff."""
    masters = [m.strip() for m in str(master).split(",") if m.strip()]
    if not masters:
        # pure configuration error — don't sleep through retry cycles
        return AssignResult(error="assign: no masters configured")
    req = master_pb2.AssignRequest(
        count=count, collection=collection, replication=replication,
        ttl=ttl, data_center=data_center)
    cycles = 4
    bo = Backoff(wait_init=0.3)
    # None until some master answers or fails; a bare "not the leader"
    # redirect is recorded only when nothing more informative is held
    last_err: Exception | str | None = None
    queue = list(masters)
    for cycle in range(cycles):
        # `seen` only bounds redirects within one cycle: a leader that
        # failed transiently this cycle is worth re-asking next cycle
        seen: set[str] = set()
        while queue:
            m = queue.pop(0)
            seen.add(m)
            try:
                call = lambda: rpc.master_stub(  # noqa: E731
                    rpc.grpc_address(m)).Assign(req, timeout=30)
                # ordinary first-cycle traffic bypasses the breaker;
                # re-asks against a failing master are admission-capped
                resp = guarded_attempt(m, call) if cycle else call()
            except (grpc.RpcError, ConnectionError, TimeoutError) as e:
                # rotate on EVERY RpcError (masterclient tryAllMasters
                # does not classify): a master mid-shutdown or
                # mid-election can surface UNKNOWN/CANCELLED, not just
                # UNAVAILABLE, and the cycle bound already caps retries —
                # exhaustion returns an AssignResult error, never raises
                glog.v(1, f"assign via {m} failed: {e}")
                last_err = e
                continue
            if resp.error:
                # follower redirect: "not the leader; ask host:port"
                leader = resp.error.rsplit("ask ", 1)[-1].strip() \
                    if "not the leader" in resp.error else ""
                if leader:
                    if leader not in seen:
                        queue.insert(0, leader)
                    elif not (isinstance(last_err, str)
                              and "not the leader" not in last_err):
                        # redirect back at a master that just failed
                        # this cycle — transient leader outage; record
                        # it ONLY if no more informative reply (a
                        # capacity/config error from the real leader)
                        # is already held, and let the next cycle
                        # re-ask after backoff
                        last_err = resp.error
                    continue
                if any(t in resp.error for t in _TRANSIENT_ASSIGN):
                    glog.v(1, f"assign via {m}: transient capacity "
                              f"error: {resp.error}")
                    last_err = resp.error
                    continue
                return AssignResult(error=resp.error)
            return AssignResult(
                fid=resp.fid, url=resp.location.url,
                public_url=resp.location.public_url, count=resp.count,
                auth=resp.auth,
                replicas=[l.url for l in resp.replicas],
            )
        queue = [m for m in masters]
        if cycle < cycles - 1:
            bo.sleep()
    if isinstance(last_err, str):
        # a master DID answer, definitively; don't misreport a capacity
        # condition as a connectivity problem
        return AssignResult(error=f"assign: {last_err} "
                                  f"(after {cycles} cycles)")
    return AssignResult(error=f"assign: no master reachable "
                              f"({masters}): {last_err}")


@dataclass
class UploadResult:
    name: str = ""
    size: int = 0
    etag: str = ""
    error: str = ""


def upload_data(url: str, data: bytes, *, filename: str = "",
                mime: str = "application/octet-stream", ttl: str = "",
                compress: bool = True, retries: int = 3,
                auth: str = "", session=None) -> UploadResult:
    """PUT needle bytes to a volume server (UploadData w/ retry,
    upload_content.go:85,134). Rides the wdclient keep-alive pool
    (ISSUE 9) so the filer-autochunker/replication upload legs reuse
    connections — and, under SWFS_HTTPS, amortize TLS handshakes —
    instead of dialing per chunk. Pass a requests.Session to pin a
    specific keepalive session instead (legacy callers)."""
    headers = trace.inject_headers(
        {"Content-Type": mime or "application/octet-stream"})
    if auth:
        headers["Authorization"] = f"Bearer {auth}"
    body = data
    if (compress and len(data) >= COMPRESS_MIN and _compressible(mime)):
        gz = gzip.compress(data, 3)
        if len(gz) < len(data) * 0.9:
            body = gz
            headers["Content-Encoding"] = "gzip"
    if ttl:
        url += ("&" if "?" in url else "?") + f"ttl={ttl}"
    last: Exception | None = None
    bo = Backoff(wait_init=0.1)
    for attempt in range(retries):
        try:
            with trace.span("client.upload", child_only=True,
                            bytes=len(body)), \
                    CLIENT_UPLOAD_SECONDS.time():
                if session is not None:
                    rr = session.put(url, data=body, headers=headers,
                                     timeout=60)
                    status, text, jload = rr.status_code, rr.text, rr.json
                else:
                    from ..wdclient import pool

                    rr = pool.put(url, body=body, headers=headers,
                                  timeout=60)
                    status, text, jload = rr.status, rr.text, rr.json
            # ordinary write replies stamp the volume server's live
            # backpressure score (ROADMAP 5(b)): feed it into the hot
            # signal so upload windows collapse BEFORE the first 429
            try:
                _p = (rr.headers or {}).get("X-Swfs-Pressure")
                if _p:
                    from ..qos.pressure import SIGNAL

                    SIGNAL.report_score(float(_p))
            except (TypeError, ValueError, AttributeError):
                pass
            if status < 300:
                j = jload()
                return UploadResult(name=j.get("name", filename),
                                    size=j.get("size", len(data)),
                                    etag=j.get("eTag", ""))
            last = IOError(f"{status}: {text[:200]}")
            if status in (429, 503):
                # a throttled upload leg marks the process hot: the
                # pipelined PUT window (ISSUE 14) collapses to
                # sequential instead of fanning more load out
                from ..qos.pressure import SIGNAL

                SIGNAL.report_shed()
            if status < 500:
                break  # 4xx (bad request, auth) won't improve on retry
        except (OSError, requests.RequestException) as e:
            last = e
            from ..utils.retry import is_retryable

            if not is_retryable(e):
                break  # e.g. a certificate rejection: fail fast
        if attempt < retries - 1:
            bo.sleep()
    return UploadResult(error=str(last))


def _compressible(mime: str) -> bool:
    if mime.startswith("text/") or mime.endswith(("json", "xml", "javascript")):
        return True
    return mime in ("", "application/octet-stream")


def sync_stride_marker(stub, volume_id: int, collection: str, base: str,
                       ext: str = ".lrg", is_ec: bool = False) -> None:
    """Mirror the SOURCE's stride-marker file next to freshly copied
    volume/EC index bytes (volume copy, backup, EC shard copy).

    Raw-byte copies carry the source's offset width, so the local marker
    must reflect the source, not this process's mode — stamping local
    mode at a copy site would make the open-time stride guards
    (storage/volume.py, storage/ec_files.py check_ecx_stride) a
    tautology and let a cross-mode copy misparse silently."""
    import os

    import grpc

    from ..pb import volume_server_pb2 as vs

    try:
        for _ in stub.CopyFile(vs.CopyFileRequest(
                volume_id=volume_id, ext=ext, collection=collection,
                is_ec_volume=is_ec), timeout=60):
            pass
        with open(base + ext, "wb"):
            pass
    except grpc.RpcError as e:
        if e.code() != grpc.StatusCode.NOT_FOUND:
            raise
        try:
            os.remove(base + ext)
        except FileNotFoundError:
            pass


def delete_files(master: str, fids: list[str]) -> list[dict]:
    """Group fids by volume location and fan out BatchDelete RPCs
    (delete_content.go DeleteFilesAtOneVolumeServer)."""
    from ..pb import volume_server_pb2 as vs
    from ..wdclient import MasterClient

    mc = MasterClient(master)
    by_server: dict[str, list[str]] = {}
    results = []
    for fid in fids:
        try:
            urls = mc.lookup_file_id(fid)
        except LookupError as e:
            results.append({"fid": fid, "error": str(e)})
            continue
        server = urls[0].split("//", 1)[1].split("/", 1)[0]
        by_server.setdefault(server, []).append(fid)
    for server, server_fids in by_server.items():
        stub = rpc.volume_stub(rpc.grpc_address(server))
        resp = stub.BatchDelete(
            vs.BatchDeleteRequest(file_ids=server_fids), timeout=60)
        for res in resp.results:
            results.append({"fid": res.file_id, "size": res.size,
                            "error": res.error or None})
    return results


def submit(master: str, data: bytes, *, filename: str = "",
           collection: str = "", replication: str = "", ttl: str = "",
           mime: str = "") -> dict:
    """assign + upload in one call (SubmitFiles, submit.go:45)."""
    a = assign(master, collection=collection, replication=replication, ttl=ttl)
    if a.error:
        return {"error": a.error}
    from ..utils.http import url_for

    r = upload_data(url_for(a.url, a.fid), data, filename=filename,
                    mime=mime, ttl=ttl, auth=a.auth)
    if r.error:
        return {"error": r.error}
    return {"fid": a.fid, "url": a.url, "size": r.size, "eTag": r.etag}
