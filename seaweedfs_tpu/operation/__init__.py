"""Client-side verbs: assign, upload, delete, submit.

Rebuild of /root/reference/weed/operation/ — `Assign`
(assign_file_id.go:37), `Upload`/`UploadData` with gzip + retry
(upload_content.go:85,134-160), `DeleteFiles` (delete_content.go), and
`SubmitFiles` (submit.go:45).
"""

from __future__ import annotations

import gzip
import threading
import time
from dataclasses import dataclass, field

import requests

from ..pb import master_pb2, rpc

_tl = threading.local()


def thread_session() -> requests.Session:
    """Default per-thread keepalive session for volume-server uploads.
    requests.Session is not safe for concurrent use, so each worker
    thread gets its own (filer autochunker, S3 gateway, replication sinks
    all upload from thread pools)."""
    s = getattr(_tl, "session", None)
    if s is None:
        s = _tl.session = requests.Session()
        s.trust_env = False  # skip per-request proxy-env scans
    return s

COMPRESS_MIN = 128  # don't bother gzipping tiny payloads


@dataclass
class AssignResult:
    fid: str = ""
    url: str = ""
    public_url: str = ""
    count: int = 0
    error: str = ""
    auth: str = ""  # write JWT minted by the master (jwt.go:30)
    replicas: list = field(default_factory=list)


def assign(master: str, *, count: int = 1, collection: str = "",
           replication: str = "", ttl: str = "", data_center: str = "") -> AssignResult:
    stub = rpc.master_stub(rpc.grpc_address(master))
    resp = stub.Assign(master_pb2.AssignRequest(
        count=count, collection=collection, replication=replication,
        ttl=ttl, data_center=data_center), timeout=30)
    if resp.error:
        return AssignResult(error=resp.error)
    return AssignResult(
        fid=resp.fid, url=resp.location.url,
        public_url=resp.location.public_url, count=resp.count,
        auth=resp.auth,
        replicas=[l.url for l in resp.replicas],
    )


@dataclass
class UploadResult:
    name: str = ""
    size: int = 0
    etag: str = ""
    error: str = ""


def upload_data(url: str, data: bytes, *, filename: str = "",
                mime: str = "application/octet-stream", ttl: str = "",
                compress: bool = True, retries: int = 3,
                auth: str = "", session=None) -> UploadResult:
    """PUT needle bytes to a volume server (UploadData w/ retry,
    upload_content.go:85,134). Pass a requests.Session to reuse keepalive
    connections on hot paths (one session per thread — Session is not
    safe for concurrent use)."""
    headers = {"Content-Type": mime or "application/octet-stream"}
    if auth:
        headers["Authorization"] = f"Bearer {auth}"
    body = data
    if (compress and len(data) >= COMPRESS_MIN and _compressible(mime)):
        gz = gzip.compress(data, 3)
        if len(gz) < len(data) * 0.9:
            body = gz
            headers["Content-Encoding"] = "gzip"
    if ttl:
        url += ("&" if "?" in url else "?") + f"ttl={ttl}"
    last: Exception | None = None
    http = session or thread_session()
    for attempt in range(retries):
        try:
            r = http.put(url, data=body, headers=headers, timeout=60)
            if r.status_code < 300:
                j = r.json()
                return UploadResult(name=j.get("name", filename),
                                    size=j.get("size", len(data)),
                                    etag=j.get("eTag", ""))
            last = IOError(f"{r.status_code}: {r.text[:200]}")
        except requests.RequestException as e:
            last = e
        time.sleep(0.2 * (attempt + 1))
    return UploadResult(error=str(last))


def _compressible(mime: str) -> bool:
    if mime.startswith("text/") or mime.endswith(("json", "xml", "javascript")):
        return True
    return mime in ("", "application/octet-stream")


def sync_stride_marker(stub, volume_id: int, collection: str, base: str,
                       ext: str = ".lrg", is_ec: bool = False) -> None:
    """Mirror the SOURCE's stride-marker file next to freshly copied
    volume/EC index bytes (volume copy, backup, EC shard copy).

    Raw-byte copies carry the source's offset width, so the local marker
    must reflect the source, not this process's mode — stamping local
    mode at a copy site would make the open-time stride guards
    (storage/volume.py, storage/ec_files.py check_ecx_stride) a
    tautology and let a cross-mode copy misparse silently."""
    import os

    import grpc

    from ..pb import volume_server_pb2 as vs

    try:
        for _ in stub.CopyFile(vs.CopyFileRequest(
                volume_id=volume_id, ext=ext, collection=collection,
                is_ec_volume=is_ec), timeout=60):
            pass
        with open(base + ext, "wb"):
            pass
    except grpc.RpcError as e:
        if e.code() != grpc.StatusCode.NOT_FOUND:
            raise
        try:
            os.remove(base + ext)
        except FileNotFoundError:
            pass


def delete_files(master: str, fids: list[str]) -> list[dict]:
    """Group fids by volume location and fan out BatchDelete RPCs
    (delete_content.go DeleteFilesAtOneVolumeServer)."""
    from ..pb import volume_server_pb2 as vs
    from ..wdclient import MasterClient

    mc = MasterClient(master)
    by_server: dict[str, list[str]] = {}
    results = []
    for fid in fids:
        try:
            urls = mc.lookup_file_id(fid)
        except LookupError as e:
            results.append({"fid": fid, "error": str(e)})
            continue
        server = urls[0].split("//", 1)[1].split("/", 1)[0]
        by_server.setdefault(server, []).append(fid)
    for server, server_fids in by_server.items():
        stub = rpc.volume_stub(rpc.grpc_address(server))
        resp = stub.BatchDelete(
            vs.BatchDeleteRequest(file_ids=server_fids), timeout=60)
        for res in resp.results:
            results.append({"fid": res.file_id, "size": res.size,
                            "error": res.error or None})
    return results


def submit(master: str, data: bytes, *, filename: str = "",
           collection: str = "", replication: str = "", ttl: str = "",
           mime: str = "") -> dict:
    """assign + upload in one call (SubmitFiles, submit.go:45)."""
    a = assign(master, collection=collection, replication=replication, ttl=ttl)
    if a.error:
        return {"error": a.error}
    r = upload_data(f"http://{a.url}/{a.fid}", data, filename=filename,
                    mime=mime, ttl=ttl, auth=a.auth)
    if r.error:
        return {"error": r.error}
    return {"fid": a.fid, "url": a.url, "size": r.size, "eTag": r.etag}
