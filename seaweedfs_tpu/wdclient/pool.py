"""Keep-alive pooling HTTP(S) client for the cluster's internal legs.

ISSUE 9 connection economics: the filer→volume chunk reads and the
replication fan-out previously paid a fresh TCP (and, under SWFS_HTTPS,
a fresh TLS handshake) per request — PR 2's syscall-diet A/B showed
connection setup dominating small-object latency, and TLS multiplies
that cost by the handshake round-trips. This pool replaces those
per-request sockets with a process-wide, per-host bounded pool of
`http.client` connections:

  * bounded idle set per (scheme, host, port) — `SWFS_HTTP_POOL_SIZE`
    connections (default 8), excess returns close (evict);
  * idle reaping — a connection idle past `SWFS_HTTP_POOL_IDLE_S`
    (default 15s) is closed at next access instead of reused (volume
    servers are free to reap their side sooner; see stale retry);
  * stale-reuse retry — a POOLED connection failing before the response
    line arrives means the server reaped it while idle; the request is
    retried ONCE on a fresh connection (a fresh connection's failure is
    real and propagates);
  * metrics — `SeaweedFS_http_pool_ops` (hit/miss/expired/evict/
    stale_retry/disabled), `SeaweedFS_http_pool_open_connections`, and
    `SeaweedFS_tls_handshakes{role="client"}` so the HTTPS A/B can show
    handshake amortization directly.

`SWFS_HTTP_POOL=0` disables reuse (every request dials fresh — the A/B
OFF arm) without changing any call site.

Error surface: everything raised here is an OSError subtype (socket and
ssl errors raw, `http.client` protocol errors wrapped in
ConnectionError), so `utils.retry.is_retryable` classifies pool
failures exactly like the requests-based paths — including the fail-
fast ssl.SSLCertVerificationError when a peer's certificate is wrong.
"""

from __future__ import annotations

import http.client
import os
import ssl
import threading
import time
from collections import deque
from urllib.parse import urlsplit

from ..utils.locks import wlock
from ..utils.stats import HTTP_POOL_OPEN, HTTP_POOL_OPS, TLS_HANDSHAKES


class PoolResponse:
    """Fully-drained response (the internal legs move needle/chunk-sized
    bodies; draining is what makes the connection reusable)."""

    __slots__ = ("status", "headers", "data")

    def __init__(self, status: int, headers, data: bytes):
        self.status = status
        self.headers = headers
        self.data = data

    def getheader(self, name: str, default=None):
        return self.headers.get(name, default)

    @property
    def text(self) -> str:
        return self.data.decode(errors="replace")

    def json(self):
        import json as _json

        return _json.loads(self.data)


def _pool_size() -> int:
    return int(os.environ.get("SWFS_HTTP_POOL_SIZE", "8") or 8)


def _idle_ttl() -> float:
    return float(os.environ.get("SWFS_HTTP_POOL_IDLE_S", "15") or 15)


def pooling_enabled() -> bool:
    return (os.environ.get("SWFS_HTTP_POOL", "1") or "1").lower() \
        not in ("0", "false", "off")


def max_per_host() -> int:
    """Warm connections the pool will keep per (scheme, host, port) —
    the bound the pipelined chunk engine (ISSUE 14) clamps its fan-out
    windows to, so one streaming request can never sweep every warm
    connection to a volume server."""
    return max(1, _pool_size())


class HttpPool:
    def __init__(self):
        self._idle: dict[tuple, deque] = {}
        self._open = 0  # idle connections currently pooled
        # witnessed leaf lock (ISSUE 15): guards the idle map only —
        # no request IO ever runs under it
        self._lock = wlock("pool.mu", rank=850)
        self._ctx: ssl.SSLContext | None = None
        self._ctx_key: tuple | None = None

    # -- TLS client context, cached per env fingerprint --------------------

    def _client_ctx(self) -> ssl.SSLContext | None:
        key = (os.environ.get("SWFS_HTTPS", ""),
               os.environ.get("SWFS_HTTPS_CA", ""))
        with self._lock:
            if self._ctx_key == key:
                return self._ctx
        from ..security.tls import load_http_client_context

        ctx = load_http_client_context()
        with self._lock:
            self._ctx, self._ctx_key = ctx, key
        return ctx

    # -- connection lifecycle ----------------------------------------------

    def _new_conn(self, scheme: str, host: str, port: int, timeout: float):
        if scheme == "https":
            ctx = self._client_ctx()
            if ctx is None:  # https:// URL with the gate off: still dial
                ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_CLIENT)
                ctx.check_hostname = False
                ctx.verify_mode = ssl.CERT_NONE
            conn = http.client.HTTPSConnection(host, port, timeout=timeout,
                                               context=ctx)
            # connect eagerly so the counter records COMPLETED
            # handshakes only — a refused dial or a failed handshake
            # (e.g. every attempt during a tls-flap restart window)
            # must not inflate the A/B's amortization numbers
            conn.connect()
            TLS_HANDSHAKES.inc(role="client")
        else:
            conn = http.client.HTTPConnection(host, port, timeout=timeout)
        return conn

    def _checkout(self, key: tuple, timeout: float):
        """-> (conn, from_pool). Reaps expired idle connections."""
        if not pooling_enabled():
            HTTP_POOL_OPS.inc(result="disabled")
            return self._new_conn(*key, timeout), False
        cut = time.monotonic() - _idle_ttl()
        with self._lock:
            dq = self._idle.get(key)
            while dq:
                conn, t = dq.pop()  # LIFO: hottest connection first
                self._open -= 1
                if t < cut:
                    HTTP_POOL_OPS.inc(result="expired")
                    try:
                        conn.close()
                    except OSError:
                        pass
                    continue
                HTTP_POOL_OPS.inc(result="hit")
                HTTP_POOL_OPEN.set(self._open)
                if conn.sock is not None:
                    conn.sock.settimeout(timeout)
                return conn, True
        HTTP_POOL_OPS.inc(result="miss")
        HTTP_POOL_OPEN.set(self._open)
        return self._new_conn(*key, timeout), False

    def _checkin(self, key: tuple, conn) -> None:
        if not pooling_enabled():
            conn.close()
            return
        with self._lock:
            dq = self._idle.setdefault(key, deque())
            if len(dq) >= _pool_size():
                HTTP_POOL_OPS.inc(result="evict")
                try:
                    conn.close()
                except OSError:
                    pass
                return
            dq.append((conn, time.monotonic()))
            self._open += 1
            HTTP_POOL_OPEN.set(self._open)

    def clear(self) -> None:
        """Close every idle connection (tests / env flips)."""
        with self._lock:
            for dq in self._idle.values():
                for conn, _ in dq:
                    try:
                        conn.close()
                    except OSError:
                        pass
                dq.clear()
            self._open = 0
            HTTP_POOL_OPEN.set(0)

    # -- the request -------------------------------------------------------

    def request(self, method: str, url: str, body=None, headers=None,
                timeout: float = 30.0) -> PoolResponse:
        # follow same-method redirects (the native C++ plane 307s
        # whatever it cannot serve to the python admin listener, exactly
        # like the requests-based callers this pool replaced)
        for _ in range(4):
            resp = self._request_once(method, url, body, headers, timeout)
            if resp.status in (301, 302, 307, 308):
                loc = resp.getheader("Location")
                if loc:
                    url = loc
                    continue
            return resp
        return resp

    def _request_once(self, method: str, url: str, body, headers,
                      timeout: float) -> PoolResponse:
        u = urlsplit(url)
        scheme = u.scheme or "http"
        host = u.hostname or "localhost"
        port = u.port or (443 if scheme == "https" else 80)
        key = (scheme, host, port)
        target = (u.path or "/") + (f"?{u.query}" if u.query else "")
        hdrs = dict(headers or {})
        # advertise gzip like requests did (the volume plane serves
        # compressed needles verbatim to gzip-capable clients) and
        # transparently decode below
        hdrs.setdefault("Accept-Encoding", "gzip")
        for attempt in (0, 1):
            if attempt:
                # the retry dials FRESH: with several idle connections
                # to a restarted server, a second checkout could hand
                # back another reaped socket and turn benign server-side
                # reaping into a client-visible error
                conn, pooled = self._new_conn(*key, timeout), False
            else:
                conn, pooled = self._checkout(key, timeout)
            try:
                conn.request(method, target, body=body, headers=hdrs)
                resp = conn.getresponse()
            except ssl.SSLCertVerificationError:
                conn.close()
                raise  # a trust decision — never retried, even off-pool
            except (OSError, http.client.HTTPException) as e:
                conn.close()
                # only connection-DEATH shapes BEFORE the response line
                # qualify as "the server reaped this idle connection":
                # a timeout (or any other failure) on a pooled socket
                # may mean the request was already received and
                # processed — replaying it would double the wait and
                # re-apply the operation
                reaped = isinstance(
                    e, (ConnectionResetError, BrokenPipeError,
                        ConnectionAbortedError,
                        http.client.RemoteDisconnected)
                ) and not isinstance(e, TimeoutError)
                if pooled and attempt == 0 and reaped:
                    HTTP_POOL_OPS.inc(result="stale_retry")
                    continue
                if isinstance(e, OSError):
                    raise
                raise ConnectionError(f"{type(e).__name__}: {e}") from e
            try:
                data = resp.read()
            except (OSError, http.client.HTTPException) as e:
                # the status line arrived, so the server definitely
                # processed the request — a mid-body failure must
                # surface, never replay
                conn.close()
                if isinstance(e, OSError):
                    raise
                raise ConnectionError(f"{type(e).__name__}: {e}") from e
            if resp.will_close:
                conn.close()
            else:
                self._checkin(key, conn)
            if (resp.headers.get("Content-Encoding") or "").lower() \
                    == "gzip" and data:
                import gzip as _gz

                data = _gz.decompress(data)  # requests-compatible
            return PoolResponse(resp.status, resp.headers, data)
        raise ConnectionError(f"{method} {url}: retry loop exhausted")

    def get(self, url: str, headers=None, timeout: float = 30.0):
        return self.request("GET", url, headers=headers, timeout=timeout)

    def put(self, url: str, body=b"", headers=None, timeout: float = 30.0):
        return self.request("PUT", url, body=body, headers=headers,
                            timeout=timeout)

    def delete(self, url: str, headers=None, timeout: float = 30.0):
        return self.request("DELETE", url, headers=headers,
                            timeout=timeout)


#: Process-wide pool: every internal data leg shares connection economics
#: (and the metrics tell one coherent story per process).
POOL = HttpPool()

request = POOL.request
get = POOL.get
put = POOL.put
delete = POOL.delete
