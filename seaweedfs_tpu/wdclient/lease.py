"""Batched fid leasing: amortize master Assign RPCs over many PUTs.

The reference's `Assign` already supports count=N (assign_file_id.go:37):
the master reserves N consecutive needle ids on one volume and clients
address them as "fid", "fid_1", ... "fid_<N-1>" (ParsePath's "_delta"
suffix, needle.go:117-142). This pool turns that into a client-side
lease: one Assign RPC stocks a block of N fids per
(collection, replication, ttl, data_center) key, and the small-file
write path mints fids locally until the block drains — N PUTs cost ~1
master round-trip instead of N.

Safety rails:

- blocks expire after `max_age` seconds, so a volume that went
  read-only/moved after the lease can only absorb a bounded burst of
  failed writes before the pool re-asks the (possibly new) master;
- `invalidate()` drops every block immediately — callers invoke it when
  an upload to a leased location fails, and master failover inside
  `operation.assign` (PR 1's rotation/redirect plumbing) supplies the
  replacement lease from whoever leads now;
- a block carrying a write JWT (`auth`) is never batched: the master
  signs the BASE fid only, so "_delta" fids would fail JWT verification
  at the volume server. Those assigns degrade to count=1 transparently.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from dataclasses import replace

from ..operation import AssignResult, assign
from ..utils import trace
from ..utils.stats import CLIENT_FID_LEASE_COUNTER

DEFAULT_BATCH = 128
DEFAULT_MAX_AGE = 10.0  # seconds a leased block may serve fids


class _Block:
    __slots__ = ("base", "count", "next", "expires_at")

    def __init__(self, base: AssignResult, count: int, expires_at: float):
        self.base = base
        self.count = count
        self.next = 0
        self.expires_at = expires_at

    def take(self) -> AssignResult:
        delta = self.next
        self.next += 1
        if delta == 0:
            return self.base
        return replace(self.base, fid=f"{self.base.fid}_{delta}", count=1)


class FidLeasePool:
    """Thread-safe per-(collection, replication, ttl, dc) fid lease pool."""

    def __init__(self, master: str, *, batch: int = DEFAULT_BATCH,
                 max_age: float = DEFAULT_MAX_AGE):
        self.master = master
        self.batch = max(1, int(batch))
        self.max_age = max_age
        self._lock = threading.Lock()
        self._blocks: dict[tuple, deque[_Block]] = {}
        # single-flight refill (ISSUE 14): the overlapped PUT window
        # means W writer threads can drain a key together; only ONE
        # should pay (and reserve) a batched Assign while the others
        # wait for its block instead of each minting their own
        self._refills: dict[tuple, threading.Event] = {}
        # per-key invalidation generation: a refill Assign runs OUTSIDE
        # the lock, so a block obtained before an invalidate() must not
        # be stocked after it (it likely points at the very volume whose
        # failure triggered the invalidation)
        self._gens: dict[tuple, int] = {}
        # keys whose assigns came back JWT-signed: batching is useless
        # there (the token covers the base fid only), so later assigns
        # for these keys request count=1 instead of reserving and then
        # wasting batch-1 needle ids per PUT
        self._jwt_keys: set[tuple] = set()

    def _take_pooled(self, key: tuple) -> AssignResult | None:
        """One fid from the key's live blocks, or None when dry."""
        now = time.monotonic()
        with self._lock:
            blocks = self._blocks.get(key)
            while blocks:
                b = blocks[0]
                if b.next >= b.count:
                    blocks.popleft()
                    continue
                if b.expires_at <= now:
                    CLIENT_FID_LEASE_COUNTER.inc(result="expired")
                    blocks.popleft()
                    continue
                CLIENT_FID_LEASE_COUNTER.inc(result="hit")
                sp = trace.current()
                if sp is not None:
                    # a lease hit is the absence of a master RPC — worth
                    # an attribute, not a span of its own
                    sp.set_attr(fidLease="hit")
                return b.take()
        return None

    def acquire(self, *, collection: str = "", replication: str = "",
                ttl: str = "", data_center: str = "") -> AssignResult:
        """-> one leased fid (AssignResult with fid/url/auth), or an
        AssignResult carrying `.error` when every master refused."""
        key = (collection, replication, ttl, data_center)
        a = self._take_pooled(key)
        if a is not None:
            return a
        # pool dry for this key: one batched Assign restocks it. The RPC
        # runs outside the lock — a slow master must not stall every
        # writer thread. Refills are SINGLE-FLIGHT per key (ISSUE 14):
        # the overlapped PUT window drains a key with W threads at once,
        # and W concurrent Assigns would reserve (and then mostly waste)
        # W whole blocks of needle ids. Followers wait for the leader's
        # block; if the leader failed or its block was consumed, they
        # fall through to their own Assign (correctness never depends
        # on the leader).
        with self._lock:
            ev = self._refills.get(key)
            leader = ev is None
            if leader:
                ev = self._refills[key] = threading.Event()
        if not leader:
            ev.wait(timeout=15.0)
            a = self._take_pooled(key)
            if a is not None:
                return a
        try:
            with self._lock:
                count = 1 if key in self._jwt_keys else self.batch
                gen = self._gens.get(key, 0)
            with trace.span("wdclient.lease.refill", child_only=True,
                            count=count):
                a = assign(self.master, count=count, collection=collection,
                           replication=replication, ttl=ttl,
                           data_center=data_center)
            if a.error:
                return a
            CLIENT_FID_LEASE_COUNTER.inc(result="refill")
            granted = max(1, int(a.count or 1))
            if a.auth:
                # JWT is bound to the base fid; "_delta" fids would 401 —
                # remember so the NEXT assign doesn't reserve (and waste)
                # a whole block of needle ids it can never hand out
                with self._lock:
                    self._jwt_keys.add(key)
                return a
            block = _Block(a, granted, time.monotonic() + self.max_age)
            first = block.take()
            if block.next < block.count:
                with self._lock:
                    if self._gens.get(key, 0) == gen:
                        self._blocks.setdefault(key, deque()).append(block)
                    # else: invalidate() ran while our Assign was in
                    # flight — this block targets a suspect volume; hand
                    # out only the first fid (its upload failing is what
                    # retries are for) and let the next acquire re-ask
                    # the master
            return first
        finally:
            if leader:
                with self._lock:
                    self._refills.pop(key, None)
                ev.set()

    def invalidate(self, *, collection: str = "", replication: str = "",
                   ttl: str = "", data_center: str = "",
                   all_keys: bool = False) -> None:
        """Drop the named key's leased blocks — or every block with
        all_keys=True (master failover: every lease is suspect). An
        upload failure on ONE collection's leased volume must not also
        destroy the healthy batching of every other key."""
        key = (collection, replication, ttl, data_center)
        with self._lock:
            if all_keys:
                if self._blocks:
                    CLIENT_FID_LEASE_COUNTER.inc(result="invalidate")
                for k in set(self._blocks) | {key}:
                    self._gens[k] = self._gens.get(k, 0) + 1
                self._blocks.clear()
            else:
                self._gens[key] = self._gens.get(key, 0) + 1
                if self._blocks.pop(key, None):
                    CLIENT_FID_LEASE_COUNTER.inc(result="invalidate")

    def remaining(self) -> int:
        now = time.monotonic()
        with self._lock:
            return sum(b.count - b.next
                       for blocks in self._blocks.values()
                       for b in blocks
                       if b.expires_at > now)
