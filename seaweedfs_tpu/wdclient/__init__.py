"""Cluster client: master connection + cached volume-location map.

Rebuild of /root/reference/weed/wdclient/ — `MasterClient` keeps a vidMap
cache of volume id -> locations (vid_map.go:72, masterclient.go:44's
5-generation cache becomes a single TTL'd dict; the generations existed to
bound Go map churn) and `LookupFileIdWithFallback` (masterclient.go:59).

Fault handling (utils/retry.py): every master RPC fails over across the
configured master list on UNAVAILABLE/DEADLINE_EXCEEDED (the responder
becomes the new leader hint — masterclient.go's tryAllMasters), stale
vid-cache entries are invalidated on lookup misses, and
`ec_fallback_urls` surfaces EC-shard holders as last-resort read
targets when every plain replica of a volume is gone.
"""

from __future__ import annotations

import random
import threading
import time

import grpc

from ..pb import master_pb2, rpc
from ..storage.file_id import parse_file_id
from ..utils import glog, trace
from ..utils.http import url_for
from ..utils.retry import multi_retry


class Location:
    __slots__ = ("url", "public_url", "grpc_port", "data_center")

    def __init__(self, url: str, public_url: str = "", grpc_port: int = 0,
                 data_center: str = ""):
        self.url = url
        self.public_url = public_url or url
        self.grpc_port = grpc_port
        self.data_center = data_center

    @property
    def grpc_address(self) -> str:
        host = self.url.rsplit(":", 1)[0]
        return f"{host}:{self.grpc_port}" if self.grpc_port else rpc.grpc_address(self.url)


class MasterClient:
    """vid -> [Location] cache with master lookup fallback."""

    def __init__(self, masters: list[str] | str, *, cache_ttl: float = 10 * 60):
        if isinstance(masters, str):
            masters = [m for m in masters.split(",") if m]
        self.masters = masters
        self.cache_ttl = cache_ttl
        self._vid_cache: dict[int, tuple[float, list[Location]]] = {}
        self._ec_vid_cache: dict[int, tuple[float, dict[int, list[Location]]]] = {}
        self._lock = threading.Lock()
        self._leader = masters[0] if masters else ""

    @property
    def current_master(self) -> str:
        return self._leader

    def _stub(self):
        return rpc.master_stub(rpc.grpc_address(self._leader))

    # -- leader failover ---------------------------------------------------

    def _with_master(self, op: str, fn):
        """Run fn(stub) against the current leader, failing over across
        the configured masters on transient gRPC errors (UNAVAILABLE /
        DEADLINE_EXCEEDED). Whichever master answers becomes the new
        leader hint, so subsequent calls go straight there."""
        candidates = [self._leader] + [m for m in self.masters
                                       if m != self._leader]

        def attempt(master):
            out = fn(rpc.master_stub(rpc.grpc_address(master)))
            if master != self._leader:
                glog.v(1, f"master failover: {op} answered by {master}")
                self._leader = master
            return out

        return multi_retry(f"master.{op}", candidates, attempt, cycles=2)

    def resolve_leader(self) -> str:
        """Ask any reachable master who leads (RaftListClusterServers;
        single-master clusters lead themselves) and repoint at it."""
        def ask(stub):
            resp = stub.RaftListClusterServers(
                master_pb2.RaftListClusterServersRequest(), timeout=10)
            for s in resp.cluster_servers:
                if s.isLeader:
                    return s.address
            return ""

        leader = self._with_master("resolve_leader", ask)
        if leader and leader != self._leader:
            self._leader = leader
            if leader not in self.masters:
                self.masters.append(leader)
        return self._leader

    # -- volume lookup -----------------------------------------------------

    def add_location(self, vid: int, loc: Location) -> None:
        with self._lock:
            exp, locs = self._vid_cache.get(vid, (0, []))
            if all(l.url != loc.url for l in locs):
                locs.append(loc)
            self._vid_cache[vid] = (time.time() + self.cache_ttl, locs)

    def delete_location(self, vid: int, url: str) -> None:
        with self._lock:
            entry = self._vid_cache.get(vid)
            if not entry:
                return
            exp, locs = entry
            locs = [l for l in locs if l.url != url]
            if locs:
                self._vid_cache[vid] = (exp, locs)
            else:
                del self._vid_cache[vid]

    def invalidate(self, vid: int) -> None:
        """Drop cached locations for a volume — called when every cached
        replica failed a read, so the next lookup re-asks the master
        instead of replaying a stale map."""
        with self._lock:
            self._vid_cache.pop(vid, None)
            self._ec_vid_cache.pop(vid, None)

    def lookup_volume(self, vid: int, *, refresh: bool = False
                      ) -> list[Location]:
        now = time.time()
        if not refresh:
            with self._lock:
                entry = self._vid_cache.get(vid)
                if entry and entry[0] > now and entry[1]:
                    return list(entry[1])
        # the cache miss is the attributable part: inside a request
        # span the master round-trip becomes a `wdclient.lookup` child
        # (hits return above without a span — they cost nothing)
        with trace.span("wdclient.lookup", child_only=True, vid=vid,
                        refresh=refresh):
            resp = self._with_master(
                "LookupVolume", lambda stub: stub.LookupVolume(
                    master_pb2.LookupVolumeRequest(
                        volume_or_file_ids=[str(vid)]),
                    timeout=10))
        locs = []
        for e in resp.volume_id_locations:
            if e.error:
                self.invalidate(vid)  # a miss means the cache lied too
                raise LookupError(e.error)
            locs = [Location(l.url, l.public_url, l.grpc_port, l.data_center)
                    for l in e.locations]
        with self._lock:
            self._vid_cache[vid] = (now + self.cache_ttl, locs)
        return locs

    def lookup_file_id(self, fid: str, *, refresh: bool = False) -> list[str]:
        """-> http URLs serving this fid (LookupFileIdWithFallback)."""
        f = parse_file_id(fid)
        locs = self.lookup_volume(f.volume_id, refresh=refresh)
        if not locs:
            self.invalidate(f.volume_id)
            raise LookupError(f"volume {f.volume_id} has no locations")
        random.shuffle(locs)
        return [url_for(l.url, fid) for l in locs]

    def ec_fallback_urls(self, fid: str) -> list[str]:
        """Last-resort read targets: HTTP URLs of servers holding ANY EC
        shard of this fid's volume — each can serve the needle by
        reconstructing from any k shards (store_ec.go recover path).
        Empty when the volume was never EC-encoded."""
        f = parse_file_id(fid)
        try:
            shard_locs = self.lookup_ec_volume(f.volume_id)
        except (grpc.RpcError, ConnectionError, TimeoutError):
            return []  # not EC-encoded (NOT_FOUND) or masters unreachable
        servers: list[str] = []
        for locs in shard_locs.values():
            for l in locs:
                if l.url not in servers:
                    servers.append(l.url)
        random.shuffle(servers)
        return [url_for(url, fid) for url in servers]

    def lookup_ec_volume(self, vid: int) -> dict[int, list[Location]]:
        now = time.time()
        with self._lock:
            entry = self._ec_vid_cache.get(vid)
            if entry and entry[0] > now:
                return dict(entry[1])
        with trace.span("wdclient.lookup_ec", child_only=True, vid=vid):
            resp = self._with_master(
                "LookupEcVolume", lambda stub: stub.LookupEcVolume(
                    master_pb2.LookupEcVolumeRequest(volume_id=vid),
                    timeout=10))
        out = {
            sl.shard_id: [Location(l.url, l.public_url, l.grpc_port)
                          for l in sl.locations]
            for sl in resp.shard_id_locations
        }
        with self._lock:
            self._ec_vid_cache[vid] = (now + self.cache_ttl, out)
        return out

    # -- keep-connected stream (masterclient.go KeepConnected) -------------

    def keep_connected(self, client_type: str = "client",
                       on_update=None, stop_event: threading.Event | None = None,
                       client_address: str = "self", filer_group: str = ""):
        """Blocking stream consumer: applies VolumeLocation updates to the
        cache; reconnects on error until stop_event is set. Filers/brokers
        pass their address + filer_group so the master registers them in
        its cluster membership (weed/cluster)."""
        stop = stop_event or threading.Event()
        current_call = [None]  # the live stream, cancelled when stop fires

        def canceller():
            stop.wait()
            call = current_call[0]
            if call is not None:
                try:
                    call.cancel()
                except Exception:
                    pass

        threading.Thread(target=canceller, daemon=True).start()
        while not stop.is_set():
            try:
                stub = self._stub()

                def reqs():
                    yield master_pb2.KeepConnectedRequest(
                        client_type=client_type,
                        client_address=client_address,
                        filer_group=filer_group)
                    while not stop.is_set():
                        time.sleep(1)
                    return

                call = stub.KeepConnected(reqs())
                current_call[0] = call
                if stop.is_set():
                    call.cancel()
                for resp in call:
                    vl = resp.volume_location
                    if vl.url:
                        if vl.leader:
                            self._leader = vl.leader
                        loc = Location(vl.url, vl.public_url, vl.grpc_port,
                                       vl.data_center)
                        for vid in vl.new_vids:
                            self.add_location(vid, loc)
                        for vid in vl.deleted_vids:
                            self.delete_location(vid, vl.url)
                    if on_update is not None:
                        on_update(resp)
                    if stop.is_set():
                        break
            except grpc.RpcError:
                # rotate to the next configured master before redialing —
                # a dead leader must not pin the stream-reconnect loop
                if len(self.masters) > 1:
                    if self._leader in self.masters:
                        i = self.masters.index(self._leader)
                        self._leader = self.masters[
                            (i + 1) % len(self.masters)]
                    else:
                        self._leader = self.masters[0]
                if stop.wait(1.0):
                    break


# -- metadata ring client (ISSUE 19) ---------------------------------------

def _ring_ttl() -> float:
    """Client-side ring cache TTL in seconds (SWFS_META_RING_TTL,
    default 10). The TTL only bounds staleness BETWEEN invalidations —
    a 410 wrong-shard answer refreshes immediately."""
    import os

    try:
        return max(0.5, float(os.environ.get("SWFS_META_RING_TTL", "10")))
    except ValueError:
        return 10.0


class MetaRingClient:
    """TTL'd cache of the master-published metadata ring.

    The vid-cache invalidation ladder (PR 1) applied to namespace
    routing: route from the cached ring; when a shard answers 410 +
    its current epoch, drop the cache if that epoch is newer, refetch,
    and retry ONCE. Fetches go to the master when one is configured,
    else to a seed filer's GetMetaRing proxy — any shard serves the
    ring it routes under, so gateways never need a master address."""

    def __init__(self, *, master: MasterClient | None = None,
                 filer_grpc: str = "", ttl: float | None = None):
        self.master = master
        self.filer_grpc = filer_grpc
        self.ttl = _ring_ttl() if ttl is None else ttl
        self._ring = None
        self._expires = 0.0
        self._lock = threading.Lock()

    def _fetch(self, trigger: str):
        from ..cluster.metaring import MetaRing
        from ..pb import meta_ring_pb2
        from ..utils.stats import META_RING_EPOCH, META_RING_FETCHES

        req = meta_ring_pb2.GetMetaRingRequest()
        try:
            if self.master is not None:
                resp = self.master._with_master(
                    "GetMetaRing",
                    lambda stub: stub.GetMetaRing(req, timeout=10))
            else:
                resp = rpc.filer_stub(self.filer_grpc).GetMetaRing(
                    req, timeout=10)
        except grpc.RpcError:
            META_RING_FETCHES.inc(trigger=trigger, result="error")
            raise
        META_RING_FETCHES.inc(trigger=trigger, result="ok")
        ring = MetaRing.from_response(resp)
        META_RING_EPOCH.set(ring.epoch)
        return ring

    def ring(self, *, refresh: bool = False, trigger: str = "ttl"):
        """Current ring snapshot (cached). grpc.RpcError propagates when
        the fetch target is down AND no cached picture exists — callers
        holding a stale ring keep routing on it rather than failing."""
        now = time.time()
        with self._lock:
            if not refresh and self._ring is not None \
                    and self._expires > now:
                return self._ring
        try:
            ring = self._fetch(trigger)
        except grpc.RpcError:
            with self._lock:
                if self._ring is not None:
                    return self._ring  # stale beats unreachable
            raise
        with self._lock:
            # an epoch can only move forward; a lagging answer (e.g. a
            # follower proxy) must not roll the cache back
            if self._ring is None or ring.epoch >= self._ring.epoch:
                self._ring = ring
            self._expires = time.time() + self.ttl
            return self._ring

    def note_epoch(self, epoch: int) -> bool:
        """Feed an epoch observed on a 410 answer; drops the cache when
        it proves the cached ring stale. -> True when invalidated."""
        with self._lock:
            if self._ring is not None and epoch > self._ring.epoch:
                self._expires = 0.0
                return True
        return False

    # -- routing -----------------------------------------------------------

    def route_entry(self, full_path: str, default: str = "") -> str:
        """HTTP address of the shard owning an entry (hashes the parent
        directory); `default` on an empty/unfetchable ring."""
        try:
            ring = self.ring()
        except grpc.RpcError:
            return default
        return ring.shard_for_entry(full_path) or default

    def route_directory(self, directory: str, default: str = "") -> str:
        """HTTP address of the shard owning a directory listing."""
        try:
            ring = self.ring()
        except grpc.RpcError:
            return default
        return ring.shard_for_directory(directory) or default

    def call_routed(self, key: str, fn, *, directory: bool = False,
                    default: str = ""):
        """Run fn(shard_http_address) with the one stale-ring retry:
        a WrongShardError feeds its epoch back, forces a refresh and
        re-routes exactly once — converged clients never loop."""
        from ..cluster.metaring import WrongShardError

        route = (self.route_directory if directory else self.route_entry)
        try:
            return fn(route(key, default))
        except WrongShardError as e:
            self.note_epoch(e.epoch)
            try:
                ring = self.ring(refresh=True, trigger="stale")
            except grpc.RpcError:
                raise e from None
            owner = (ring.shard_for_directory(key) if directory
                     else ring.shard_for_entry(key))
            return fn(owner or default)
