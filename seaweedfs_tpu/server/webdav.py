"""WebDAV gateway over the filer.

Rebuild of /root/reference/weed/server/webdav_server.go (which wraps
golang.org/x/net/webdav around a filer-backed FileSystem). Here the DAV
wire protocol is implemented directly: PROPFIND/MKCOL/COPY/MOVE against
the filer gRPC API, GET/PUT/DELETE proxied through the filer HTTP data
plane (which already chunks bodies). LOCK/UNLOCK are backed by a real
in-memory lock table with expiry/refresh/If-token enforcement
(LockManager below) — the analogue of x/net/webdav's memLS that the
reference inherits.
"""

from __future__ import annotations

import re
import threading
import time
import urllib.parse
import xml.etree.ElementTree as ET
from dataclasses import dataclass, field
from http.server import BaseHTTPRequestHandler

from ..utils.httpd import TunedThreadingHTTPServer

import requests

from ..utils.http import requests_verify

from ..cluster.metaring import WRONG_SHARD_STATUS, wrong_shard_of
from ..pb import filer_pb2, rpc
from ..utils import glog

DAV_NS = "DAV:"


def _dav(tag: str) -> str:
    return f"{{{DAV_NS}}}{tag}"


DEFAULT_LOCK_SECONDS = 600.0
MAX_LOCK_SECONDS = 3600.0
_TOKEN_RE = re.compile(r"<(opaquelocktoken:[^>]+)>")


@dataclass
class DavLock:
    token: str
    path: str  # normalized filer path, no trailing slash
    depth_infinity: bool
    owner_xml: str
    timeout_s: float
    expires_at: float = field(default=0.0)

    def refresh(self, timeout_s: float | None = None) -> None:
        if timeout_s is not None:
            self.timeout_s = timeout_s
        self.expires_at = time.monotonic() + self.timeout_s


class LockManager:
    """Exclusive write locks keyed by normalized path — the memLS
    semantics the reference gets from golang.org/x/net/webdav
    (webdav_server.go wires webdav.NewMemLS()): create/refresh with
    Timeout, lazy expiry, conflict via ancestors (depth-infinity locks
    cover their subtree) and descendants, token confirmation from the
    If header. Shared locks are granted but enforced exclusively —
    documented deviation, same practical protection."""

    def __init__(self) -> None:
        self._locks: dict[str, DavLock] = {}
        self._mu = threading.Lock()

    # -- internals (call with _mu held) ------------------------------------

    def _purge(self) -> None:
        now = time.monotonic()
        for p in [p for p, l in self._locks.items() if l.expires_at <= now]:
            del self._locks[p]

    def _covering(self, path: str) -> DavLock | None:
        """The lock protecting `path`: on itself, or a depth-infinity
        lock on any ancestor."""
        l = self._locks.get(path)
        if l is not None:
            return l
        parent = path.rsplit("/", 1)[0]
        while parent:
            l = self._locks.get(parent)
            if l is not None and l.depth_infinity:
                return l
            parent = parent.rsplit("/", 1)[0]
        l = self._locks.get("/")
        return l if l is not None and l.depth_infinity else None

    def _descendant_locked(self, path: str) -> DavLock | None:
        prefix = path.rstrip("/") + "/"
        for p, l in self._locks.items():
            if p.startswith(prefix):
                return l
        return None

    # -- surface -----------------------------------------------------------

    def lock(self, path: str, owner_xml: str, depth_infinity: bool,
             timeout_s: float) -> DavLock | None:
        """-> new lock, or None on conflict (423)."""
        path = path.rstrip("/") or "/"
        with self._mu:
            self._purge()
            if self._covering(path) is not None:
                return None
            if depth_infinity and self._descendant_locked(path) is not None:
                return None
            import uuid

            l = DavLock(
                token=f"opaquelocktoken:{uuid.uuid4()}",
                path=path, depth_infinity=depth_infinity,
                owner_xml=owner_xml, timeout_s=timeout_s)
            l.refresh()
            self._locks[path] = l
            return l

    def refresh(self, path: str, tokens: list[str],
                timeout_s: float | None) -> DavLock | None:
        """LOCK with no body + If token refreshes (RFC 4918 §7.8)."""
        path = path.rstrip("/") or "/"
        with self._mu:
            self._purge()
            l = self._covering(path)
            if l is None or l.token not in tokens:
                return None
            l.refresh(timeout_s)
            return l

    def unlock(self, path: str, token: str) -> bool:
        path = path.rstrip("/") or "/"
        with self._mu:
            self._purge()
            l = self._covering(path)
            if l is None or l.token != token:
                return False
            del self._locks[l.path]
            return True

    def can_modify(self, path: str, tokens: list[str]) -> bool:
        """True when `path` is unlocked or the caller submitted the
        covering lock's token (write-op gate, RFC 4918 §6.4)."""
        path = path.rstrip("/") or "/"
        with self._mu:
            self._purge()
            l = self._covering(path)
            return l is None or l.token in tokens

    def can_modify_recursive(self, path: str, tokens: list[str]) -> bool:
        """can_modify + every lock held INSIDE the subtree must also be
        submitted — DELETE/MOVE of a collection affects all members
        (RFC 4918 §9.6.1: 423 when any member is locked)."""
        path = path.rstrip("/") or "/"
        with self._mu:
            self._purge()
            l = self._covering(path)
            if l is not None and l.token not in tokens:
                return False
            prefix = path.rstrip("/") + "/"
            return all(l.token in tokens
                       for p, l in self._locks.items()
                       if p.startswith(prefix))

    def release_subtree(self, path: str) -> None:
        """Drop every lock on `path` or beneath it — the resources are
        gone (successful DELETE / MOVE source, RFC 4918 §9.6.1). Callers
        authorize via can_modify_recursive first."""
        path = path.rstrip("/") or "/"
        prefix = path + "/"
        with self._mu:
            for p in [p for p in self._locks
                      if p == path or p.startswith(prefix)]:
                del self._locks[p]

    def discover(self, path: str) -> DavLock | None:
        with self._mu:
            self._purge()
            return self._covering(path.rstrip("/") or "/")


def _parse_timeout_header(value: str | None) -> float:
    """"Second-600" / "Infinite" / comma list -> clamped seconds."""
    if not value:
        return DEFAULT_LOCK_SECONDS
    for part in value.split(","):
        part = part.strip()
        if part.lower().startswith("second-"):
            try:
                return min(float(part[7:]), MAX_LOCK_SECONDS)
            except ValueError:
                continue
        if part.lower() == "infinite":
            return MAX_LOCK_SECONDS
    return DEFAULT_LOCK_SECONDS


def _if_tokens(headers) -> list[str]:
    """All lock tokens submitted in If / Lock-Token headers. The full
    RFC 4918 If grammar (tagged lists, etag conditions, Not) collapses
    here to token extraction — enough to enforce ownership."""
    out = []
    for name in ("If", "Lock-Token"):
        v = headers.get(name)
        if v:
            out.extend(_TOKEN_RE.findall(v))
    return out


def _lockdiscovery_xml(l: DavLock) -> bytes:
    prop = ET.Element(_dav("prop"))
    ld = ET.SubElement(prop, _dav("lockdiscovery"))
    al = ET.SubElement(ld, _dav("activelock"))
    ET.SubElement(ET.SubElement(al, _dav("locktype")), _dav("write"))
    ET.SubElement(ET.SubElement(al, _dav("lockscope")), _dav("exclusive"))
    ET.SubElement(al, _dav("depth")).text = (
        "infinity" if l.depth_infinity else "0")
    if l.owner_xml:
        ET.SubElement(al, _dav("owner")).text = l.owner_xml
    ET.SubElement(al, _dav("timeout")).text = f"Second-{int(l.timeout_s)}"
    lt = ET.SubElement(al, _dav("locktoken"))
    ET.SubElement(lt, _dav("href")).text = l.token
    ET.SubElement(ET.SubElement(al, _dav("lockroot")),
                  _dav("href")).text = l.path
    return ET.tostring(prop, xml_declaration=True, encoding="utf-8")


class WebDavServer:
    def __init__(self, *, port: int = 7333, filer: str = "localhost:8888",
                 base_dir: str = "/"):
        self.port = port
        self.filer = filer
        self.base_dir = base_dir.rstrip("/") or ""
        self.locks = LockManager()
        # metadata ring (ISSUE 19): route every filer op to the shard
        # owning the path; 1-entry ring = the seed filer, unchanged
        from ..wdclient import MetaRingClient

        self.ring_client = MetaRingClient(
            filer_grpc=rpc.grpc_address(filer))
        self._httpd: TunedThreadingHTTPServer | None = None
        self._thread: threading.Thread | None = None

    @property
    def stub(self):
        return rpc.filer_stub(rpc.grpc_address(self.filer))

    def meta_call(self, path: str, fn, *, directory: bool = False):
        """fn(stub) against the shard owning `path`, one stale-ring
        retry (same ladder as the S3 gateway's meta_call)."""
        import grpc as _grpc

        def leg(addr):
            stub = (self.stub if not addr or addr == self.filer
                    else rpc.filer_stub(rpc.grpc_address(addr)))
            try:
                return fn(stub)
            except _grpc.RpcError as e:
                ws = wrong_shard_of(e)
                if ws is not None:
                    raise ws from e
                raise

        return self.ring_client.call_routed(
            path, leg, directory=directory, default=self.filer)

    def start(self) -> None:
        from ..security.tls import load_http_server_context

        handler = _make_handler(self)
        self._httpd = TunedThreadingHTTPServer(
            ("0.0.0.0", self.port), handler,
            ssl_context=load_http_server_context("webdav"))
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        daemon=True)
        self._thread.start()
        glog.info(f"webdav server started on :{self.port} -> filer "
                  f"{self.filer}{self.base_dir or '/'}")

    def stop(self) -> None:
        if self._httpd:
            self._httpd.shutdown()
            self._httpd.server_close()

    # -- filer helpers -----------------------------------------------------

    def full_path(self, dav_path: str) -> str:
        p = urllib.parse.unquote(dav_path.split("?", 1)[0])
        return (self.base_dir + "/" + p.strip("/")).rstrip("/") or "/"

    def find(self, path: str) -> filer_pb2.Entry | None:
        if path == "/":
            return filer_pb2.Entry(name="", is_directory=True)
        directory, name = path.rsplit("/", 1)
        try:
            resp = self.meta_call(
                path,
                lambda stub: stub.LookupDirectoryEntry(
                    filer_pb2.LookupDirectoryEntryRequest(
                        directory=directory or "/", name=name), timeout=30))
        # lint: allow-broad-except(WebDAV lookup maps any filer failure
        # to not-found; PROPFIND callers answer 404, never 500)
        except Exception:
            return None
        if not resp.entry.name:
            return None
        return resp.entry

    def list_dir(self, path: str) -> list[filer_pb2.Entry]:
        def listing(stub):
            return [filer_pb2.Entry.FromString(
                        resp.entry.SerializeToString())
                    for resp in stub.ListEntries(filer_pb2.ListEntriesRequest(
                        directory=path, limit=1 << 20))]

        return self.meta_call(path, listing, directory=True)

    def filer_url(self, path: str, refresh: bool = False) -> str:
        from ..utils.http import url_for

        if refresh:
            self.ring_client.ring(refresh=True, trigger="stale")
        shard = self.ring_client.route_entry(path, self.filer)
        return url_for(shard, urllib.parse.quote(path))

    def note_stale_ring(self, resp) -> None:
        """Absorb the epoch from a 410 wrong-shard HTTP answer."""
        from ..cluster.metaring import EPOCH_HEADER

        try:
            self.ring_client.note_epoch(
                int(resp.headers.get(EPOCH_HEADER, "0")))
        except (TypeError, ValueError):
            pass


def _prop_response(href: str, entry: filer_pb2.Entry) -> ET.Element:
    resp = ET.Element(_dav("response"))
    ET.SubElement(resp, _dav("href")).text = href
    propstat = ET.SubElement(resp, _dav("propstat"))
    prop = ET.SubElement(propstat, _dav("prop"))
    rtype = ET.SubElement(prop, _dav("resourcetype"))
    if entry.is_directory:
        ET.SubElement(rtype, _dav("collection"))
    else:
        size = entry.attributes.file_size
        ET.SubElement(prop, _dav("getcontentlength")).text = str(size)
        if entry.attributes.mime:
            ET.SubElement(prop, _dav("getcontenttype")).text = \
                entry.attributes.mime
    mtime = entry.attributes.mtime or int(time.time())
    ET.SubElement(prop, _dav("getlastmodified")).text = time.strftime(
        "%a, %d %b %Y %H:%M:%S GMT", time.gmtime(mtime))
    ET.SubElement(prop, _dav("displayname")).text = entry.name
    ET.SubElement(propstat, _dav("status")).text = "HTTP/1.1 200 OK"
    return resp


def _make_handler(srv: WebDavServer):
    class Handler(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"
        server_version = "seaweedfs-tpu-webdav"

        def log_message(self, fmt, *args):
            glog.v(2, f"webdav {fmt % args}")

        def _send(self, status: int, body: bytes = b"",
                  content_type: str = "text/xml; charset=utf-8",
                  headers: dict | None = None):
            self.send_response(status)
            self.send_header("Content-Length", str(len(body)))
            if body:
                self.send_header("Content-Type", content_type)
            for k, v in (headers or {}).items():
                self.send_header(k, v)
            self.end_headers()
            if body:
                self.wfile.write(body)

        def _read_body(self) -> bytes:
            if "chunked" in (self.headers.get("Transfer-Encoding") or ""):
                out = bytearray()
                while True:
                    line = self.rfile.readline().strip()
                    size = int(line.split(b";")[0] or b"0", 16)
                    if size == 0:
                        self.rfile.readline()  # trailing CRLF
                        break
                    out += self.rfile.read(size)
                    self.rfile.readline()  # chunk CRLF
                return bytes(out)
            n = int(self.headers.get("Content-Length") or 0)
            return self.rfile.read(n) if n else b""

        def do_OPTIONS(self):
            self._send(200, headers={
                "DAV": "1, 2",
                "Allow": "OPTIONS, GET, HEAD, PUT, DELETE, PROPFIND, "
                         "PROPPATCH, MKCOL, COPY, MOVE, LOCK, UNLOCK",
                "MS-Author-Via": "DAV"})

        def do_PROPFIND(self):
            self._read_body()  # body (prop filters) ignored: return all
            path = srv.full_path(self.path)
            entry = srv.find(path)
            if entry is None:
                return self._send(404)
            depth = self.headers.get("Depth", "1")
            ms = ET.Element(_dav("multistatus"))
            # self.path is already percent-encoded wire form; reuse as-is
            href = self.path.split("?", 1)[0] or "/"
            ms.append(_prop_response(href, entry))
            if entry.is_directory and depth != "0":
                for child in srv.list_dir(path):
                    ch = href.rstrip("/") + "/" + urllib.parse.quote(
                        child.name)
                    ms.append(_prop_response(ch, child))
            body = ET.tostring(ms, xml_declaration=True, encoding="utf-8")
            self._send(207, body)

        def do_PROPPATCH(self):
            if not self._check_lock(srv.full_path(self.path)):
                return
            self._read_body()
            ms = ET.Element(_dav("multistatus"))
            body = ET.tostring(ms, xml_declaration=True, encoding="utf-8")
            self._send(207, body)

        def do_MKCOL(self):
            path = srv.full_path(self.path)
            if not self._check_lock(path):
                return
            if srv.find(path) is not None:
                return self._send(405)
            directory, name = path.rsplit("/", 1)
            entry = filer_pb2.Entry(name=name, is_directory=True)
            entry.attributes.file_mode = 0o40770
            entry.attributes.mtime = int(time.time())
            srv.meta_call(
                path,
                lambda stub: stub.CreateEntry(filer_pb2.CreateEntryRequest(
                    directory=directory or "/", entry=entry), timeout=30))
            self._send(201)

        def do_GET(self):
            path = srv.full_path(self.path)
            entry = srv.find(path)
            if entry is None:
                return self._send(404)
            if entry.is_directory:
                return self._send(405)
            rng = self.headers.get("Range")
            r = requests.get(srv.filer_url(path), timeout=300, stream=True,
                             headers={"Range": rng} if rng else {},
                             verify=requests_verify())
            if r.status_code == WRONG_SHARD_STATUS:
                srv.note_stale_ring(r)
                r.close()
                r = requests.get(srv.filer_url(path, refresh=True),
                                 timeout=300, stream=True,
                                 headers={"Range": rng} if rng else {},
                                 verify=requests_verify())
            if r.status_code >= 300:
                return self._send(r.status_code)
            self.send_response(r.status_code)
            for h in ("Content-Length", "Content-Type", "Content-Range",
                      "ETag", "Last-Modified", "Accept-Ranges"):
                if h in r.headers:
                    self.send_header(h, r.headers[h])
            self.end_headers()
            for piece in r.iter_content(chunk_size=256 * 1024):
                self.wfile.write(piece)

        def do_HEAD(self):
            # served from metadata only — no body transfer
            path = srv.full_path(self.path)
            entry = srv.find(path)
            if entry is None:
                return self._send(404)
            self.send_response(200)
            if not entry.is_directory:
                self.send_header("Content-Length",
                                 str(entry.attributes.file_size))
                if entry.attributes.mime:
                    self.send_header("Content-Type", entry.attributes.mime)
            self.send_header("Accept-Ranges", "bytes")
            self.end_headers()

        def do_PUT(self):
            path = srv.full_path(self.path)
            if not self._check_lock(path):
                return
            body = self._read_body()
            headers = {"Content-Type":
                       self.headers.get("Content-Type") or
                       "application/octet-stream"}
            r = requests.put(srv.filer_url(path), data=body, timeout=300,
                             headers=headers, verify=requests_verify())
            if r.status_code == WRONG_SHARD_STATUS:
                srv.note_stale_ring(r)
                r = requests.put(srv.filer_url(path, refresh=True),
                                 data=body, timeout=300, headers=headers,
                                 verify=requests_verify())
            self._send(201 if r.status_code < 300 else r.status_code)

        def do_DELETE(self):
            path = srv.full_path(self.path)
            if not self._check_lock(path, recursive=True):
                return
            entry = srv.find(path)
            if entry is None:
                return self._send(404)
            directory, name = path.rsplit("/", 1)
            resp = srv.meta_call(
                path,
                lambda stub: stub.DeleteEntry(filer_pb2.DeleteEntryRequest(
                    directory=directory or "/", name=name,
                    is_delete_data=True, is_recursive=True), timeout=60))
            if not resp.error:
                srv.locks.release_subtree(path)  # resources gone (§9.6.1)
            self._send(204 if not resp.error else 409)

        def _dest_path(self) -> str | None:
            dest = self.headers.get("Destination")
            if not dest:
                return None
            u = urllib.parse.urlparse(dest)
            return srv.full_path(u.path)

        def do_MOVE(self):
            import grpc

            src = srv.full_path(self.path)
            dst = self._dest_path()
            if dst is None:
                return self._send(400)
            if (not self._check_lock(src, recursive=True)
                    or not self._check_lock(dst, recursive=True)):
                return
            if srv.find(src) is None:
                return self._send(404)
            od, on = src.rsplit("/", 1)
            nd, nn = dst.rsplit("/", 1)
            try:
                # routed by SOURCE entry: the shard owning the old parent
                # runs the (possibly two-phase cross-shard) rename
                srv.meta_call(
                    src,
                    lambda stub: stub.AtomicRenameEntry(
                        filer_pb2.AtomicRenameEntryRequest(
                            old_directory=od or "/", old_name=on,
                            new_directory=nd or "/", new_name=nn),
                        timeout=60))
            except grpc.RpcError as e:
                code = e.code()
                return self._send(
                    404 if code == grpc.StatusCode.NOT_FOUND else 502)
            srv.locks.release_subtree(src)  # moved away (§9.6.1 analogue)
            self._send(201)

        def do_COPY(self):
            src = srv.full_path(self.path)
            dst = self._dest_path()
            if dst is None:
                return self._send(400)
            if not self._check_lock(dst):  # COPY reads src, writes dst
                return
            entry = srv.find(src)
            if entry is None:
                return self._send(404)
            if entry.is_directory:
                return self._send(501)  # directory COPY: not supported
            r = requests.get(srv.filer_url(src), timeout=300,
                             verify=requests_verify())
            if r.status_code == WRONG_SHARD_STATUS:
                srv.note_stale_ring(r)
                r = requests.get(srv.filer_url(src, refresh=True),
                                 timeout=300, verify=requests_verify())
            if r.status_code >= 300:
                return self._send(502)
            pr = requests.put(srv.filer_url(dst), data=r.content,
                              timeout=300, verify=requests_verify())
            if pr.status_code == WRONG_SHARD_STATUS:
                srv.note_stale_ring(pr)
                pr = requests.put(srv.filer_url(dst, refresh=True),
                                  data=r.content, timeout=300,
                                  verify=requests_verify())
            self._send(201 if pr.status_code < 300 else pr.status_code)

        def _check_lock(self, path: str, recursive: bool = False) -> bool:
            """False (and a 423 response sent) when `path` is locked and
            the request lacks the covering token. recursive=True also
            requires tokens for locks inside the subtree (DELETE/MOVE of
            collections, RFC 4918 §9.6.1)."""
            tokens = _if_tokens(self.headers)
            ok = (srv.locks.can_modify_recursive(path, tokens) if recursive
                  else srv.locks.can_modify(path, tokens))
            if ok:
                return True
            self._send(423)
            return False

        def do_LOCK(self):
            body = self._read_body()
            path = srv.full_path(self.path)
            timeout_s = _parse_timeout_header(self.headers.get("Timeout"))
            if not body:
                # refresh (RFC 4918 §7.8): no body, token in If
                l = srv.locks.refresh(path, _if_tokens(self.headers),
                                      timeout_s)
                if l is None:
                    return self._send(412)
                return self._send(200, _lockdiscovery_xml(l))
            owner = ""
            try:
                root = ET.fromstring(body)
                o = root.find(_dav("owner"))
                if o is not None:
                    owner = "".join(o.itertext()).strip() or \
                        "".join(ET.tostring(c, encoding="unicode")
                                for c in o)
            except ET.ParseError:
                return self._send(400)
            depth_inf = (self.headers.get("Depth", "infinity").lower()
                         != "0")
            l = srv.locks.lock(path, owner, depth_inf, timeout_s)
            if l is None:
                return self._send(423)
            # 201 when LOCK created the (previously absent) resource is
            # not implemented: lock-null resources are obsolete in 4918
            self._send(200, _lockdiscovery_xml(l),
                       headers={"Lock-Token": f"<{l.token}>"})

        def do_UNLOCK(self):
            path = srv.full_path(self.path)
            m = _TOKEN_RE.search(self.headers.get("Lock-Token") or "")
            if not m:
                return self._send(400)
            if not srv.locks.unlock(path, m.group(1)):
                return self._send(409)
            self._send(204)

    return Handler
