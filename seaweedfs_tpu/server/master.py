"""Master server: cluster brain — heartbeat ingest, assignment, EC lookup.

Rebuild of /root/reference/weed/server/master_server.go +
master_grpc_server.go + master_server_handlers.go. Serves:

* gRPC (master_pb.Seaweed): SendHeartbeat bidirectional stream (:61),
  Assign, LookupVolume, LookupEcVolume, VolumeList, Statistics,
  CollectionList/Delete, KeepConnected membership push (:250),
  LeaseAdminToken (shell cluster lock), Ping.
* HTTP on the master port: /dir/assign (master_server_handlers.go:102),
  /dir/lookup, /vol/vacuum, /col/delete, /cluster/status, /dir/status,
  /metrics (Prometheus text).

Single-master deployment is the default; multi-master leadership is a
pluggable hook (is_leader / leader_address) the same way the reference
gates every mutating RPC on `Topo.IsLeader()`.
"""

from __future__ import annotations

import json
import queue
import threading
import time
from http.server import BaseHTTPRequestHandler

from ..utils.httpd import TunedThreadingHTTPServer
from urllib.parse import parse_qs, urlparse

import grpc

from ..cluster import Cluster
from ..pb import master_pb2, rpc
from ..sequence import new_sequencer
from ..storage.file_id import parse_file_id
from ..storage.super_block import ReplicaPlacement
from ..storage.ttl import EMPTY_TTL, TTL
from ..topology import Topology, VolumeGrowth
from ..topology.topology import EcShardInfo, VolumeInfo
from ..utils import glog, locks, trace
from ..utils.stats import (
    MASTER_RECEIVED_HEARTBEATS,
    gather,
    metrics_content_type,
    qos_stats,
    status_base,
)


class MasterServer:
    def __init__(self, *, ip: str = "localhost", port: int = 9333,
                 volume_size_limit_mb: int = 30_000,
                 default_replication: str = "000",
                 pulse_seconds: int = 5,
                 sequencer_type: str = "memory",
                 garbage_threshold: float = 0.3,
                 allocate_fn=None,
                 peers: list[str] | None = None,
                 raft_dir: str | None = None,
                 raft_transport=None,
                 metrics_address: str = "",
                 metrics_interval_sec: int = 15,
                 write_jwt_key: bytes = b"",
                 jwt_expires_sec: int = 10):
        # JWT minting for authorized writes (security/jwt.go:30)
        self.write_jwt_key = write_jwt_key
        self.jwt_expires_sec = jwt_expires_sec
        # push-gateway target broadcast to the fleet at heartbeat
        # (GetMasterConfiguration -> volume servers start pushing)
        self.metrics_address = metrics_address
        self.metrics_interval_sec = metrics_interval_sec
        self.ip = ip
        self.port = port
        self.grpc_port = rpc.derived_grpc_port(port)
        self.default_replication = default_replication
        self.garbage_threshold = garbage_threshold
        # volume.vacuum.disable pauses the periodic driver (the reference's
        # Topology.isDisableVacuum); manual /vol/vacuum still works
        self.vacuum_disabled = False
        # integrity plane (ISSUE 4): periodic fleet-wide scrub driver —
        # each tick asks the least-recently-scrubbed volume server (the
        # topology round-robin hook) to run one self-healing pass
        self.scrub_disabled = False
        self.topo = Topology(
            volume_size_limit=volume_size_limit_mb * 1024 * 1024,
            pulse_seconds=pulse_seconds,
            sequencer=new_sequencer(sequencer_type),
        )
        self.growth = VolumeGrowth(self.topo, allocate_fn=allocate_fn)
        # QoS plane (ISSUE 8): cluster-wide background byte budget leased
        # to volume servers over QosGrant (strict priority: repair >
        # scrub/archival), plus the per-node pressure reports assign
        # placement consults. Unconfigured env = observe-only.
        from ..qos import GrantLedger

        self.qos_ledger = GrantLedger()
        # master-plane locks on the PR-15 witness (ranks 30-70, above
        # the rank-20 run locks, below the volume plane at 300)
        self._grow_lock = locks.wlock("master.grow", rank=30)
        self._admin_locks: dict[str, tuple[int, int, str]] = {}  # name -> (token, ts, client)
        self._admin_lock_mu = locks.wlock("master.admin_locks", rank=60)
        self._keepalive_clients: dict[str, queue.Queue] = {}
        self._keepalive_mu = locks.wlock("master.keepalive", rank=70)
        # fleet-scale metadata plane (ISSUE 19): the master is the ring
        # authority — filer shards join/renew over JoinMetaRing, every
        # membership change bumps the epoch, and clients fetch the
        # published picture via GetMetaRing (direct or proxied by any
        # shard). Empty ring = unpartitioned deployment, nothing routes.
        from ..cluster.metaring import MetaRing

        self.meta_ring = MetaRing([])
        self._meta_ring_mu = locks.wlock("master.meta_ring", rank=50)
        # filer/broker group membership + leader hinting (weed/cluster)
        self.cluster = Cluster()
        self._grpc_server = None
        self._http_server = None
        self._vacuum_thread = None
        self._stop = threading.Event()
        self._started_at = time.time()
        # multi-master: Raft-replicated MaxVolumeId + leader election
        # (raft_server.go / cluster_commands.go)
        self.raft = None
        self._vid_propose_lock = locks.wlock("master.vid_propose", rank=40)
        if peers:
            from ..master.raft import RaftNode

            self.raft = RaftNode(
                self.address, peers, self._raft_apply,
                transport=raft_transport, state_dir=raft_dir,
                snapshot_fn=lambda: {
                    "max_volume_id": self.topo.max_volume_id},
                restore_fn=lambda s: self._raft_apply(
                    {"op": "max_volume_id", "value": s["max_volume_id"]}),
            )
            self.topo.next_volume_id = self._raft_next_volume_id

    # -- leadership --------------------------------------------------------

    def is_leader(self) -> bool:
        return self.raft is None or self.raft.role == "leader"

    def leader_address(self) -> str:
        if self.raft is None or self.raft.leader_id is None:
            return self.address
        return self.raft.leader_id

    def mint_write_jwt(self, fid: str) -> str:
        if not self.write_jwt_key:
            return ""
        from ..security import gen_write_jwt

        return gen_write_jwt(self.write_jwt_key, fid, self.jwt_expires_sec)

    def _raft_apply(self, cmd: dict) -> None:
        if cmd.get("op") == "max_volume_id":
            with self.topo._lock:
                self.topo.max_volume_id = max(self.topo.max_volume_id,
                                              int(cmd["value"]))

    def _raft_next_volume_id(self) -> int:
        """Raft-committed replacement for Topology.next_volume_id
        (MaxVolumeIdCommand, cluster_commands.go)."""
        with self._vid_propose_lock:
            candidate = self.topo.max_volume_id + 1
            self.raft.propose({"op": "max_volume_id", "value": candidate})
            return candidate

    @property
    def address(self) -> str:
        return f"{self.ip}:{self.port}"

    # -- lifecycle ---------------------------------------------------------

    def start(self, *, vacuum_interval: float = 60.0,
              scrub_interval: float | None = None) -> None:
        trace.set_identity("master", self.address)
        self._grpc_server = rpc.new_server()
        creds = rpc.add_servicer(self._grpc_server, rpc.MASTER_SERVICE,
                                 MasterGrpc(self), component="master",
                                 address=self.address)
        rpc.serve_port(self._grpc_server, f"[::]:{self.grpc_port}",
                       "master", creds=creds)
        self._grpc_server.start()
        # HTTPS (ISSUE 9): the master's HTTP plane (assign/lookup/
        # status/debug) rides the same gate as the data planes — one
        # SWFS_HTTPS switch moves the whole fleet, and harness /status
        # probes keep working under --https
        from ..security.tls import load_http_server_context

        self._http_server = TunedThreadingHTTPServer(
            ("", self.port), _make_http_handler(self),
            ssl_context=load_http_server_context("master")
        )
        threading.Thread(target=self._http_server.serve_forever, daemon=True).start()
        self._vacuum_thread = threading.Thread(
            target=self._vacuum_loop, args=(vacuum_interval,), daemon=True
        )
        self._vacuum_thread.start()
        if scrub_interval is None:
            import os as _os

            try:
                scrub_interval = float(_os.environ.get(
                    "SWFS_MASTER_SCRUB_INTERVAL_S", "0"))
            except ValueError:
                scrub_interval = 0.0
        if scrub_interval > 0:
            threading.Thread(target=self._scrub_loop,
                             args=(scrub_interval,), daemon=True).start()
        if self.raft is not None:
            self.raft.start()
        glog.info(f"master started on {self.address} (grpc :{self.grpc_port})")

    def stop(self) -> None:
        self._stop.set()
        if self.raft is not None:
            self.raft.stop()
        if self._http_server:
            self._http_server.shutdown()
        if self._grpc_server:
            self._grpc_server.stop(grace=0.5)

    # -- assignment --------------------------------------------------------

    def assign(self, *, count: int = 1, replication: str = "",
               collection: str = "", ttl: str = "", data_center: str = "",
               rack: str = "", data_node: str = "") -> dict:
        if not self.is_leader():
            return {"error": f"not the leader; ask {self.leader_address()}",
                    "leader": self.leader_address()}
        rp = ReplicaPlacement.parse(replication or self.default_replication)
        t = TTL.parse(ttl) if ttl else EMPTY_TTL
        vl = self.topo.get_layout(collection, rp, t)
        grow_err: Exception | None = None
        if vl.active_count() == 0:
            with self._grow_lock:  # single grower, like vgCh serialization
                if vl.active_count() == 0:
                    try:
                        self.growth.grow(
                            collection, rp, t,
                            count=self.growth.default_count(rp),
                            data_center=data_center, rack=rack,
                            data_node=data_node,
                        )
                    except (ValueError, grpc.RpcError, IOError) as e:
                        # a full/churning cluster is a routine condition —
                        # including a chosen volume server dying between
                        # heartbeat and AllocateVolume (grpc.RpcError):
                        # surface it as an assign error (clients retry it
                        # as transient), never as a raw gRPC exception
                        glog.v(1, f"volume growth failed: {e}")
                        grow_err = e
        try:
            fid, n, locations = self.topo.pick_for_write(collection, rp, t, count=count)
        except ValueError as e:
            # when growth is WHY there is nothing to pick, the grow error
            # is the real diagnosis — a generic "no writable volumes"
            # would read as transient churn to clients and bury a
            # permanent placement-shape misconfiguration. Placement
            # ValueErrors pass through raw (their strings classify
            # client-side); transport failures get a marker that
            # operation.assign treats as transient.
            if isinstance(grow_err, ValueError):
                return {"error": str(grow_err)}
            if grow_err is not None:
                return {"error": f"volume growth rpc failed: {grow_err}"}
            return {"error": str(e)}
        # QoS shed (ISSUE 8): above SWFS_QOS_SHED_PRESSURE (0 = off)
        # refuse the assign OUTRIGHT instead of handing out a target
        # whose write would queue behind a saturated group-commit /
        # dispatch plane and time out late. Clients see an explicit
        # overload marker with a retry hint (HTTP maps it to 429).
        import os as _os

        try:
            shed_at = float(_os.environ.get("SWFS_QOS_SHED_PRESSURE", "0"))
        except ValueError:
            shed_at = 0.0
        if shed_at > 0:
            from ..utils.stats import QOS_ADMISSION_OPS

            worst = max((dn.effective_pressure() for dn in locations),
                        default=0.0)
            if worst >= shed_at:
                QOS_ADMISSION_OPS.inc(plane="master", result="reject")
                return {"error": f"overloaded: volume server pressure "
                                 f"{worst:.2f} >= {shed_at:.2f}",
                        "overloaded": True, "retryAfterS": 1.0}
            QOS_ADMISSION_OPS.inc(plane="master", result="admit")
        primary = locations[0]
        return {
            "fid": fid,
            "count": n,
            "url": primary.url,
            "publicUrl": primary.public_url,
            "replicas": locations[1:],
            "location": primary,
        }

    # -- heartbeat ingest --------------------------------------------------

    def handle_heartbeat(self, hb: master_pb2.Heartbeat, dn=None):
        from ..topology.topology import DataNode

        MASTER_RECEIVED_HEARTBEATS.inc()
        if dn is None:
            dn = DataNode(
                ip=hb.ip, port=hb.port, public_url=hb.public_url,
                grpc_port=hb.grpc_port or hb.port + rpc.GRPC_PORT_DELTA,
                data_center=hb.data_center or "DefaultDataCenter",
                rack=hb.rack or "DefaultRack",
            )
            dn = self.topo.register_node(dn)
        dn.last_seen = time.time()
        if hb.max_volume_counts:
            dn.max_volume_count = sum(hb.max_volume_counts.values())
        if hb.max_file_key:
            dn.max_file_key = hb.max_file_key
            self.topo.sequence.set_max(hb.max_file_key)
        new_vids, gone_vids = [], []
        if hb.volumes or hb.has_no_volumes:
            before = set(dn.volumes)
            self.topo.sync_node_volumes(dn, [VolumeInfo.from_pb(v) for v in hb.volumes])
            after = set(dn.volumes)
            new_vids, gone_vids = sorted(after - before), sorted(before - after)
        for v in hb.new_volumes:
            self.topo.register_volume(VolumeInfo(
                id=v.id, collection=v.collection,
                replica_placement=ReplicaPlacement.from_byte(v.replica_placement),
                ttl=TTL.from_uint32(v.ttl), version=v.version or 3,
            ), dn)
            new_vids.append(v.id)
        for v in hb.deleted_volumes:
            if v.id in dn.volumes:
                self.topo._unregister_volume(dn.volumes[v.id], dn)
                gone_vids.append(v.id)
        if hb.ec_shards or hb.has_no_ec_shards:
            self.topo.sync_node_ec_shards(dn, [
                EcShardInfo(e.id, e.collection, e.ec_index_bits)
                for e in hb.ec_shards
            ])
        for e in hb.new_ec_shards:
            self.topo.register_ec_shards(
                EcShardInfo(e.id, e.collection, e.ec_index_bits), dn
            )
        for e in hb.deleted_ec_shards:
            self.topo.unregister_ec_shards(e.id, dn, e.ec_index_bits)
        if new_vids or gone_vids:
            self._broadcast_location(dn, new_vids, gone_vids)
        return dn

    def _broadcast_cluster_updates(self, updates) -> None:
        """Push cluster.NodeUpdate events to every KeepConnected client
        (master_grpc_server.go broadcastToClients)."""
        for u in updates:
            msg = master_pb2.KeepConnectedResponse(
                cluster_node_update=master_pb2.ClusterNodeUpdate(
                    node_type=u.node_type, address=u.address,
                    filer_group=u.filer_group, is_leader=u.is_leader,
                    is_add=u.is_add))
            with self._keepalive_mu:
                for q in self._keepalive_clients.values():
                    q.put(msg)

    def meta_ring_join(self, address: str, leave: bool = False):
        """Ring membership mutation (JoinMetaRing): idempotent — a shard
        re-announcing over its heartbeat loop neither bumps the epoch
        nor disturbs routing, so a crashed-and-restarted shard rejoins
        at the SAME ring position. -> the current ring snapshot."""
        from ..utils.stats import META_RING_EPOCH, META_RING_SHARDS

        changed = False
        with self._meta_ring_mu:
            ring = self.meta_ring
            present = address in ring.shards
            if leave and present:
                self.meta_ring = ring.without_shard(address)
                changed = True
            elif not leave and not present:
                self.meta_ring = ring.with_shard(address)
                changed = True
            ring = self.meta_ring
        if changed:
            META_RING_EPOCH.set(ring.epoch)
            META_RING_SHARDS.set(len(ring))
            glog.info(f"meta ring epoch {ring.epoch}: "
                      f"{'-' if leave else '+'}{address} "
                      f"({len(ring)} shard(s))")
            # nudge every KeepConnected client: shards and gateways
            # refetch the ring on any metaRingShard update instead of
            # waiting out their cache TTL
            with self._keepalive_mu:
                for q in self._keepalive_clients.values():
                    q.put(master_pb2.KeepConnectedResponse(
                        cluster_node_update=master_pb2.ClusterNodeUpdate(
                            node_type="metaRingShard", address=address,
                            is_add=not leave)))
        return ring

    def _broadcast_location(self, dn, new_vids, deleted_vids) -> None:
        msg = master_pb2.KeepConnectedResponse(
            volume_location=master_pb2.VolumeLocation(
                url=dn.url, public_url=dn.public_url, grpc_port=dn.grpc_port,
                data_center=dn.data_center,
                new_vids=new_vids, deleted_vids=deleted_vids,
                leader=self.address,
            )
        )
        with self._keepalive_mu:
            for q in self._keepalive_clients.values():
                q.put(msg)

    # -- vacuum driver (topology_vacuum.go) --------------------------------

    def _vacuum_loop(self, interval: float) -> None:
        while not self._stop.wait(interval):
            if self.vacuum_disabled:
                continue
            try:
                self.vacuum_once(self.garbage_threshold)
            except Exception as e:  # noqa: BLE001 - keep the driver alive
                glog.warning(f"vacuum pass failed: {e}")

    # -- scrub driver (integrity plane, ISSUE 4) ---------------------------

    def _scrub_loop(self, interval: float) -> None:
        """Periodic fleet scrub: each tick nudges the least-recently-
        scrubbed volume server (topology.next_scrub_targets) to run a
        self-healing pass. The per-server scrubber does its own pacing;
        this loop only spreads WHICH server sweeps WHEN."""
        while not self._stop.wait(interval):
            if self.scrub_disabled or not self.is_leader():
                continue
            try:
                self.scrub_once()
            except Exception as e:  # noqa: BLE001 - keep the driver alive
                glog.warning(f"scrub pass failed: {e}")

    def scrub_once(self, max_nodes: int = 1, repair: bool = True) -> int:
        """Ask up to `max_nodes` due volume servers for one scrub pass.
        -> servers that completed."""
        from ..pb import scrub_pb2

        done = 0
        for dn in self.topo.next_scrub_targets(max_nodes):
            try:
                stub = rpc.volume_stub(dn.grpc_address)
                resp = stub.VolumeScrub(
                    scrub_pb2.VolumeScrubRequest(repair=repair),
                    timeout=3600)
                if resp.findings:
                    glog.warning(
                        f"scrub on {dn.url}: {len(resp.findings)} "
                        f"finding(s), {resp.repaired} repaired")
                done += 1
            except grpc.RpcError as e:
                glog.warning(f"scrub on {dn.url}: {e.code()}")
        return done

    def vacuum_once(self, threshold: float, volume_id: int = 0) -> int:
        """One scan: compact+commit every volume whose garbage ratio exceeds
        `threshold` on all replicas. -> volumes vacuumed."""
        from ..pb import volume_server_pb2 as vs

        done = 0
        for vl in list(self.topo.layouts.values()):
            for vid, nodes in list(vl.locations.items()):
                if volume_id and vid != volume_id:
                    continue
                try:
                    ratios = []
                    for dn in nodes:
                        stub = rpc.volume_stub(dn.grpc_address)
                        r = stub.VacuumVolumeCheck(
                            vs.VacuumVolumeCheckRequest(volume_id=vid), timeout=30)
                        ratios.append(r.garbage_ratio)
                    if not ratios or min(ratios) < threshold:
                        continue
                    vl.set_volume_unavailable(vid)
                    for dn in nodes:
                        stub = rpc.volume_stub(dn.grpc_address)
                        for _ in stub.VacuumVolumeCompact(
                                vs.VacuumVolumeCompactRequest(volume_id=vid),
                                timeout=3600):
                            pass
                    for dn in nodes:
                        stub = rpc.volume_stub(dn.grpc_address)
                        stub.VacuumVolumeCommit(
                            vs.VacuumVolumeCommitRequest(volume_id=vid), timeout=600)
                    done += 1
                except grpc.RpcError as e:
                    glog.warning(f"vacuum volume {vid}: {e.code()}")
        return done


# -- gRPC servicer ---------------------------------------------------------

class MasterGrpc:
    def __init__(self, ms: MasterServer):
        self.ms = ms

    def SendHeartbeat(self, request_iterator, context):
        ms = self.ms
        dn = None
        try:
            for hb in request_iterator:
                dn = ms.handle_heartbeat(hb, dn)
                yield master_pb2.HeartbeatResponse(
                    volume_size_limit=ms.topo.volume_size_limit,
                    leader=ms.leader_address(),
                )
        finally:
            # stream break = node presumed down (defer-unregister path)
            if dn is not None:
                ms.topo.unregister_node(dn.url)

    def KeepConnected(self, request_iterator, context):
        ms = self.ms
        first = next(iter(request_iterator), None)
        if first is None:
            return
        key = f"{first.client_type}@{first.client_address}#{id(context)}"
        q: queue.Queue = queue.Queue()
        with ms._keepalive_mu:
            ms._keepalive_clients[key] = q
        # filers/brokers joining the stream join their cluster group
        # (master_grpc_server.go KeepConnected -> AddClusterNode)
        ms._broadcast_cluster_updates(ms.cluster.add_cluster_node(
            first.filer_group, first.client_type, first.client_address,
            version=first.version))
        # seed the newcomer with the CURRENT group membership — members
        # that joined earlier were broadcast before this stream existed
        for node_type in ("filer", "broker"):
            for n in ms.cluster.list_cluster_nodes(first.filer_group,
                                                   node_type):
                q.put(master_pb2.KeepConnectedResponse(
                    cluster_node_update=master_pb2.ClusterNodeUpdate(
                        node_type=node_type, address=n.address,
                        filer_group=first.filer_group, is_add=True,
                        is_leader=ms.cluster.is_one_leader(
                            first.filer_group, node_type, n.address))))
        try:
            # initial full picture: every node with its volumes
            for dn in ms.topo.alive_nodes():
                yield master_pb2.KeepConnectedResponse(
                    volume_location=master_pb2.VolumeLocation(
                        url=dn.url, public_url=dn.public_url,
                        grpc_port=dn.grpc_port, data_center=dn.data_center,
                        new_vids=sorted(dn.volumes),
                        new_ec_vids=sorted(dn.ec_shards),
                        leader=ms.leader_address(),
                    )
                )
            while context.is_active():
                try:
                    yield q.get(timeout=1.0)
                except queue.Empty:
                    continue
        finally:
            with ms._keepalive_mu:
                ms._keepalive_clients.pop(key, None)
            ms._broadcast_cluster_updates(ms.cluster.remove_cluster_node(
                first.filer_group, first.client_type, first.client_address))

    def ListClusterNodes(self, request, context):
        ms = self.ms
        resp = master_pb2.ListClusterNodesResponse()
        for n in ms.cluster.list_cluster_nodes(request.filer_group,
                                               request.client_type):
            resp.cluster_nodes.add(
                address=n.address, version=n.version,
                is_leader=ms.cluster.is_one_leader(
                    request.filer_group, request.client_type, n.address),
                created_at_ns=int(n.created_ts * 1e9),
                data_center=n.data_center, rack=n.rack)
        return resp

    def _leader_stub(self):
        """Stub to the Raft leader, or None when we are it. Followers hold
        no topology (volume servers heartbeat only to the leader), so
        lookups are proxied (the reference redirects the same way)."""
        ms = self.ms
        if ms.is_leader() or ms.leader_address() == ms.address:
            return None
        return rpc.master_stub(rpc.grpc_address(ms.leader_address()))

    def LookupVolume(self, request, context):
        leader = self._leader_stub()
        if leader is not None:
            try:
                return leader.LookupVolume(request, timeout=10)
            except grpc.RpcError:
                pass  # fall through to (possibly stale) local view
        resp = master_pb2.LookupVolumeResponse()
        for vof in request.volume_or_file_ids:
            entry = resp.volume_id_locations.add(volume_or_file_id=vof)
            try:
                vid_str = vof.split(",")[0]
                vid = int(vid_str)
            except ValueError:
                entry.error = f"unknown volume id {vof}"
                continue
            nodes = self.ms.topo.lookup(request.collection, vid)
            if not nodes:
                entry.error = f"volume {vid} not found"
                continue
            for dn in nodes:
                entry.locations.append(dn.to_location())
        return resp

    def Assign(self, request, context):
        r = self.ms.assign(
            count=int(request.count) or 1, replication=request.replication,
            collection=request.collection, ttl=request.ttl,
            data_center=request.data_center, rack=request.rack,
            data_node=request.data_node,
        )
        if "error" in r:
            return master_pb2.AssignResponse(error=r["error"])
        return master_pb2.AssignResponse(
            fid=r["fid"], count=r["count"],
            location=r["location"].to_location(),
            replicas=[dn.to_location() for dn in r["replicas"]],
            auth=self.ms.mint_write_jwt(r["fid"]),
        )

    def Statistics(self, request, context):
        total, used, files = self.ms.topo.statistics(request.collection)
        return master_pb2.StatisticsResponse(
            total_size=total, used_size=used, file_count=files
        )

    def CollectionList(self, request, context):
        return master_pb2.CollectionListResponse(
            collections=[master_pb2.Collection(name=c)
                         for c in self.ms.topo.collections()]
        )

    def CollectionDelete(self, request, context):
        from ..pb import volume_server_pb2 as vs

        for dn in self.ms.topo.alive_nodes():
            try:
                rpc.volume_stub(dn.grpc_address).DeleteCollection(
                    vs.DeleteCollectionRequest(collection=request.name), timeout=60)
            except grpc.RpcError:
                pass
        return master_pb2.CollectionDeleteResponse()

    def VolumeList(self, request, context):
        return master_pb2.VolumeListResponse(
            topology_info=self.ms.topo.to_topology_info(),
            volume_size_limit_mb=self.ms.topo.volume_size_limit // (1024 * 1024),
        )

    def LookupEcVolume(self, request, context):
        leader = self._leader_stub()
        if leader is not None:
            try:
                return leader.LookupEcVolume(request, timeout=10)
            except grpc.RpcError as e:
                if e.code() == grpc.StatusCode.NOT_FOUND:
                    context.abort(grpc.StatusCode.NOT_FOUND,
                                  f"ec volume {request.volume_id} not found")
        shard_locs = self.ms.topo.lookup_ec_shards(request.volume_id)
        if not shard_locs:
            context.abort(grpc.StatusCode.NOT_FOUND,
                          f"ec volume {request.volume_id} not found")
        resp = master_pb2.LookupEcVolumeResponse(volume_id=request.volume_id)
        for sid in sorted(shard_locs):
            entry = resp.shard_id_locations.add(shard_id=sid)
            for dn in shard_locs[sid]:
                entry.locations.append(dn.to_location())
        return resp

    def VacuumVolume(self, request, context):
        self.ms.vacuum_once(request.garbage_threshold or 0.0001,
                            volume_id=request.volume_id)
        return master_pb2.VacuumVolumeResponse()

    def DisableVacuum(self, request, context):
        # master_grpc_server_volume.go:287 (Topo.DisableVacuum)
        self.ms.vacuum_disabled = True
        return master_pb2.DisableVacuumResponse()

    def EnableVacuum(self, request, context):
        # master_grpc_server_volume.go:294 (Topo.EnableVacuum)
        self.ms.vacuum_disabled = False
        return master_pb2.EnableVacuumResponse()

    def DisableScrub(self, request, context):
        # pause the fleet scrub driver (incident knob; per-server
        # daemons keep their own SWFS_SCRUB_INTERVAL_S schedule)
        from ..pb import scrub_pb2

        self.ms.scrub_disabled = True
        return scrub_pb2.DisableScrubResponse()

    def EnableScrub(self, request, context):
        from ..pb import scrub_pb2

        self.ms.scrub_disabled = False
        return scrub_pb2.EnableScrubResponse()

    def VolumeMarkReadonly(self, request, context):
        # master_grpc_server_volume.go:301 — flip the layout standing so
        # assignment stops (or resumes) handing out the volume
        ms = self.ms
        if not ms.is_leader():
            context.abort(grpc.StatusCode.FAILED_PRECONDITION,
                          f"not the leader; leader is {ms.leader_address()}")
        url = f"{request.ip}:{request.port}" if request.ip else ""
        found = ms.topo.mark_volume_readonly(
            request.collection, request.volume_id, request.is_readonly,
            url=url)
        if not found:
            context.abort(grpc.StatusCode.NOT_FOUND,
                          f"volume {request.volume_id} not found")
        return master_pb2.VolumeMarkReadonlyResponse()

    def RaftListClusterServers(self, request, context):
        # master_grpc_server_raft.go:13; in single-master mode the
        # cluster is this one server, leading itself
        ms = self.ms
        resp = master_pb2.RaftListClusterServersResponse()
        if ms.raft is None:
            resp.cluster_servers.add(id=ms.address, address=ms.address,
                                     suffrage="Voter", isLeader=True)
            return resp
        st = ms.raft.status()
        for addr in sorted({st["id"], *st["peers"]}):
            resp.cluster_servers.add(
                id=addr, address=addr, suffrage="Voter",
                isLeader=addr == st["leader"])
        return resp

    def RaftAddServer(self, request, context):
        # master_grpc_server_raft.go:37
        ms = self.ms
        if ms.raft is None:
            context.abort(grpc.StatusCode.FAILED_PRECONDITION,
                          "raft not enabled (single-master mode)")
        if not ms.is_leader():
            context.abort(grpc.StatusCode.FAILED_PRECONDITION,
                          f"not the leader; leader is {ms.leader_address()}")
        # this raft identifies peers BY address (id == address); a
        # distinct id would be registered under the address and then be
        # unremovable by RaftRemoveServer(id=...)
        if request.id and request.address and request.id != request.address:
            context.abort(grpc.StatusCode.INVALID_ARGUMENT,
                          "server id must equal its address here "
                          f"(got id={request.id!r} "
                          f"address={request.address!r})")
        try:
            ms.raft.add_peer(request.address or request.id)
        except Exception as e:  # noqa: BLE001 - surface the raft error
            context.abort(grpc.StatusCode.INTERNAL, str(e))
        return master_pb2.RaftAddServerResponse()

    def RaftRemoveServer(self, request, context):
        # master_grpc_server_raft.go:64
        ms = self.ms
        if ms.raft is None:
            context.abort(grpc.StatusCode.FAILED_PRECONDITION,
                          "raft not enabled (single-master mode)")
        if not ms.is_leader():
            context.abort(grpc.StatusCode.FAILED_PRECONDITION,
                          f"not the leader; leader is {ms.leader_address()}")
        st = ms.raft.status()
        if request.id not in {st["id"], *st["peers"]}:
            # a silent no-op "success" would hide a typo'd id forever
            context.abort(grpc.StatusCode.NOT_FOUND,
                          f"{request.id} is not a member")
        try:
            ms.raft.remove_peer(request.id)
        except Exception as e:  # noqa: BLE001
            context.abort(grpc.StatusCode.INTERNAL, str(e))
        return master_pb2.RaftRemoveServerResponse()

    def GetMasterConfiguration(self, request, context):
        return master_pb2.GetMasterConfigurationResponse(
            leader=self.ms.leader_address(),
            default_replication=self.ms.default_replication,
            volume_size_limit_m_b=self.ms.topo.volume_size_limit // (1024 * 1024),
            metrics_address=self.ms.metrics_address,
            metrics_interval_seconds=self.ms.metrics_interval_sec,
        )

    def LeaseAdminToken(self, request, context):
        ms = self.ms
        now = time.time_ns()
        with ms._admin_lock_mu:
            cur = ms._admin_locks.get(request.lock_name)
            if cur is not None:
                token, ts, client = cur
                expired = now - ts > 60e9
                same_client = client == request.client_name
                if not expired and not same_client and request.previous_token != token:
                    context.abort(grpc.StatusCode.FAILED_PRECONDITION,
                                  f"lock is held by {client}")
            token = now
            ms._admin_locks[request.lock_name] = (token, now, request.client_name)
            return master_pb2.LeaseAdminTokenResponse(token=token, lock_ts_ns=now)

    def ReleaseAdminToken(self, request, context):
        with self.ms._admin_lock_mu:
            cur = self.ms._admin_locks.get(request.lock_name)
            if cur is not None and cur[0] == request.previous_token:
                del self.ms._admin_locks[request.lock_name]
        return master_pb2.ReleaseAdminTokenResponse()

    def Ping(self, request, context):
        now = time.time_ns()
        return master_pb2.PingResponse(
            start_time_ns=now, remote_time_ns=now, stop_time_ns=time.time_ns()
        )

    def GetMetaRing(self, request, context):
        """Metadata ring fetch (ISSUE 19): the published membership +
        epoch; clients derive the identical virtual-node layout."""
        from ..pb import meta_ring_pb2

        resp = meta_ring_pb2.MetaRingResponse()
        self.ms.meta_ring.fill_response(resp)
        return resp

    def JoinMetaRing(self, request, context):
        """Shard join/renew/leave — the response doubles as an
        epoch-bumped ring update riding the shard's heartbeat loop."""
        from ..pb import meta_ring_pb2

        ring = self.ms.meta_ring_join(request.address,
                                      leave=request.leave)
        resp = meta_ring_pb2.MetaRingResponse()
        ring.fill_response(resp)
        return resp

    def QosGrant(self, request, context):
        """QoS plane (ISSUE 8): lease background byte budget to a volume
        server (strict priority by reservation in the GrantLedger) and
        absorb its pressure report into the topology so assign placement
        prefers calm servers."""
        from ..pb import qos_pb2

        ms = self.ms
        granted, ttl = ms.qos_ledger.grant(
            request.address, request.work_class,
            request.requested_bytes, request.pressure)
        dn = ms.topo.nodes.get(request.address)
        if dn is not None:
            dn.qos_pressure = float(request.pressure)
            dn.qos_pressure_at = time.time()
        rate = ms.qos_ledger.rate_bytes()
        return qos_pb2.QosGrantResponse(
            granted_bytes=granted, lease_ttl_seconds=ttl,
            cluster_rate_bytes=int(max(rate, 0.0)))


# -- HTTP plane ------------------------------------------------------------

def _make_http_handler(ms: MasterServer):
    class Handler(BaseHTTPRequestHandler):
        def log_message(self, fmt, *args):  # route to glog, not stderr
            glog.v(2, f"master http: {fmt % args}")

        def _json(self, obj, code: int = 200, headers=None) -> None:
            body = json.dumps(obj).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            tid = getattr(self, "_trace_id", "")
            if tid:
                self.send_header("X-Trace-Id", tid)
            for k, v in (headers or {}).items():
                self.send_header(k, v)
            self.end_headers()
            self.wfile.write(body)

        def do_GET(self):  # noqa: C901 - flat route table
            self._trace_id = ""  # never leak across keep-alive requests
            if urlparse(self.path).path in ("/", "/ui"):
                from .ui import master_ui

                body = master_ui(ms)
                self.send_response(200)
                self.send_header("Content-Type", "text/html; charset=utf-8")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)
                return
            u = urlparse(self.path)
            q = {k: v[0] for k, v in parse_qs(u.query).items()}
            if u.path == "/dir/assign":
                with trace.span("master.assign", carrier=self.headers,
                                component="master",
                                server=ms.address) as tsp:
                    self._trace_id = tsp.trace_id
                    r = ms.assign(
                        count=int(q.get("count", 1)),
                        replication=q.get("replication", ""),
                        collection=q.get("collection", ""),
                        ttl=q.get("ttl", ""),
                        data_center=q.get("dataCenter", ""),
                        rack=q.get("rack", ""),
                    )
                    if "error" in r:
                        # an attribute, not keep-if-error: a cluster-full
                        # burst answers hundreds of these per second and
                        # must not flush the bounded retained set (same
                        # policy as expected S3 4xx)
                        tsp.set_attr(assignError=r["error"][:120])
                        if r.get("overloaded"):
                            # QoS shed (ISSUE 8): explicit early
                            # rejection with a retry hint, not a 404
                            return self._json(
                                r, 429, headers={"Retry-After": str(
                                    int(r.get("retryAfterS", 1) + 0.5)
                                    or 1)})
                        return self._json(r, 404)
                    out = {
                        "fid": r["fid"], "count": r["count"],
                        "url": r["url"], "publicUrl": r["publicUrl"],
                    }
                    auth = ms.mint_write_jwt(r["fid"])
                    if auth:
                        out["auth"] = auth
                    return self._json(out)
            if u.path == "/dir/lookup":
                if not ms.is_leader() and ms.leader_address() != ms.address:
                    import requests as _rq

                    from ..utils.http import requests_verify, url_for

                    try:
                        r = _rq.get(
                            url_for(ms.leader_address(), self.path),
                            timeout=10, verify=requests_verify())
                        return self._json(r.json(), r.status_code)
                    except _rq.RequestException:
                        pass  # fall through to local (stale) view
                vof = q.get("volumeId", q.get("fileId", ""))
                try:
                    vid = int(str(vof).split(",")[0])
                except ValueError:
                    return self._json({"error": f"bad volumeId {vof}"}, 400)
                nodes = ms.topo.lookup(q.get("collection", ""), vid)
                if not nodes:
                    return self._json(
                        {"volumeOrFileId": vof, "error": "not found"}, 404)
                return self._json({
                    "volumeOrFileId": vof,
                    "locations": [
                        {"url": n.url, "publicUrl": n.public_url} for n in nodes
                    ],
                })
            if u.path == "/cluster/raft/status":
                if ms.raft is None:
                    return self._json({"mode": "single-master",
                                       "leader": ms.address})
                return self._json(ms.raft.status())
            if u.path in ("/status", "/dir/status", "/cluster/status"):
                total, used, files = ms.topo.statistics()
                return self._json({
                    **status_base(ms._started_at),
                    "IsLeader": ms.is_leader(),
                    "Leader": ms.leader_address(),
                    "Topology": {
                        "Max": total, "Size": used, "FileCount": files,
                        "DataNodes": sorted(ms.topo.nodes),
                    },
                    "Trace": trace.STORE.stats(),
                    # QoS plane (ISSUE 8): grant ledger + per-node
                    # pressure + admission counters
                    "Qos": {
                        **qos_stats(),
                        "ledger": ms.qos_ledger.status(),
                    },
                    "MetaRing": ms.meta_ring.describe(),
                })
            if u.path == "/debug/traces":
                return self._json(trace.debug_traces_payload(q))
            if u.path == "/vol/grow":
                if not ms.is_leader():
                    return self._json(
                        {"error": "not the leader",
                         "leader": ms.leader_address()}, 400)
                from ..storage.super_block import ReplicaPlacement
                from ..storage.ttl import EMPTY_TTL, TTL

                try:
                    rp = ReplicaPlacement.parse(
                        q.get("replication") or ms.default_replication)
                    t = TTL.parse(q["ttl"]) if q.get("ttl") else EMPTY_TTL
                    n = ms.growth.grow(
                        q.get("collection", ""), rp, t,
                        count=int(q.get("count", 1)))
                except ValueError as e:
                    return self._json({"error": str(e)}, 400)
                return self._json({"count": n})
            if u.path == "/vol/vacuum":
                n = ms.vacuum_once(float(q.get("garbageThreshold", 0.0001)))
                return self._json({"vacuumed": n})
            # vacuum enable/disable and raft membership moved to gRPC
            # (DisableVacuum/EnableVacuum/RaftAddServer/RaftRemoveServer)
            # — the reference keeps no HTTP twin for them either
            if u.path == "/col/delete":
                return self._json({"error": "use gRPC CollectionDelete"}, 400)
            if u.path == "/metrics":
                ex = "exemplars" in q
                body = gather(exemplars=ex).encode()
                self.send_response(200)
                self.send_header("Content-Type", metrics_content_type(ex))
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)
                return
            self._json({"error": "not found"}, 404)

        def do_POST(self):
            u = urlparse(self.path)
            if u.path == "/cluster/raft":
                if ms.raft is None:
                    return self._json({"error": "raft not enabled"}, 400)
                n = int(self.headers.get("Content-Length") or 0)
                req = json.loads(self.rfile.read(n) or b"{}")
                handler = getattr(ms.raft, "handle_" + req.get("method", ""),
                                  None)
                if handler is None:
                    return self._json({"error": "unknown raft method"}, 400)
                return self._json(handler(req.get("payload", {})))
            return self.do_GET()

    return Handler
