"""Volume server: HTTP data plane + gRPC admin plane + master heartbeat.

Rebuild of /root/reference/weed/server/volume_server.go,
volume_server_handlers_{read,write}.go, volume_grpc_*.go and
volume_grpc_client_to_master.go:50-92. The data plane speaks HTTP
(PUT/GET/DELETE of "/vid,fid" needles, replica fan-out with
`?type=replicate`); the admin plane is gRPC (vacuum, allocate, mount,
copy, tail, and the nine erasure-coding RPCs whose shard math runs on the
JAX/TPU coder).
"""

from __future__ import annotations

import json
import os
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from http.server import BaseHTTPRequestHandler

from ..utils.httpd import TunedThreadingHTTPServer
from urllib.parse import parse_qs, urlparse

import grpc
import numpy as np

from ..ops import dispatch
from ..pb import master_pb2, rpc, scrub_pb2, volume_server_pb2 as vs
from ..scrub import Scrubber
from ..scrub import digest as scrub_digest
from ..storage import types
from ..storage.ec_files import (
    find_dat_file_size,
    rebuild_ec_files,
    write_dat_file,
    write_ec_files,
    write_idx_file_from_ec_index,
    write_sorted_file_from_idx,
)
from ..storage.ec_locate import Geometry, locate_data
from ..storage.ec_volume import EcVolume, delete_needle_from_ecx
from ..storage.errors import (
    CookieMismatch,
    DeletedError,
    NotFoundError,
    QuarantinedError,
)
from ..storage.file_id import parse_file_id
from ..storage.needle import CrcError, Needle
from ..storage.store import Store
from ..storage.ttl import TTL
from ..utils import failpoint, fanout, glog, numa, trace
from ..utils.http import not_modified, parse_range, range_applies, url_for
from ..utils.stats import (
    VOLUME_REPLICA_DELETE_FAILURES,
    VOLUME_SERVER_EC_ENCODE_BYTES,
    VOLUME_SERVER_NATIVE_REQUESTS,
    VOLUME_SERVER_REQUEST_HISTOGRAM,
    VOLUME_SERVER_VOLUME_COUNTER,
    gather,
    metrics_content_type,
    status_base,
)

BUFFER_SIZE_LIMIT = 2 * 1024 * 1024  # streaming chunk (volume_grpc_copy.go:25)


class _RateMeter:
    """Sliding-window foreground request rate — the signal the scrub
    plane backs off on (scrub must yield to client traffic). note() is
    amortized O(1): each timestamp is appended once and popped once, so
    the hot data path never pays a window-sized rebuild under the lock."""

    def __init__(self, window_s: float = 2.0):
        from collections import deque

        self.window = window_s
        self._events: "deque[float]" = deque()
        self._lock = threading.Lock()

    def note(self) -> None:
        now = time.monotonic()
        cut = now - self.window
        with self._lock:
            self._events.append(now)
            while self._events and self._events[0] < cut:
                self._events.popleft()

    def qps(self) -> float:
        cut = time.monotonic() - self.window
        with self._lock:
            while self._events and self._events[0] < cut:
                self._events.popleft()
            return len(self._events) / self.window


class VolumeServer:
    def __init__(self, *, directories: list[str], master: str,
                 ip: str = "localhost", port: int = 8080,
                 public_url: str = "", data_center: str = "", rack: str = "",
                 max_volume_counts: list[int] | None = None,
                 pulse_seconds: int = 5, coder=None,
                 ec_geometry: Geometry = Geometry(),
                 tier_backends: dict | None = None,
                 needle_map_kind: str = "memory",
                 write_jwt_key: bytes = b"",
                 guard=None, native: bool = False):
        self.write_jwt_key = write_jwt_key
        self.guard = guard  # IP whitelist (security.Guard) or None
        # C++ data plane: serves needle GET/PUT/DELETE on the public port,
        # 307s everything else to the Python listener on admin_port. Only
        # meaningful when neither JWT auth nor an IP guard is configured
        # (those checks live in the Python handlers).
        # SEAWEEDFS_TPU_NATIVE=1 forces it on process-wide, =0 forces it
        # off (CI sweep knob); unset respects the constructor argument.
        env_native = os.environ.get("SEAWEEDFS_TPU_NATIVE", "").lower()
        if env_native in ("1", "true", "on"):
            native = True
        elif env_native in ("0", "false", "off"):
            native = False
        # the C++ plane speaks 16-byte idx entries only; in large-disk
        # (5-byte offset) mode it could never serve a volume, so don't
        # bind it at all — clients keep the direct python port. Under
        # SWFS_HTTPS the public port must speak TLS, which the C++ plane
        # does not: the python listener owns the (encrypted) data plane
        # and serving falls back to the buffered path (ISSUE 9).
        from ..utils.http import https_on

        self.native_enabled = (bool(native) and not write_jwt_key
                               and guard is None and types.OFFSET_SIZE == 4
                               and not https_on())
        self.native_plane = None
        if self.native_enabled:
            self.admin_port = rpc.derived_admin_port(port)
        else:
            self.admin_port = port
        if tier_backends:
            from ..storage.backend import load_tier_backends

            load_tier_backends(tier_backends)
        self.ip = ip
        self.port = port
        self.grpc_port = rpc.derived_grpc_port(port)
        self.masters = [m.strip() for m in master.split(",") if m.strip()]
        self.master = self.masters[0]  # HTTP address; gRPC is +10000
        self.master_grpc = rpc.grpc_address(self.master)
        self.pulse_seconds = pulse_seconds
        self.ec_geometry = ec_geometry
        self.store = Store(
            directories, coder=coder, max_volume_counts=max_volume_counts,
            ip=ip, port=port, public_url=public_url, grpc_port=self.grpc_port,
            data_center=data_center, rack=rack,
            needle_map_kind=needle_map_kind,
        )
        self.volume_size_limit = 30_000 * 1024 * 1024
        self._grpc_server = None
        self._http_server = None
        self._stop = threading.Event()
        self._hb_wake = threading.Event()
        # vid -> {shard_id: [addresses]} with expiry (store_ec.go:238 cache)
        self._ec_loc_cache: dict[int, tuple[float, dict[int, list[str]]]] = {}
        self._loc_cache: dict[int, tuple[float, list[str]]] = {}
        self._native_lock = threading.Lock()
        # reconstructed-interval LRU for degraded EC reads: a hot lost
        # shard pays the k-survivor fetch + device dispatch once per
        # block; invalidated on shard mount/unmount/delete (the gRPC
        # handlers below). SWFS_EC_RECON_CACHE_MB=0 disables it.
        self.ec_recon_cache = dispatch.ReconstructIntervalCache()
        # integrity plane (ISSUE 4): the paced background scrubber —
        # needle CRC sweeps, EC syndrome verification, anti-entropy and
        # the self-healing repair ladder (scrub/scrubber.py). The
        # foreground rate meter is what it backs off on.
        self._fg_rate = _RateMeter()
        self.scrubber = Scrubber(self.store, self)
        # QoS plane (ISSUE 8): every background byte (repair > scrub /
        # archival, strict priority) passes through the governor, which
        # leases cluster-wide budgets from the master over QosGrant and
        # reports this server's pressure score on each refresh.
        # Unconfigured env = no-op gate.
        from ..qos import BackgroundGovernor

        self.qos_governor = BackgroundGovernor(self)
        # response-stamped pressure (ROADMAP 5(b) / ISSUE 19): ordinary
        # read/write replies carry the live score so clients learn about
        # building backpressure from traffic they already have in
        # flight, BEFORE the first 429
        self._pressure_stamp = "0.0"
        self._pressure_stamp_at = 0.0
        self._started_at = time.time()

    @property
    def address(self) -> str:
        return f"{self.ip}:{self.port}"

    def ec_dispatch_depths(self) -> dict[str, int]:
        """Live queued-slab depth per chip lane of this store's EC
        dispatch scheduler ({} until EC work has attached one) — the
        /status signal that shows which chips' queues are filling."""
        sched = getattr(self.store.coder, "_ec_dispatch_sched", None)
        if sched is None or sched.closed:
            return {}
        return sched.chip_depths()

    def ec_dispatch_arena(self) -> dict:
        """Live stack-arena snapshot of this store's scheduler (ISSUE
        12): pooled/in-use/quarantined buffers — the host memory plane's
        working-set view, complementing the cumulative counters in
        ec_dispatch_stats()."""
        sched = getattr(self.store.coder, "_ec_dispatch_sched", None)
        if sched is None or sched.closed:
            return {}
        return sched.arena_stats()

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> None:
        trace.set_identity("volume", self.address)
        self._grpc_server = rpc.new_server()
        creds = rpc.add_servicer(self._grpc_server, rpc.VOLUME_SERVICE,
                                 VolumeGrpc(self), component="volume",
                                 address=self.address)
        rpc.serve_port(self._grpc_server, f"[::]:{self.grpc_port}",
                       "volume", creds=creds)
        self._grpc_server.start()
        handler = _make_http_handler(self)
        # HTTPS data plane (ISSUE 9): TLS on the public listener when
        # SWFS_HTTPS / security.toml [https.volume] configure it
        from ..security.tls import load_http_server_context

        https_ctx = load_http_server_context("volume")
        try:
            self._http_server = TunedThreadingHTTPServer(
                ("", self.admin_port), handler, ssl_context=https_ctx)
        except OSError:
            if not self.native_enabled:
                raise
            # deterministic admin port (public+11000) taken by another
            # process: fall back to an ephemeral one — only redirects
            # reference it, via the Location header
            self._http_server = TunedThreadingHTTPServer(
                ("", 0), handler, ssl_context=https_ctx)
            self.admin_port = self._http_server.server_address[1]
        threading.Thread(target=self._http_server.serve_forever, daemon=True).start()
        if self.native_enabled:
            from ..native import NativeDataPlane

            self.native_plane = NativeDataPlane(
                "", self.port, self.admin_port, nthreads=8)
            self._sync_native_registry()
        threading.Thread(target=self._heartbeat_loop, daemon=True).start()
        threading.Thread(target=self._check_with_master, daemon=True).start()
        report_s = float(os.environ.get("SWFS_QOS_REPORT_S", "0") or 0)
        if report_s <= 0 and (self.qos_governor.enabled()
                              or float(os.environ.get(
                                  "SWFS_QOS_SHED_PRESSURE", "0") or 0) > 0):
            report_s = 1.0  # QoS plane active: default 1s pressure feed
        if report_s > 0:
            threading.Thread(target=self._qos_report_loop,
                             args=(report_s,), daemon=True).start()
        self.scrubber.start()
        # crash-consistency handoff (ISSUE 16): every volume the mount
        # ladder repaired gets a targeted verify — the fabric re-checks
        # it against replicas and re-replicates acked-but-local-lost
        # needles, closing the zero-acked-loss contract cluster-wide
        for vid in getattr(self.store.recovery_report, "suspects", []):
            self.scrubber.report_suspect(vid)
        glog.info(f"volume server started on {self.address} "
                  f"(grpc :{self.grpc_port}"
                  + (", https" if https_ctx is not None else "")
                  + (f", native data plane, admin :{self.admin_port})"
                     if self.native_plane else ")"))

    def _sync_native_registry(self) -> None:
        """Reconcile the C++ plane's volume registry with the store: add
        new volumes, drop gone ones, track read-only flips. Called at
        start, every heartbeat, and after volume lifecycle RPCs."""
        plane = self.native_plane
        if plane is None:
            return
        with self._native_lock:  # heartbeat + gRPC handlers race here
            current: dict[int, object] = {}
            for loc in self.store.locations:
                current.update(loc.volumes)
            registered = getattr(self, "_native_vids", {})
            for vid, v in current.items():
                if v.is_tiered or v._dat is None or v._gc_frozen:
                    # frozen: un-flushable buffered bytes — handing the
                    # plane write authority (attach flushes) would raise
                    continue
                if types.OFFSET_SIZE != 4:
                    # the C++ plane reads/writes 16-byte idx entries only;
                    # large-disk (5-byte offset, 17B stride) volumes stay
                    # on the python engine
                    continue
                writable = (not v.read_only
                            and v.super_block.replica_placement.copy_count == 1
                            and not str(v.ttl))
                if vid not in registered:
                    base = v.file_name()
                    try:
                        plane.add_volume(vid, base + ".dat", base + ".idx",
                                         v.version, writable)
                    except OSError:
                        continue
                    v.native_writable = writable
                    v.attach_native(plane)
                    registered[vid] = writable
                elif registered[vid] != writable:
                    plane.set_writable(vid, writable)
                    v.native_writable = writable
                    registered[vid] = writable
            for vid in list(registered):
                if vid not in current:
                    plane.remove_volume(vid)
                    registered.pop(vid)
            self._native_vids = registered
            # absorb C++-appended idx entries so nm counters (heartbeats,
            # vacuum decisions) stay authoritative
            for vid in registered:
                v = current.get(vid)
                if v is not None:
                    v.sync_native()

    def _check_with_master(self) -> None:
        """checkWithMaster (volume_grpc_client_to_master.go:28-47): pull
        cluster config — start pushing metrics if the master names a
        push gateway."""
        from ..utils.stats import start_push

        while not self._stop.is_set():
            try:
                resp = rpc.master_stub(self.master_grpc) \
                    .GetMasterConfiguration(
                        master_pb2.GetMasterConfigurationRequest(),
                        timeout=10)
                if resp.metrics_address:
                    self._stop_metrics_push = start_push(
                        resp.metrics_address,
                        f"volumeServer-{self.port}",
                        resp.metrics_interval_seconds or 15)
                return
            except grpc.RpcError:
                if self._stop.wait(2.0):
                    return

    def stop(self) -> None:
        self._stop.set()
        self._hb_wake.set()
        self.scrubber.stop()
        stop_push = getattr(self, "_stop_metrics_push", None)
        if stop_push is not None:
            stop_push()
        if self._http_server:
            self._http_server.shutdown()
        if self._grpc_server:
            self._grpc_server.stop(grace=0.5)
        if self.native_plane is not None:
            self.native_plane.stop()
            self.native_plane = None
        self.store.close()

    # -- heartbeat client (volume_grpc_client_to_master.go:50-92) ----------

    def _heartbeat_loop(self) -> None:
        while not self._stop.is_set():
            try:
                self._do_heartbeat()
            except grpc.RpcError as e:
                glog.v(1, f"heartbeat to {self.master} failed: {e.code()}")
                # rotate to the next configured master; a leader redirect
                # may have pointed self.master outside the configured list
                if len(self.masters) > 1:
                    if self.master in self.masters:
                        i = self.masters.index(self.master)
                        nxt = self.masters[(i + 1) % len(self.masters)]
                    else:
                        nxt = self.masters[0]
                    self.master = nxt
                    self.master_grpc = rpc.grpc_address(nxt)
            if not self._stop.is_set():
                self._stop.wait(1.0)

    def _do_heartbeat(self) -> None:
        stub = rpc.master_stub(self.master_grpc)

        def requests():
            while not self._stop.is_set():
                self._sync_native_registry()
                yield self.store.collect_heartbeat()
                self._hb_wake.wait(self.pulse_seconds)
                self._hb_wake.clear()

        for resp in stub.SendHeartbeat(requests()):
            if resp.volume_size_limit:
                self.volume_size_limit = resp.volume_size_limit
            if resp.leader and resp.leader != self.master:
                # follow the Raft leader (checkWithMaster redirect)
                glog.info(f"heartbeat redirected to leader {resp.leader}")
                self.master = resp.leader
                self.master_grpc = rpc.grpc_address(resp.leader)
                return
            VOLUME_SERVER_VOLUME_COUNTER.set(
                sum(len(l.volumes) for l in self.store.locations)
            )
            plane = self.native_plane  # stop() may null it concurrently
            if plane is not None:
                from ..utils.stats import HTTP_NATIVE_SENDFILE

                VOLUME_SERVER_NATIVE_REQUESTS.set(plane.request_count())
                HTTP_NATIVE_SENDFILE.set(plane.sendfile_count())
            if self._stop.is_set():
                return

    def trigger_heartbeat(self) -> None:
        self._hb_wake.set()

    # -- needle read incl. EC (store.go:410 / store_ec.go:136) -------------

    def foreground_qps(self) -> float:
        """Client data-plane request rate; the scrubber backs off on it."""
        return self._fg_rate.qps()

    # -- QoS plane (ISSUE 8) -----------------------------------------------

    def qos_group_commit_depth(self) -> int:
        """Writes registered for a group-commit flush but not yet covered
        by one, summed over volumes — the write-plane half of the
        pressure score (the aggregate view of PR-7's gcWaitMs spans)."""
        total = 0
        for loc in self.store.locations:
            for v in list(loc.volumes.values()):
                total += max(0, v._gc_seq - v._gc_flushed)
        return total

    def pressure_header_value(self) -> str:
        """Cached [0,1] score for per-reply stamping: recomputed at most
        every 0.25s, so the per-request cost is one field read instead
        of a full volume walk."""
        import time as _time

        now = _time.monotonic()
        if now >= self._pressure_stamp_at:
            self._pressure_stamp = f"{self.qos_pressure():.4f}"
            self._pressure_stamp_at = now + 0.25
        return self._pressure_stamp

    def qos_pressure(self, gc_depth: int | None = None,
                     dispatch_depth: int | None = None) -> float:
        """This server's [0,1] backpressure score: group-commit buffer
        depth folded with EC-dispatch queue depth (qos/pressure.py).
        Rides every QosGrant refresh to the master, which folds it into
        assign placement and early shedding. Callers that already
        sampled the depths pass them in (one volume walk, one score)."""
        from ..qos import pressure_score
        from ..qos.pressure import SIGNAL
        from ..utils.stats import QOS_PRESSURE

        if gc_depth is None:
            gc_depth = self.qos_group_commit_depth()
        if dispatch_depth is None:
            dispatch_depth = sum(self.ec_dispatch_depths().values())
        p = pressure_score(gc_depth, dispatch_depth)
        QOS_PRESSURE.set(p)
        # feed the process-local hot signal (ISSUE 14): in combined
        # topologies (`weed server -filer` — filer + volume in one
        # process) the pipelined chunk engine collapses its windows
        # when this server's own queues cross the shed threshold,
        # BEFORE the first 429/503 is ever emitted
        SIGNAL.report_score(p)
        return p

    def qos_acquire(self, work_class: str, nbytes: int) -> float:
        """Background-work admission: delegate to the governor (no-op
        when the cluster budget is unconfigured). QosUnavailable
        propagates — callers pause their background work (fail closed),
        never surface it to a foreground client."""
        return self.qos_governor.acquire(work_class, nbytes)

    def _qos_report_loop(self, interval: float) -> None:
        """Periodic pressure-only QosGrant (work_class "") so the master
        sees THIS server's pressure even while no background work is
        drawing tokens — foreground-induced pressure must reach assign
        placement too."""
        from ..pb import qos_pb2, rpc as _rpc

        while not self._stop.wait(interval):
            try:
                gc_depth = self.qos_group_commit_depth()
                dispatch_depth = sum(self.ec_dispatch_depths().values())
                _rpc.master_stub(self.master_grpc).QosGrant(
                    qos_pb2.QosGrantRequest(
                        address=self.address, work_class="",
                        requested_bytes=0,
                        pressure=self.qos_pressure(gc_depth,
                                                   dispatch_depth),
                        gc_depth=gc_depth,
                        dispatch_depth=dispatch_depth),
                    timeout=5)
            # lint: allow-broad-except(best-effort pressure telemetry;
            # the next 1s tick retries and a down master is routine —
            # real token draws fail closed through the governor)
            except Exception:  # noqa: BLE001
                continue

    def read_needle(self, vid: int, needle_id: int, cookie: int | None):
        v = self.store.find_volume(vid)
        if v is not None:
            try:
                return v.read_needle(needle_id, cookie)
            except QuarantinedError:
                # scrub quarantined the local record mid-repair: answer
                # from a healthy replica so the client never sees either
                # the corrupt bytes or an error
                n = self._read_needle_from_replica(v, needle_id, cookie)
                if n is not None:
                    return n
                raise
        ev = self.store.find_ec_volume(vid)
        if ev is not None:
            return self._read_ec_needle(ev, vid, needle_id, cookie)
        raise NotFoundError(f"volume {vid} not found")

    def _read_needle_from_replica(self, v, needle_id: int,
                                  cookie: int | None) -> Needle | None:
        from ..scrub.scrubber import fetch_needle_from_replicas

        n = fetch_needle_from_replicas(self, v.id, needle_id, v.version)
        if n is None:
            return None
        if cookie is not None and n.cookie != cookie:
            raise CookieMismatch("cookie mismatch on replica read")
        if n.has_expired():
            raise NotFoundError(f"needle {needle_id:x} expired")
        return n

    def _read_ec_needle(self, ev: EcVolume, vid: int, needle_id: int,
                        cookie: int | None) -> Needle:
        offset, size = ev.find_needle(needle_id)
        if types.size_is_deleted(size):
            raise DeletedError(f"needle {needle_id:x} deleted")
        length = types.actual_size(size, ev.version)

        def parse_verified(blob: bytes) -> Needle:
            n = Needle.from_bytes(blob, ev.version, expected_size=size)
            if cookie is not None and n.cookie != cookie:
                # under suspected rot a cookie mismatch is ambiguous
                # (rotten header byte vs bad client) — let the ladder
                # decide by reconstructing; a genuine bad cookie fails
                # the same way against the reconstructed bytes too
                raise CookieMismatch("cookie mismatch on EC read")
            return n

        # extent-read failures (unreachable shards, failed reconstruct)
        # propagate directly: the self-heal ladder below is ONLY for
        # bytes that were read but failed verification — retrying an
        # infrastructure failure per candidate shard would turn one
        # failing read into N expensive k-survivor gathers
        blob = self._read_ec_extent(ev, vid, offset, length)
        try:
            return parse_verified(blob)
        except (CrcError, ValueError, IOError, CookieMismatch) as first:
            # The record failed verification straight off local shard
            # files (IOError here is parse-level: a flipped size byte
            # reads as SizeMismatch/short-body, not a CRC error;
            # CookieMismatch covers cookie-byte rot). One of the shards
            # this needle touches has rotted on disk — but which one
            # isn't knowable from the failure alone. Retry once per
            # candidate shard, reconstructing the extent with that shard
            # excluded everywhere (13 survivors still >= k): the
            # candidate whose exclusion yields a verified parse is the
            # rotten one. The client gets clean bytes; the volume is
            # queued for a targeted scrub + durable rebuild.
            intervals = locate_data(ev.geo, ev.dat_size_estimate, offset,
                                    length)
            sids = list(dict.fromkeys(
                iv.to_shard_id_and_offset(ev.geo)[0] for iv in intervals))
            if isinstance(first, CookieMismatch):
                # the cookie lives in the record HEADER, i.e. the first
                # interval's shard — one candidate bounds the work, so a
                # client sending a genuinely wrong cookie costs one
                # reconstruction, not one per shard (request
                # amplification)
                sids = sids[:1]
            for suspect in sids:
                try:
                    n = parse_verified(self._read_ec_extent(
                        ev, vid, offset, length, exclude_shard=suspect))
                except (CrcError, ValueError, IOError, CookieMismatch):
                    continue
                self.scrubber.report_suspect(vid)
                glog.warning(
                    f"ec vol {vid} needle {needle_id:x}: local shard "
                    f"bytes failed verification (suspect shard "
                    f"{suspect}); served via reconstruction, scrub "
                    f"queued")
                return n
            raise

    def _read_ec_extent(self, ev: EcVolume, vid: int, offset: int,
                        length: int,
                        exclude_shard: int | None = None) -> bytes:
        """readEcShardIntervals (store_ec.go:176): local shard file, else
        remote peer holding the shard, else reconstruct from any k. With
        `exclude_shard`, that shard's local bytes are treated as rotten:
        its intervals reconstruct around it and it is never used as a
        survivor (scrub self-heal)."""
        intervals = locate_data(ev.geo, ev.dat_size_estimate, offset, length)
        out = bytearray()
        for iv in intervals:
            sid, soff = iv.to_shard_id_and_offset(ev.geo)
            if exclude_shard is not None and sid == exclude_shard:
                out += self._reconstruct_range(
                    ev, vid, sid, soff, iv.size,
                    self._lookup_ec_shards(vid), exclude={exclude_shard})
            else:
                out += self._read_ec_interval(ev, vid, sid, soff, iv.size)
        return bytes(out)

    def _read_ec_interval(self, ev: EcVolume, vid: int, sid: int,
                          soff: int, size: int) -> bytes:
        f = ev.shard_files.get(sid)
        if f is not None:
            try:
                # chaos hook: a lost/unreadable local shard pushes the
                # read down the remote-peer / reconstruct-from-any-k path
                failpoint.fail("ec.shard.read",
                               ctx=f"{self.address}, shard={sid},")
                data = f.read_at(soff, size)
                return data + b"\0" * (size - len(data))
            except OSError as e:  # includes injected FailpointError
                glog.v(1, f"ec vol {vid} shard {sid} local read failed "
                          f"({e}); degrading to remote/reconstruct")
        locs = self._lookup_ec_shards(vid)
        for addr in locs.get(sid, []):
            if addr == self.address:
                continue
            try:
                return self._remote_shard_read(addr, vid, sid, soff, size)
            except grpc.RpcError:
                continue
        # degraded: gather k intervals from local+remote shards in parallel
        # (recoverOneRemoteEcShardInterval, store_ec.go:339-393)
        return self._reconstruct_interval(ev, vid, sid, soff, size, locs)

    def _remote_shard_read(self, addr: str, vid: int, sid: int,
                           soff: int, size: int) -> bytes:
        stub = rpc.volume_stub(rpc.grpc_address(addr))
        buf = bytearray()
        for resp in stub.VolumeEcShardRead(vs.VolumeEcShardReadRequest(
                volume_id=vid, shard_id=sid, offset=soff, size=size), timeout=60):
            buf += resp.data
        buf += b"\0" * (size - len(buf))
        return bytes(buf)

    def _reconstruct_interval(self, ev: EcVolume, vid: int, sid: int,
                              soff: int, size: int,
                              locs: dict[int, list[str]]) -> bytes:
        """Degraded read: serve [soff, soff+size) of a lost shard.

        Rides the reconstructed-interval cache (block-aligned, LRU,
        invalidated on shard mount/unmount/delete) so repeated degraded
        reads of a hot lost shard stop paying a full k-shard fetch +
        device dispatch each; cache-miss blocks and cache-off reads go
        through `_reconstruct_range`, whose dispatches micro-batch with
        every other concurrent degraded read via the EC dispatch
        scheduler."""
        cache = self.ec_recon_cache
        if (cache is None or not cache.enabled()
                or len(ev.shard_files) < ev.geo.data_shards):
            # remote-survivor reconstructs stay interval-sized: block-
            # aligning them would multiply the remote fetch traffic by
            # up to block/interval per missing local shard
            return self._reconstruct_range(ev, vid, sid, soff, size, locs)
        out = bytearray()
        bs = cache.block_size
        gen = cache.generation(vid)  # before any survivor bytes are read
        for blk in cache.blocks_for(soff, size):
            start = blk * bs
            blen = min(bs, max(ev.shard_size, soff + size) - start)
            data = cache.get(vid, sid, blk)
            if data is None:
                data = self._reconstruct_range(
                    ev, vid, sid, start, blen, locs)
                cache.put(vid, sid, blk, data, gen=gen)
            lo = max(soff, start) - start
            hi = min(soff + size, start + blen) - start
            out += data[lo:hi]
        if len(out) < size:  # interval ran past the cached shard extent
            out += b"\0" * (size - len(out))
        return bytes(out)

    def _reconstruct_range(self, ev: EcVolume, vid: int, sid: int,
                           soff: int, size: int,
                           locs: dict[int, list[str]],
                           exclude: set[int] | None = None) -> bytes:
        """recoverOneRemoteEcShardInterval (store_ec.go:339-393): gather k
        survivor intervals (local + remote, in parallel), then reconstruct
        through the stacked fast path — concurrent calls sharing a
        survivor set coalesce into one device dispatch. Shards in
        `exclude` are never used as survivors (scrub self-heal: their
        bytes exist locally but are suspected rotten)."""
        with trace.span("volume.ec.reconstruct", child_only=True,
                        server=self.address, vid=vid, shard=sid,
                        size=size) as tsp:
            out = self._reconstruct_range_traced(
                ev, vid, sid, soff, size, locs, exclude, tsp)
        return out

    def _reconstruct_range_traced(self, ev, vid, sid, soff, size, locs,
                                  exclude, tsp) -> bytes:
        geo = ev.geo
        exclude = exclude or set()
        out = self._reconstruct_range_planned(ev, vid, sid, soff, size,
                                              locs, exclude, tsp)
        if out is not None:
            return out
        bufs: dict[int, np.ndarray] = {}
        for i, f in ev.shard_files.items():
            if i in exclude:
                continue
            try:
                failpoint.fail("ec.shard.read",
                               ctx=f"{self.address}, shard={i},")
                data = f.read_at(soff, size)
            except OSError:  # includes injected FailpointError
                continue  # survivor set shrinks; any k still suffice
            bufs[i] = np.frombuffer(data + b"\0" * (size - len(data)), np.uint8)

        missing = [
            i for i in range(geo.total_shards)
            if i not in bufs and i != sid and i not in exclude
            and locs.get(i)
        ]

        def fetch(i):
            for addr in locs.get(i, []):
                if addr == self.address:
                    continue
                try:
                    return i, np.frombuffer(
                        self._remote_shard_read(addr, vid, i, soff, size), np.uint8)
                except grpc.RpcError:
                    continue
            return i, None

        from ..models.geometry import UnsolvableError

        try:
            geom = geo.code_geometry()
        except ValueError:
            geom = None

        def solvable() -> bool:
            # RS: any k survivors decode (the historical count check).
            # Non-RS: k survivors may be rank-deficient for THIS shard
            # (e.g. a local parity among them) — ask the solver, so the
            # remote fetch keeps going until sid is actually spanned.
            if geom is None or geom.is_rs:
                return len(bufs) >= geo.data_shards
            if sid in bufs:
                return True
            try:
                geom.repair_matrix(tuple(sorted(bufs)), (sid,))
                return True
            except (UnsolvableError, ValueError):
                return False

        if not solvable() and missing:
            # lint: allow-executor — lazy ex.map + early break once the
            # solver is satisfied needs a scoped pool whose exit joins
            # the stragglers; bounded by the shard count (<= 13 tasks)
            with ThreadPoolExecutor(max_workers=8) as ex:
                for i, arr in ex.map(fetch, missing):
                    if arr is not None:
                        bufs[i] = arr
                    if solvable():
                        break
        if not solvable():
            raise IOError(
                f"ec volume {vid}: {len(bufs)} reachable shards "
                f"({geo.code_name}) cannot reconstruct shard {sid}")
        if sid in bufs:  # a flaky local read healed mid-gather
            return bufs[sid].tobytes()
        pres = tuple(sorted(bufs))  # canonical order -> shared lane
        tsp.set_attr(survivors=len(pres))
        # RS keeps want=None: concurrent readers of DIFFERENT lost
        # shards sharing a survivor set coalesce into one fused dispatch
        # (the ISSUE-3 micro-batch); non-RS solves just sid — the full
        # complement may be unsolvable even when sid is.
        want = None if (geom is None or geom.is_rs) else (sid,)
        try:
            mids, rows = dispatch.reconstruct_now(
                ev.coder, pres, np.stack([bufs[i] for i in pres]),
                want=want)
        except (UnsolvableError, ValueError) as e:
            raise IOError(
                f"ec volume {vid}: survivors {pres} do not span "
                f"shard {sid}") from e
        return np.asarray(rows[list(mids).index(sid)],
                          np.uint8).tobytes()

    def _reconstruct_range_planned(self, ev, vid, sid, soff, size, locs,
                                   exclude, tsp) -> bytes | None:
        """Minimal-read degraded reconstruct (ISSUE 11): the geometry's
        repair plan names the survivors — a lost shard inside an LRC
        local group gathers its 5 group peers (local reads preferred)
        instead of any k=10. Returns None when a planned read fails or
        the plan is unsolvable; the caller then runs the generic any-k
        gather, which remains the correctness backstop."""
        from ..models.geometry import UnsolvableError
        from ..utils.stats import EC_REPAIR_BYTES, EC_REPAIR_PLANS

        geo = ev.geo
        try:
            geom = geo.code_geometry()
        except ValueError:
            return None
        local = set(ev.shard_files) - exclude - {sid}
        remote = {i for i, addrs in locs.items()
                  if addrs and i not in exclude and i != sid} - local
        plan = None
        for cand in (tuple(sorted(local)),
                     tuple(sorted(local | remote))):
            try:
                plan = geom.repair_plan((sid,), cand)
                break
            except (UnsolvableError, ValueError):
                continue
        if plan is None:
            return None
        bufs: dict[int, np.ndarray] = {}
        need_remote: list[int] = []
        for i in plan.reads:
            f = ev.shard_files.get(i)
            if f is not None and i not in exclude:
                try:
                    failpoint.fail("ec.shard.read",
                                   ctx=f"{self.address}, shard={i},")
                    data = f.read_at(soff, size)
                    bufs[i] = np.frombuffer(
                        data + b"\0" * (size - len(data)), np.uint8)
                    continue
                except OSError:
                    pass  # fall through to a remote copy, if any
            need_remote.append(i)
        n_local = len(bufs)

        def fetch_planned(i):
            for addr in locs.get(i, []):
                if addr == self.address:
                    continue
                try:
                    return i, np.frombuffer(self._remote_shard_read(
                        addr, vid, i, soff, size), np.uint8)
                except grpc.RpcError:
                    continue
            return i, None

        if need_remote:
            # gather the plan's remote survivors CONCURRENTLY — the
            # minimal-read path must pay max(RTT), not sum(RTT), or it
            # loses to the parallel any-k backstop it exists to beat
            # lint: allow-executor — scoped pool: the all-or-nothing
            # early return (None -> generic path) must join every fetch
            with ThreadPoolExecutor(
                    max_workers=min(8, len(need_remote))) as ex:
                for i, arr in ex.map(fetch_planned, need_remote):
                    if arr is None:
                        return None  # planned survivor unreachable:
                        #              generic path takes over
                    bufs[i] = arr
        n_remote = len(need_remote)
        pres = tuple(sorted(bufs))  # canonical order -> shared lane
        tsp.set_attr(survivors=len(pres), repairPlan=geo.code_name)
        # RS: want=None so concurrent readers of different lost shards
        # sharing a survivor set keep coalescing into ONE fused dispatch
        # (ISSUE 3); non-RS solves exactly sid (the plan's survivor set
        # may not span the full complement)
        want = None if geom.is_rs else (sid,)
        try:
            mids, rows = dispatch.reconstruct_now(
                ev.coder, pres, np.stack([bufs[i] for i in pres]),
                want=want)
        except (UnsolvableError, ValueError, TypeError):
            return None
        if n_local:
            EC_REPAIR_BYTES.inc(n_local * size, geometry=geo.code_name,
                                kind="degraded_read", source="local")
        if n_remote:
            EC_REPAIR_BYTES.inc(n_remote * size, geometry=geo.code_name,
                                kind="degraded_read", source="remote")
        EC_REPAIR_PLANS.inc(geometry=geo.code_name, kind="degraded_read")
        return np.asarray(rows[list(mids).index(sid)],
                          np.uint8).tobytes()

    def _lookup_ec_shards(self, vid: int) -> dict[int, list[str]]:
        """cachedLookupEcShardLocations (store_ec.go:238), 10s TTL."""
        now = time.time()
        cached = self._ec_loc_cache.get(vid)
        if cached and cached[0] > now:
            return cached[1]
        out: dict[int, list[str]] = {}
        try:
            stub = rpc.master_stub(self.master_grpc)
            resp = stub.LookupEcVolume(
                master_pb2.LookupEcVolumeRequest(volume_id=vid), timeout=10)
            for sl in resp.shard_id_locations:
                out[sl.shard_id] = [l.url for l in sl.locations]
        except grpc.RpcError as e:
            glog.v(1, f"LookupEcVolume {vid}: {e.code()}")
        self._ec_loc_cache[vid] = (now + 10.0, out)
        return out

    # -- replication (topology/store_replicate.go:24) ----------------------

    def replicate_write(self, fid: str, body: bytes, params: dict,
                        locations: list[str],
                        content_type: str = "",
                        content_encoding: str = "") -> None:
        from ..wdclient import pool

        # the body is forwarded VERBATIM (possibly gzipped, possibly a
        # multipart envelope), so the headers describing it must travel
        # too: without Content-Encoding the replica stores compressed
        # bytes with is_compressed unset and later serves raw gzip to
        # readers (silent corruption on replica failover)
        headers = trace.inject_headers({})  # replicas join the trace
        if content_type:
            headers["Content-Type"] = content_type
        if content_encoding:
            headers["Content-Encoding"] = content_encoding
        # replicas enforce JWT like any write; re-sign with the shared
        # cluster key (the reference re-mints for fan-out the same way)
        if self.write_jwt_key:
            from ..security import gen_write_jwt

            headers["Authorization"] = \
                f"Bearer {gen_write_jwt(self.write_jwt_key, fid)}"

        def send(addr):
            # the replica leg rides the keep-alive pool (ISSUE 9): the
            # primary holds one warm connection per replica instead of a
            # TCP(+TLS) dial per replicated write
            url = url_for(addr, f"{fid}?type=replicate")
            for k, v in params.items():
                url += f"&{k}={v}"
            try:
                r = pool.put(url, body=body, headers=headers, timeout=30)
            except OSError as e:
                raise IOError(f"replica write to {addr}: {e}") from e
            if r.status >= 300:
                raise IOError(f"replica write to {addr}: {r.status}")

        # shared bounded fan-out executor (ISSUE 14): the old code built
        # and tore down a 4-thread ThreadPoolExecutor PER replicated
        # write — thread spawn on the hottest write path. run_all waits
        # for every send to settle before raising the first failure
        # (same semantics as the old `list(ex.map(...))` + `with` exit).
        # The "replicate" tier, NOT the pipeline tier: in a combined
        # filer+volume process, pipeline-tier uploads block on this
        # very handler — sharing their pool would be a circular wait.
        fanout.run_all(send, [a for a in locations if a != self.address],
                       pool="replicate")

    def lookup_volume_locations(self, vid: int) -> list[str]:
        """Replica locations for a volume, cached ~10s (the write hot path
        calls this per request; GetWritableRemoteReplications in the
        reference resolves peers from its own topology push instead —
        store_replicate.go:188)."""
        now = time.monotonic()
        hit = self._loc_cache.get(vid)
        if hit and hit[0] > now:
            return hit[1]
        locs: list[str] = []
        ok = False
        try:
            stub = rpc.master_stub(self.master_grpc)
            resp = stub.LookupVolume(
                master_pb2.LookupVolumeRequest(volume_or_file_ids=[str(vid)]),
                timeout=10)
            for e in resp.volume_id_locations:
                locs = [l.url for l in e.locations]
                break
            ok = bool(locs)  # empty list = master still warming: short TTL
        except grpc.RpcError:
            pass
        # a failed/empty lookup must not disable replication for a full
        # TTL — cache it only long enough to ride out a hiccup
        self._loc_cache[vid] = (now + (10.0 if ok else 1.0), locs)
        return locs

    def volume_needs_replication(self, vid: int) -> bool:
        """False when the volume's own superblock says single-copy (the
        common case) — skips the per-write location lookup entirely."""
        v = self.store.find_volume(vid)
        if v is None:
            return True  # unknown here: let the lookup decide
        return v.super_block.replica_placement.copy_count > 1


# -- gRPC admin servicer ---------------------------------------------------

class VolumeGrpc:
    def __init__(self, srv: VolumeServer):
        self.srv = srv
        self.store = srv.store

    # ---- batch delete

    def BatchDelete(self, request, context):
        resp = vs.BatchDeleteResponse()
        for fid in request.file_ids:
            res = resp.results.add(file_id=fid)
            try:
                f = parse_file_id(fid)
                cookie = None if request.skip_cookie_check else f.cookie
                res.size = self.store.delete_needle(f.volume_id, f.key, cookie)
                res.status = 202
            except Exception as e:  # noqa: BLE001
                res.status, res.error = 500, str(e)
        return resp

    # ---- vacuum

    def VacuumVolumeCheck(self, request, context):
        v = self._volume(request.volume_id, context)
        return vs.VacuumVolumeCheckResponse(garbage_ratio=v.garbage_level())

    def VacuumVolumeCompact(self, request, context):
        from ..storage.errors import VacuumCrcError

        v = self._volume(request.volume_id, context)
        try:
            v.compact()
        except VacuumCrcError:
            # the scrub-aware vacuum found ROT while copying (not some
            # environmental IOError): abort is already done (compact
            # never commits bad bytes) — queue the repair ladder so the
            # NEXT vacuum finds a healed volume
            self.srv.scrubber.report_suspect(request.volume_id)
            raise
        yield vs.VacuumVolumeCompactResponse(processed_bytes=v.data_size())

    def VacuumVolumeCommit(self, request, context):
        v = self._volume(request.volume_id, context)
        v.commit_compact()
        return vs.VacuumVolumeCommitResponse(
            is_read_only=v.read_only, volume_size=v.data_size())

    def VacuumVolumeCleanup(self, request, context):
        v = self._volume(request.volume_id, context)
        base = v.file_name()
        for ext in (".cpd", ".cpx"):
            try:
                os.remove(base + ext)
            except FileNotFoundError:
                pass
        v.is_compacting = False
        return vs.VacuumVolumeCleanupResponse()

    # ---- collections / allocation

    def DeleteCollection(self, request, context):
        self.store.delete_collection(request.collection)
        self.srv.trigger_heartbeat()
        return vs.DeleteCollectionResponse()

    def AllocateVolume(self, request, context):
        self.store.add_volume(
            request.volume_id, request.collection,
            request.replication, request.ttl,
        )
        self.srv.trigger_heartbeat()
        self.srv._sync_native_registry()
        return vs.AllocateVolumeResponse()

    # ---- status / sync

    def VolumeSyncStatus(self, request, context):
        v = self._volume(request.volume_id, context)
        return vs.VolumeSyncStatusResponse(
            volume_id=v.id, collection=v.collection,
            replication=str(v.super_block.replica_placement),
            ttl=str(v.ttl), tail_offset=v.data_size(),
            compact_revision=v.super_block.compaction_revision,
            idx_file_size=os.path.getsize(v.nm.idx_path),
        )

    def VolumeIncrementalCopy(self, request, context):
        """Stream .dat bytes appended at/after since_ns (volume_backup.go
        binary-search semantics, linear scan here)."""
        v = self._volume(request.volume_id, context)
        start = None
        for n, off in v.scan_needles():
            if n.append_at_ns >= request.since_ns:
                start = off
                break
        if start is None:
            return
        size = v.data_size()
        while start < size:
            chunk = v._pread(start, min(BUFFER_SIZE_LIMIT, size - start))
            if not chunk:
                break
            yield vs.VolumeIncrementalCopyResponse(file_content=chunk)
            start += len(chunk)

    # ---- mount / unmount / delete / readonly

    def VolumeMount(self, request, context):
        self.store.mount_volume(request.volume_id)
        self.srv.trigger_heartbeat()
        self.srv._sync_native_registry()
        return vs.VolumeMountResponse()

    def VolumeUnmount(self, request, context):
        self.store.unmount_volume(request.volume_id)
        self.srv.trigger_heartbeat()
        self.srv._sync_native_registry()
        return vs.VolumeUnmountResponse()

    def VolumeDelete(self, request, context):
        try:
            self.store.delete_volume(request.volume_id, request.only_empty)
        except NotFoundError:
            pass
        self.srv.trigger_heartbeat()
        self.srv._sync_native_registry()
        return vs.VolumeDeleteResponse()

    def VolumeMarkReadonly(self, request, context):
        self._volume(request.volume_id, context).read_only = True
        self.srv.trigger_heartbeat()
        self.srv._sync_native_registry()
        return vs.VolumeMarkReadonlyResponse()

    def VolumeMarkWritable(self, request, context):
        self._volume(request.volume_id, context).read_only = False
        self.srv.trigger_heartbeat()
        self.srv._sync_native_registry()
        return vs.VolumeMarkWritableResponse()

    def VolumeConfigure(self, request, context):
        from ..storage.super_block import ReplicaPlacement

        v = self._volume(request.volume_id, context)
        v.super_block.replica_placement = ReplicaPlacement.parse(request.replication)
        return vs.VolumeConfigureResponse()

    def VolumeStatus(self, request, context):
        v = self._volume(request.volume_id, context)
        return vs.VolumeStatusResponse(
            is_read_only=v.read_only, volume_size=v.data_size(),
            file_count=v.file_count(), file_deleted_count=v.deleted_count(),
        )

    # ---- copy

    def VolumeCopy(self, request, context):
        """Pull a whole volume from source_data_node (volume_grpc_copy.go)."""
        vid = request.volume_id
        if self.store.has_volume(vid):
            context.abort(grpc.StatusCode.ALREADY_EXISTS, f"volume {vid} exists")
        src = rpc.volume_stub(rpc.grpc_address(request.source_data_node))
        status = src.ReadVolumeFileStatus(
            vs.ReadVolumeFileStatusRequest(volume_id=vid), timeout=30)
        loc = self.store._pick_location(request.disk_type or None)
        base = loc.base_name(status.collection, vid)
        total = 0
        for ext in (".dat", ".idx"):
            with open(base + ext, "wb") as f:
                for chunk in src.CopyFile(vs.CopyFileRequest(
                        volume_id=vid, ext=ext, collection=status.collection,
                        stop_offset=(status.dat_file_size if ext == ".dat" else 0)),
                        timeout=3600):
                    f.write(chunk.file_content)
                    total += len(chunk.file_content)
            yield vs.VolumeCopyResponse(processed_bytes=total)
        # the copied bytes carry the SOURCE's offset width — mirror its
        # marker rather than stamping local mode (operation docstring)
        from ..operation import sync_stride_marker

        sync_stride_marker(src, vid, status.collection, base)
        self.store.mount_volume(vid)
        self.srv.trigger_heartbeat()
        v = self.store.find_volume(vid)
        yield vs.VolumeCopyResponse(last_append_at_ns=v.last_append_at_ns)

    def ReadVolumeFileStatus(self, request, context):
        v = self._volume(request.volume_id, context)
        base = v.file_name()
        return vs.ReadVolumeFileStatusResponse(
            volume_id=v.id, collection=v.collection,
            dat_file_size=v.data_size(),
            idx_file_size=os.path.getsize(base + ".idx"),
            file_count=v.file_count(),
            compaction_revision=v.super_block.compaction_revision,
        )

    def CopyFile(self, request, context):
        """Stream any volume/EC file by extension in 2MB chunks."""
        vid, ext = request.volume_id, request.ext
        path = None
        for loc in self.store.locations:
            vols, ecs = loc.scan()
            col = request.collection
            cand = loc.base_name(col, vid) + ext
            if os.path.exists(cand):
                path = cand
                break
            # collection may be unknown to caller: scan both maps
            if vid in vols and os.path.exists(loc.base_name(vols[vid][0], vid) + ext):
                path = loc.base_name(vols[vid][0], vid) + ext
                break
            if vid in ecs and os.path.exists(loc.base_name(ecs[vid][0], vid) + ext):
                path = loc.base_name(ecs[vid][0], vid) + ext
                break
        if path is None:
            if request.ignore_source_file_not_found:
                return
            context.abort(grpc.StatusCode.NOT_FOUND, f"{vid}{ext} not found")
        stop = request.stop_offset or os.path.getsize(path)
        sent = 0
        with open(path, "rb") as f:
            while sent < stop:
                chunk = f.read(min(BUFFER_SIZE_LIMIT, stop - sent))
                if not chunk:
                    break
                yield vs.CopyFileResponse(file_content=chunk)
                sent += len(chunk)

    # ---- needle blob

    def ReadNeedleBlob(self, request, context):
        v = self._volume(request.volume_id, context)
        offset, size = request.offset, request.size
        if offset == 0 and size == 0 and request.needle_id:
            # by-id form (scrub/anti-entropy): callers on OTHER servers
            # can't know local offsets — resolve through the needle map
            nv = v.nm.get(request.needle_id)
            if nv is None or types.size_is_deleted(nv.size):
                context.abort(grpc.StatusCode.NOT_FOUND, "needle not found")
            offset = types.stored_to_actual_offset(nv.offset)
            size = nv.size
        blob = v.read_needle_blob(offset, size)
        return vs.ReadNeedleBlobResponse(needle_blob=blob)

    def WriteNeedleBlob(self, request, context):
        v = self._volume(request.volume_id, context)
        n = Needle.from_bytes(request.needle_blob, v.version, check_crc=False)
        # verbatim record transfer (anti-entropy heal, scrub repair):
        # the blob carries the ORIGINATING write's epoch tag — stamping
        # a fresh one here would forge causality for a copy
        v.write_needle(n, check_cookie=False, stamp=False)
        return vs.WriteNeedleBlobResponse()

    def ReadAllNeedles(self, request, context):
        for vid in request.volume_ids:
            v = self.store.find_volume(vid)
            if v is None:
                continue
            for n, off in v.scan_needles():
                nv = v.nm.get(n.id)
                if nv is None or types.size_is_deleted(nv.size):
                    continue
                if types.stored_to_actual_offset(nv.offset) != off:
                    continue
                yield vs.ReadAllNeedlesResponse(
                    volume_id=vid, needle_id=n.id, cookie=n.cookie,
                    needle_blob=n.data,
                )

    # ---- tail

    def VolumeTailSender(self, request, context):
        v = self._volume(request.volume_id, context)
        deadline = time.time() + (request.idle_timeout_seconds or 2)
        since = request.since_ns
        while time.time() < deadline and context.is_active():
            progressed = False
            for n, _off in v.scan_needles():
                if n.append_at_ns <= since:
                    continue
                since = n.append_at_ns
                progressed = True
                blob = n.to_bytes(v.version)
                yield vs.VolumeTailSenderResponse(
                    needle_header=blob[:types.NEEDLE_HEADER_SIZE],
                    needle_body=blob[types.NEEDLE_HEADER_SIZE:],
                )
            if progressed:
                deadline = time.time() + (request.idle_timeout_seconds or 2)
            else:
                time.sleep(0.1)
        yield vs.VolumeTailSenderResponse(is_last_chunk=True)

    def VolumeTailReceiver(self, request, context):
        v = self._volume(request.volume_id, context)
        src = rpc.volume_stub(rpc.grpc_address(request.source_volume_server))
        for resp in src.VolumeTailSender(vs.VolumeTailSenderRequest(
                volume_id=request.volume_id, since_ns=request.since_ns,
                idle_timeout_seconds=request.idle_timeout_seconds), timeout=600):
            if resp.is_last_chunk:
                break
            n = Needle.from_bytes(resp.needle_header + resp.needle_body,
                                  v.version, check_crc=False)
            v.write_needle(n, check_cookie=False, stamp=False)
        return vs.VolumeTailReceiverResponse()

    # ---- erasure coding (volume_grpc_erasure_coding.go) ------------------

    def _generate_prologue(self, request, context):
        """Shared head of the plain and streamed generate handlers:
        -> (volume, geometry, coder, pace)."""
        v = self.store.find_volume(request.volume_id)
        if v is None:
            context.abort(grpc.StatusCode.NOT_FOUND,
                          f"volume {request.volume_id} not found")
        if request.collection and v.collection != request.collection:
            context.abort(grpc.StatusCode.INVALID_ARGUMENT, "collection mismatch")
        geo = self.srv.ec_geometry
        code = getattr(request, "geometry", "")
        if code:
            # registry-backed validation (ISSUE 11): an unknown geometry
            # name fails fast, listing what IS registered
            from ..models import geometry as geom_mod

            try:
                cg = geom_mod.get(code)
            except ValueError as e:
                context.abort(grpc.StatusCode.INVALID_ARGUMENT, str(e))
            if not cg.volume_capable:
                context.abort(
                    grpc.StatusCode.INVALID_ARGUMENT,
                    f"geometry {code!r} is not volume-capable "
                    f"(stripe-level codec only)")
            if (request.data_shards
                    and request.data_shards != cg.data_shards) or \
                    (request.parity_shards
                     and request.parity_shards != cg.parity_shards):
                context.abort(
                    grpc.StatusCode.INVALID_ARGUMENT,
                    f"geometry {code!r} is {cg.data_shards}+"
                    f"{cg.parity_shards}; -dataShards/-parityShards "
                    f"disagree")
            geo = Geometry(data_shards=cg.data_shards,
                           parity_shards=cg.parity_shards,
                           large_block=geo.large_block,
                           small_block=geo.small_block,
                           code=cg.name)
        elif request.data_shards:
            geo = Geometry(data_shards=request.data_shards,
                           parity_shards=request.parity_shards or 4,
                           large_block=geo.large_block,
                           small_block=geo.small_block)
        # QoS plane (ISSUE 8): archival encodes are the lowest priority
        # class. Admission-probe a BOUNDED first chunk before touching
        # data (fail closed: an unreachable master pauses archival
        # instead of letting it contend with foreground I/O); the rest
        # of the volume is drawn slab by slab through `pace` so volumes
        # larger than the wait cap's worth of budget still encode.
        from ..qos import DEFAULT_MAX_GRANT_BYTES, QosUnavailable

        probe = max(min(v.data_size(), DEFAULT_MAX_GRANT_BYTES), 1)
        try:
            self.srv.qos_acquire("archival", probe)
        except QosUnavailable as e:
            context.abort(grpc.StatusCode.RESOURCE_EXHAUSTED, str(e))
        pace = self.srv.qos_governor.pacer("archival", prepaid=probe)
        return v, geo, self._geo_coder(geo), pace

    def _generate_epilogue(self, v, geo, base, t0, enc_stats) -> None:
        write_sorted_file_from_idx(base)
        from ..storage.ec_volume import save_volume_info

        save_volume_info(base, {
            "version": v.version,
            "dataShards": geo.data_shards, "parityShards": geo.parity_shards,
            "largeBlock": geo.large_block, "smallBlock": geo.small_block,
            # the code geometry travels WITH the shard set: readable at
            # mount, so mixed-geometry clusters decode every volume with
            # the right generator matrix (ISSUE 11)
            "geometry": geo.code_name,
        })
        VOLUME_SERVER_EC_ENCODE_BYTES.inc(v.data_size())
        glog.v(0, f"ec encode vol {v.id}: {v.data_size()} bytes in "
                  f"{time.perf_counter() - t0:.2f}s "
                  f"(read {enc_stats.read_s:.2f}s, device-wait "
                  f"{enc_stats.device_wait_s:.2f}s, write {enc_stats.write_s:.2f}s, "
                  f"overlap x{enc_stats.overlap_ratio:.2f})")

    def VolumeEcShardsGenerate(self, request, context):
        """.dat -> .ec00.. + .ecx + .vif (handler :38-81). The stripe math
        runs through the store's (TPU) coder."""
        from ..qos import QosUnavailable

        v, geo, coder, pace = self._generate_prologue(request, context)
        base = v.file_name()
        t0 = time.perf_counter()
        try:
            enc_stats = write_ec_files(base, coder, geo, pace=pace)
        except QosUnavailable as e:
            # starved mid-encode (budget reserved for higher classes or
            # master lost): same abort surface as the admission probe —
            # the shell's failure path rolls the replica back writable
            context.abort(grpc.StatusCode.RESOURCE_EXHAUSTED, str(e))
        self._generate_epilogue(v, geo, base, t0, enc_stats)
        return vs.VolumeEcShardsGenerateResponse()

    def VolumeEcShardsGenerateStreamed(self, request, context):
        """ISSUE 6 tentpole: generate shards AND push each remote
        destination's shards to it while the encode is still running —
        network transfer, GF matmul and destination shard I/O all in
        flight concurrently (storage/ec_stream.py). Local shard files
        are still written (the source keeps its own shards; they are
        also the resume source after a destination flap)."""
        from ..pb import ec_stream_pb2 as es
        from ..storage.ec_stream import EcStreamDestination, EcStreamSinkSet
        from ..utils.stats import EC_STREAM_OVERLAP_RATIO

        v, geo, coder, pace = self._generate_prologue(request, context)
        base = v.file_name()
        shard_size = geo.shard_size(v.data_size())
        dests = [
            EcStreamDestination(
                t.address, request.volume_id, request.collection,
                list(t.shard_ids), base, geo, shard_size,
                source=self.srv.address)
            for t in request.targets if t.shard_ids
        ]
        t0 = time.perf_counter()
        sinks = EcStreamSinkSet(dests)
        try:
            enc_stats = write_ec_files(base, coder, geo, sinks=sinks,
                                       pace=pace)
        except BaseException as e:
            sinks.abort()
            from ..qos import QosUnavailable

            if isinstance(e, QosUnavailable):
                context.abort(grpc.StatusCode.RESOURCE_EXHAUSTED, str(e))
            raise
        resp = es.VolumeEcShardsGenerateStreamedResponse()

        def finish_one(d):
            # per-destination verdict; finish() may run a full
            # missing-range resume with retries, so destinations must
            # not serialize behind each other's catch-up
            try:
                d.finish()
                return d, None
            except BaseException as e:  # noqa: BLE001
                return d, (d.error or f"{type(e).__name__}: {e}")

        results = []
        if dests:
            # lint: allow-executor — per-conversion admin path (one
            # pool per ec.encode stream, not per request); finish() can
            # block minutes on resume retries, which would starve the
            # shared fan-out budget
            with ThreadPoolExecutor(max_workers=len(dests)) as ex:
                results = list(ex.map(finish_one, dests))
        for d, err in results:
            r = resp.targets.add(address=d.address)
            if err is None:
                r.ok = True
            else:
                r.ok = False
                r.error = err
                glog.warning(f"ec stream vol {v.id} -> {d.address} "
                             f"failed after retries: {err}; caller "
                             f"falls back to VolumeEcShardsCopy")
            r.bytes_streamed = d.bytes_streamed
            r.resumes = d.resumes
            r.resumed_bytes = d.resumed_bytes
            resp.bytes_streamed += d.bytes_streamed
            resp.resumes += d.resumes
        wall = time.perf_counter() - t0
        self._generate_epilogue(v, geo, base, t0, enc_stats)
        resp.encode_seconds = enc_stats.wall_s
        resp.wall_seconds = wall
        resp.overlap_ratio = enc_stats.wall_s / wall if wall > 0 else 0.0
        if dests:
            EC_STREAM_OVERLAP_RATIO.set(resp.overlap_ratio)
        return resp

    # ---- streaming shard receive (ec_stream.proto; ISSUE 6) --------------

    def VolumeEcShardsStream(self, request_iterator, context):
        """Destination side of the pipelined archival encode: append
        shard slabs as they arrive (in offset order per shard), verify
        each slab's crc32c in transit, chain per-shard digests while
        writing, and at commit check them against the source's
        crc32c_combine-folded expectation — then persist the `.dig`
        manifest without re-reading a byte. `resume=True` continues
        after this server's on-disk prefix (the prefix digest is
        re-chained from disk, the only re-read on the resume path)."""
        from ..scrub.digest import ShardCrc, write_ec_manifest
        from ..storage.crc import crc32c, crc32c_combine
        from ..utils.stats import EC_STREAM_BYTES, EC_STREAM_SLABS

        it = iter(request_iterator)
        first = next(it, None)
        if first is None or not first.HasField("header"):
            context.abort(grpc.StatusCode.INVALID_ARGUMENT,
                          "first stream message must be the header")
        h = first.header
        loc = self.store.locations[0]
        base = loc.base_name(h.collection, h.volume_id)
        geo = self.srv.ec_geometry
        files: dict[int, object] = {}
        digests: dict[int, int] = {}
        sizes: dict[int, int] = {}
        phase = "resume" if h.resume else "live"
        received = 0
        commit = None
        try:
            for sid in h.shard_ids:
                path = geo.shard_file_name(base, sid)
                if h.resume and os.path.exists(path):
                    f = open(path, "r+b")
                    crc = 0
                    n = 0
                    while True:  # re-chain the digest over the prefix
                        chunk = f.read(1 << 20)
                        if not chunk:
                            break
                        crc = crc32c(chunk, crc)
                        n += len(chunk)
                    digests[sid], sizes[sid] = crc, n
                else:
                    f = open(path, "wb")
                    digests[sid], sizes[sid] = 0, 0
                files[sid] = f
            for msg in it:
                if msg.HasField("slab"):
                    s = msg.slab
                    # chaos hook (ISSUE 6): a targeted destination drops
                    # mid-stream; the source resumes from this server's
                    # reported on-disk prefix. Matchable per shard AND
                    # per slab range (comma-terminated ctx convention).
                    try:
                        failpoint.fail(
                            "ec.stream.slab",
                            ctx=f"{self.srv.address}, "
                                f"shard={s.shard_id}, off={s.offset},")
                    except failpoint.FailpointError as e:
                        context.abort(grpc.StatusCode.UNAVAILABLE, str(e))
                    f = files.get(s.shard_id)
                    if f is None:
                        context.abort(grpc.StatusCode.INVALID_ARGUMENT,
                                      f"shard {s.shard_id} not in header")
                    slab_crc = crc32c(s.data)
                    if slab_crc != s.crc:
                        context.abort(grpc.StatusCode.DATA_LOSS,
                                      f"slab crc mismatch in transit "
                                      f"(shard {s.shard_id} @ {s.offset})")
                    if s.offset != sizes[s.shard_id]:
                        context.abort(
                            grpc.StatusCode.FAILED_PRECONDITION,
                            f"non-contiguous slab for shard {s.shard_id}:"
                            f" offset {s.offset}, have {sizes[s.shard_id]}")
                    if f.tell() != s.offset:  # interleaved shards only
                        f.seek(s.offset)
                    f.write(s.data)
                    sizes[s.shard_id] += len(s.data)
                    # chain via the O(32^2) combine fold instead of a
                    # second full crc pass over the slab bytes
                    digests[s.shard_id] = crc32c_combine(
                        digests[s.shard_id], slab_crc, len(s.data))
                    received += len(s.data)
                    EC_STREAM_BYTES.inc(len(s.data), role="dest",
                                        phase=phase)
                    EC_STREAM_SLABS.inc(role="dest", phase=phase)
                elif msg.HasField("commit"):
                    commit = msg.commit
                    break
            if commit is None:
                context.abort(grpc.StatusCode.ABORTED,
                              "stream ended without commit")
            for f in files.values():
                f.flush()
                if os.environ.get("SWFS_EC_STREAM_FSYNC", "0").lower() \
                        in ("1", "true", "on"):
                    # off by default: the VolumeEcShardsCopy path the
                    # stream replaces never fsyncs either (the source
                    # holds every shard until the shell's delete step,
                    # so a crashed destination is simply re-streamed)
                    os.fsync(f.fileno())
            for d in commit.digests:
                if d.shard_id not in files:
                    continue
                if (sizes[d.shard_id], digests[d.shard_id]) != (d.size,
                                                                d.crc):
                    context.abort(
                        grpc.StatusCode.DATA_LOSS,
                        f"shard {d.shard_id} digest mismatch at commit: "
                        f"wrote size={sizes[d.shard_id]} "
                        f"crc={digests[d.shard_id]:#x}, source expects "
                        f"size={d.size} crc={d.crc:#x}")
            # the PR-4 digest manifest falls out of the digests chained
            # while writing — no second read (cached_ec_digest serves
            # VolumeDigest from it once the shards mount)
            write_ec_manifest(base, {
                sid: ShardCrc(sid, digests[sid], sizes[sid])
                for sid in files})
            from ..pb import ec_stream_pb2 as es

            resp = es.VolumeEcShardsStreamResponse(bytes_received=received)
            for sid in sorted(files):
                resp.shards.add(shard_id=sid, crc=digests[sid],
                                size=sizes[sid])
            return resp
        finally:
            for f in files.values():
                try:
                    f.close()
                except OSError:
                    pass

    def VolumeEcShardsStreamStatus(self, request, context):
        """Resume probe: contiguous bytes of each requested shard durably
        on this server's disk (slabs arrive in offset order, so file
        size IS the complete prefix length)."""
        from ..pb import ec_stream_pb2 as es

        loc = self.store.locations[0]
        base = loc.base_name(request.collection, request.volume_id)
        geo = self.srv.ec_geometry
        resp = es.VolumeEcShardsStreamStatusResponse()
        for sid in request.shard_ids:
            try:
                size = os.path.getsize(geo.shard_file_name(base, sid))
            except OSError:
                size = 0
            resp.shards.add(shard_id=sid, size=size)
        return resp

    def VolumeEcShardsRead(self, request, context):
        """Cross-server syndrome-verify gather source (ISSUE 13): stream
        the requested shard RANGES as chunked, CRC-stamped,
        offset-addressed slabs — the VolumeEcShardsStream wire shape in
        reverse. Ranges advance in lockstep (offset-major) so a consumer
        assembling verify windows across shards never has to buffer a
        whole shard of one range while another lags."""
        from ..pb import ec_gather_pb2 as eg
        from ..storage.crc import crc32c

        ev = self.store.find_ec_volume(request.volume_id)
        if ev is None:
            context.abort(grpc.StatusCode.NOT_FOUND,
                          f"ec volume {request.volume_id} not mounted")
        slab = min(request.slab or BUFFER_SIZE_LIMIT, BUFFER_SIZE_LIMIT)
        cursors = []
        for r in request.ranges:
            f = ev.shard_files.get(r.shard_id)
            if f is None:
                context.abort(grpc.StatusCode.NOT_FOUND,
                              f"shard {r.shard_id} not on this server")
            end = f.size() if not r.size else min(r.offset + r.size,
                                                  f.size())
            cursors.append([r.shard_id, f, r.offset, end])
        progressed = True
        while progressed:
            progressed = False
            for cur in cursors:
                sid, f, off, end = cur
                if off >= end:
                    continue
                n = min(slab, end - off)
                try:
                    # chaos hook: a targeted peer drops mid-gather; the
                    # scrubber resumes only the missing ranges.
                    # Matchable per peer AND per (shard, offset).
                    failpoint.fail(
                        "scrub.gather.range",
                        ctx=f"{self.srv.address}, shard={sid}, "
                            f"off={off},")
                except failpoint.FailpointError as e:
                    context.abort(grpc.StatusCode.UNAVAILABLE, str(e))
                data = f.read_at(off, n)
                data += b"\0" * (n - len(data))
                yield eg.VolumeEcShardsReadResponse(
                    shard_id=sid, offset=off, data=data,
                    crc=crc32c(data))
                cur[2] = off + n
                progressed = True

    def VolumeEcShardsRebuild(self, request, context):
        """Regenerate missing .ecXX from survivors (handler :84-123)."""
        base = self._ec_base(request.volume_id, request.collection, context)
        geo = self._ec_geo(base)
        coder = self._geo_coder(geo)
        # rebuilds are REPAIR-class work: they outrank scrub/archival in
        # the grant ledger (a repair storm must never starve behind an
        # archival backlog), and fail closed like every background class.
        # Probe a BOUNDED first chunk, then draw the rest slab by slab —
        # a lump acquire of the whole survivor set could exceed what the
        # budget can ever accumulate inside one wait cap, making large
        # rebuilds permanently impossible.
        from ..qos import DEFAULT_MAX_GRANT_BYTES, QosUnavailable

        est = sum(os.path.getsize(geo.shard_file_name(base, i))
                  for i in range(geo.total_shards)
                  if os.path.exists(geo.shard_file_name(base, i)))
        probe = max(min(est, DEFAULT_MAX_GRANT_BYTES), 1)
        try:
            self.srv.qos_acquire("repair", probe)
        except QosUnavailable as e:
            context.abort(grpc.StatusCode.RESOURCE_EXHAUSTED, str(e))
        pace = self.srv.qos_governor.pacer("repair", prepaid=probe)
        # `shard_ids` (geometry-aware request form): the genuinely-
        # missing set cluster-wide — locally-absent shards that exist on
        # peers need no rebuild, and the minimal-read plan only covers
        # the asked-for shards
        want = list(getattr(request, "shard_ids", [])) or None
        rstats: dict = {}
        try:
            rebuilt = rebuild_ec_files(base, coder, geo, pace=pace,
                                       want=want, stats=rstats)
        except QosUnavailable as e:
            context.abort(grpc.StatusCode.RESOURCE_EXHAUSTED, str(e))
        from ..pb import ec_geometry_pb2 as eg
        from ..storage.ec_volume import rebuild_ecx_file

        rebuild_ecx_file(base)
        self.srv.scrubber.invalidate_ec_digest(request.volume_id,
                                               remove_manifest=True)
        self.srv.trigger_heartbeat()
        return eg.EcRebuildResponse(
            rebuilt_shard_ids=rebuilt, geometry=geo.code_name,
            survivor_bytes_read=rstats.get("survivor_bytes_read", 0),
            survivor_shards=rstats.get("survivor_shards", 0))

    def VolumeEcShardsCopy(self, request, context):
        """Pull shard files from source_data_node (handler :126-177).
        Instrumented with byte/throughput counters so A/Bs against the
        ISSUE-6 streaming path compare like for like."""
        from ..utils.stats import (
            EC_COPY_FALLBACK_BYTES,
            EC_COPY_FALLBACK_SECONDS,
        )

        loc = self.store.locations[0]
        base = loc.base_name(request.collection, request.volume_id)
        src = rpc.volume_stub(rpc.grpc_address(request.source_data_node))
        exts = [f".ec{sid:02d}" for sid in request.shard_ids]
        if request.copy_ecx_file:
            exts.append(".ecx")
        if request.copy_ecj_file:
            exts.append(".ecj")
        if request.copy_vif_file:
            exts.append(".vif")
        t0 = time.perf_counter()
        for ext in exts:
            kind = "shard" if ext.startswith(".ec") and ext[3:].isdigit() \
                else "index"
            with open(base + ext, "wb") as f:
                for chunk in src.CopyFile(vs.CopyFileRequest(
                        volume_id=request.volume_id, ext=ext,
                        collection=request.collection, is_ec_volume=True,
                        ignore_source_file_not_found=(ext == ".ecj")),
                        timeout=3600):
                    # simulated-WAN hook, mirror of ec.stream.slab's
                    # delay mode: the stream-vs-copy A/B arms BOTH so a
                    # per-chunk wire latency hits the paths symmetrically
                    failpoint.delay("ec.copy.chunk",
                                    ctx=f"{self.srv.address},")
                    f.write(chunk.file_content)
                    EC_COPY_FALLBACK_BYTES.inc(len(chunk.file_content),
                                               kind=kind)
            if ext == ".ecj" and os.path.getsize(base + ext) == 0:
                os.remove(base + ext)
            if ext == ".ecx":
                # the per-index stride marker travels WITH the .ecx: the
                # SOURCE's offset width decides how its entries parse
                from ..operation import sync_stride_marker

                sync_stride_marker(src, request.volume_id,
                                   request.collection, base,
                                   ext=".ecx.lrg", is_ec=True)
        EC_COPY_FALLBACK_SECONDS.inc(time.perf_counter() - t0)
        # an index-only copy (the streaming path ships shard bytes itself
        # and pulls just .ecx/.ecj/.vif here) leaves shard bytes — and
        # therefore the streamed `.dig` manifest — intact
        self.srv.scrubber.invalidate_ec_digest(
            request.volume_id, remove_manifest=bool(request.shard_ids))
        return vs.VolumeEcShardsCopyResponse()

    def VolumeEcShardsDelete(self, request, context):
        """Remove local shard files; drop index files once no shard remains
        (handler :181-264)."""
        for loc in self.store.locations:
            base = loc.base_name(request.collection, request.volume_id)
            if not os.path.exists(base + ".ecx") and not any(
                    os.path.exists(base + f".ec{sid:02d}")
                    for sid in request.shard_ids):
                # (streamed shard files can exist before any .ecx does —
                # a rollback after a failed streamed encode must still
                # be able to clean them up)
                continue
            for sid in request.shard_ids:
                try:
                    os.remove(base + f".ec{sid:02d}")
                except FileNotFoundError:
                    pass
            geo = self._ec_geo(base)
            if not any(os.path.exists(base + f".ec{i:02d}")
                       for i in range(geo.total_shards)):
                # the per-index marker goes with its .ecx — a stale one
                # would falsely refuse a later re-encode in the other mode
                for ext in (".ecx", ".ecj", ".vif", ".ecx.lrg", ".dig"):
                    try:
                        os.remove(base + ext)
                    except FileNotFoundError:
                        pass
            # refresh the mounted runtime so it stops serving (and
            # heartbeating) the deleted shard files
            if self.store.find_ec_volume(request.volume_id) is not None:
                self.store.unmount_ec_shards(request.volume_id)
                if os.path.exists(base + ".ecx"):
                    self.store.mount_ec_shards(
                        request.volume_id, request.collection, [])
        self.srv.ec_recon_cache.invalidate(request.volume_id)
        self.srv.scrubber.invalidate_ec_digest(request.volume_id,
                                               remove_manifest=True)
        self.srv.trigger_heartbeat()
        return vs.VolumeEcShardsDeleteResponse()

    def VolumeEcShardsMount(self, request, context):
        self.store.mount_ec_shards(
            request.volume_id, request.collection, list(request.shard_ids))
        # cached reconstructions may describe shards that just (re)appeared
        self.srv.ec_recon_cache.invalidate(request.volume_id)
        self.srv.scrubber.invalidate_ec_digest(request.volume_id)
        self.srv.trigger_heartbeat()
        return vs.VolumeEcShardsMountResponse()

    def VolumeEcShardsUnmount(self, request, context):
        self.store.unmount_ec_shards(request.volume_id, list(request.shard_ids))
        self.srv.ec_recon_cache.invalidate(request.volume_id)
        self.srv.scrubber.invalidate_ec_digest(request.volume_id)
        self.srv.trigger_heartbeat()
        return vs.VolumeEcShardsUnmountResponse()

    def VolumeEcShardRead(self, request, context):
        """Stream a shard extent in 2MB messages (handler :309-375)."""
        try:
            # same chaos hook as the local path: a peer asking for a
            # "lost" shard here gets UNAVAILABLE and reconstructs instead
            failpoint.fail(
                "ec.shard.read",
                ctx=f"{self.srv.address}, shard={request.shard_id},")
        except failpoint.FailpointError as e:
            context.abort(grpc.StatusCode.UNAVAILABLE, str(e))
        ev = self.store.find_ec_volume(request.volume_id)
        if ev is None:
            context.abort(grpc.StatusCode.NOT_FOUND,
                          f"ec volume {request.volume_id} not mounted")
        f = ev.shard_files.get(request.shard_id)
        if f is None:
            context.abort(grpc.StatusCode.NOT_FOUND,
                          f"shard {request.shard_id} not on this server")
        if request.file_key:
            _off, size = ev.find_needle(request.file_key)
            if types.size_is_deleted(size):
                yield vs.VolumeEcShardReadResponse(is_deleted=True)
                return
        remaining = request.size
        off = request.offset
        while remaining > 0:
            chunk = f.read_at(off, min(BUFFER_SIZE_LIMIT, remaining))
            if not chunk:
                break
            yield vs.VolumeEcShardReadResponse(data=chunk)
            off += len(chunk)
            remaining -= len(chunk)

    def VolumeEcBlobDelete(self, request, context):
        """Tombstone a needle in a mounted EC volume (handler :377-405)."""
        ev = self.store.find_ec_volume(request.volume_id)
        if ev is None:
            context.abort(grpc.StatusCode.NOT_FOUND, "ec volume not mounted")
        ev.delete_needle(request.file_key)
        return vs.VolumeEcBlobDeleteResponse()

    def VolumeEcShardsToVolume(self, request, context):
        """Decode .ec00-.ec09 back into .dat/.idx (handler :407-446)."""
        base = self._ec_base(request.volume_id, request.collection, context)
        geo = self._ec_geo(base)
        missing = [i for i in range(geo.data_shards)
                   if not os.path.exists(base + f".ec{i:02d}")]
        if missing:
            context.abort(grpc.StatusCode.FAILED_PRECONDITION,
                          f"missing data shards {missing}")
        from ..storage.ec_volume import load_volume_info

        version = load_volume_info(base).get("version", types.CURRENT_VERSION)
        dat_size = find_dat_file_size(base, version)
        write_dat_file(base, dat_size, geo)
        write_idx_file_from_ec_index(base)
        self.store.mount_volume(request.volume_id)
        self.srv.trigger_heartbeat()
        return vs.VolumeEcShardsToVolumeResponse()

    # ---- status / leave / ping

    # -- tiered storage (volume_grpc_tier_upload/download.go) --------------

    def VolumeTierMoveDatToRemote(self, request, context):
        from ..storage.backend import get_tier_backend

        v = self.store.find_volume(request.volume_id)
        if v is None:
            context.abort(grpc.StatusCode.NOT_FOUND,
                          f"volume {request.volume_id} not found")
        try:
            backend = get_tier_backend(request.destination_backend_name)
        except KeyError as e:
            context.abort(grpc.StatusCode.INVALID_ARGUMENT, str(e))
        moved = v.tier_to_remote(
            backend, keep_local=request.keep_local_dat_file)
        yield vs.VolumeTierMoveDatToRemoteResponse(
            processed=moved, processed_percentage=100.0)

    def VolumeTierMoveDatFromRemote(self, request, context):
        v = self.store.find_volume(request.volume_id)
        if v is None:
            context.abort(grpc.StatusCode.NOT_FOUND,
                          f"volume {request.volume_id} not found")
        try:
            moved = v.tier_from_remote(
                keep_remote=request.keep_remote_dat_file)
        except IOError as e:
            context.abort(grpc.StatusCode.FAILED_PRECONDITION, str(e))
        yield vs.VolumeTierMoveDatFromRemoteResponse(
            processed=moved, processed_percentage=100.0)

    def VolumeServerStatus(self, request, context):
        resp = vs.VolumeServerStatusResponse(
            version="seaweedfs-tpu 0.1", data_center=self.store.data_center,
            rack=self.store.rack)
        for loc in self.store.locations:
            st = os.statvfs(loc.directory)
            all_b = st.f_blocks * st.f_frsize
            free_b = st.f_bavail * st.f_frsize
            resp.disk_statuses.append(vs.DiskStatus(
                dir=loc.directory, all=all_b, free=free_b, used=all_b - free_b,
                percent_free=100.0 * free_b / all_b if all_b else 0.0,
                percent_used=100.0 * (all_b - free_b) / all_b if all_b else 0.0,
            ))
        return resp

    def VolumeServerLeave(self, request, context):
        self.srv._stop.set()
        self.srv._hb_wake.set()
        return vs.VolumeServerLeaveResponse()

    def Ping(self, request, context):
        now = time.time_ns()
        return vs.PingResponse(start_time_ns=now, remote_time_ns=now,
                               stop_time_ns=time.time_ns())

    # ---- integrity plane (scrub.proto; ISSUE 4) --------------------------

    def VolumeDigest(self, request, context):
        """Digest manifest of one volume: sorted per-needle stored CRCs +
        rolling digest (anti-entropy compares THIS instead of shipping
        bytes). EC volumes answer per-shard whole-file CRCs instead."""
        vid = request.volume_id
        v = self.store.find_volume(vid)
        if v is not None:
            entries = scrub_digest.volume_digest_entries(v)
            resp = scrub_pb2.VolumeDigestResponse(
                volume_id=vid,
                needle_count=sum(1 for e in entries if e.size >= 0),
                tombstone_count=sum(1 for e in entries if e.size < 0),
                rolling_crc=scrub_digest.rolling_digest(entries))
            if request.include_entries:
                for e in entries:
                    inc, seq, srv = e.epoch or (0, 0, 0)
                    resp.entries.add(needle_id=e.needle_id, crc=e.crc,
                                     size=e.size, epoch_incarnation=inc,
                                     epoch_seq=seq, epoch_server=srv)
            return resp
        ev = self.store.find_ec_volume(vid)
        if ev is None:
            context.abort(grpc.StatusCode.NOT_FOUND,
                          f"volume {vid} not found")
        # a fresh syndrome sweep caches fold-combined shard CRCs
        # (invalidated on any shard mount/unmount/delete/rebuild);
        # compute directly when none is cached
        cached = self.srv.scrubber.cached_ec_digest(vid)
        shard_crcs = cached or scrub_digest.ec_shard_crcs(ev)
        resp = scrub_pb2.VolumeDigestResponse(volume_id=vid, is_ec=True)
        for sc in shard_crcs.values():
            resp.shard_digests.add(shard_id=sc.shard_id, crc=sc.crc,
                                   size=sc.size)
        return resp

    def VolumeScrub(self, request, context):
        """On-demand scrub: sweep one volume (or all) now, optionally
        escalating findings into repair (the shell's `volume.scrub`)."""
        report = self.srv.scrubber.run_once(
            vid=request.volume_id or None, full=request.full,
            repair=request.repair)
        resp = scrub_pb2.VolumeScrubResponse(
            volumes_scrubbed=report.volumes,
            needles_checked=report.needles,
            bytes_verified=report.bytes,
            repaired=report.repaired,
            skipped_pairs=report.skipped_pairs)
        for f in report.findings:
            resp.findings.add(
                volume_id=f.volume_id, kind=f.kind, needle_id=f.needle_id,
                shard_id=max(f.shard_id, 0), detail=f.detail,
                state=f.state, found_at_unix=f.found_at)
        return resp

    def ScrubStatus(self, request, context):
        sc = self.srv.scrubber
        st = sc.status()  # one locked snapshot feeds the whole response
        resp = scrub_pb2.ScrubStatusResponse(
            sweeps_completed=sc.sweeps_completed,
            running=sc.running,
            last_sweep_unix=sc.last_sweep_unix,
            suspect_backlog=st["suspectBacklog"])
        for c in st["cursors"]:
            resp.cursors.add(volume_id=c["volumeId"],
                             offset=max(c["offset"], 0),
                             volume_size=0, sweeps=c["sweeps"])
        for f in sc.snapshot_findings():
            resp.findings.add(
                volume_id=f.volume_id, kind=f.kind, needle_id=f.needle_id,
                shard_id=max(f.shard_id, 0), detail=f.detail,
                state=f.state, found_at_unix=f.found_at)
        return resp

    # ---- needle metadata / status (volume_server.proto:289-301,596-607) --

    def _parse_record(self, v, offset: int, size: int, context) -> Needle:
        try:
            blob = v.read_needle_blob(offset, size)
            return Needle.from_bytes(blob, v.version, check_crc=False)
        except (IOError, ValueError) as e:
            context.abort(grpc.StatusCode.INTERNAL, f"needle read: {e}")

    def ReadNeedleMeta(self, request, context):
        """Needle attributes without the body (volume_grpc_read_write.go
        ReadNeedleMeta): callers pass the (offset, size) they learned from
        the index so no lookup is repeated."""
        v = self._volume(request.volume_id, context)
        offset, size = request.offset, request.size
        if offset == 0:
            nv = v.nm.get(request.needle_id)
            if nv is None or types.size_is_deleted(nv.size):
                context.abort(grpc.StatusCode.NOT_FOUND, "needle not found")
            offset = types.stored_to_actual_offset(nv.offset)
            size = nv.size
        n = self._parse_record(v, offset, size, context)
        return vs.ReadNeedleMetaResponse(
            cookie=n.cookie, last_modified=n.last_modified,
            crc=n.checksum & 0xFFFFFFFF, ttl=str(n.ttl),
            append_at_ns=n.append_at_ns)

    def VolumeNeedleStatus(self, request, context):
        """Index + header view of one needle (volume_grpc_read_write.go
        VolumeNeedleStatus)."""
        v = self._volume(request.volume_id, context)
        nv = v.nm.get(request.needle_id)
        if nv is None or types.size_is_deleted(nv.size):
            context.abort(grpc.StatusCode.NOT_FOUND, "needle not found")
        n = self._parse_record(
            v, types.stored_to_actual_offset(nv.offset), nv.size, context)
        return vs.VolumeNeedleStatusResponse(
            needle_id=request.needle_id, cookie=n.cookie, size=nv.size,
            last_modified=n.last_modified, crc=n.checksum & 0xFFFFFFFF,
            ttl=str(n.ttl))

    # ---- remote fetch (volume_grpc_remote.go FetchAndWriteNeedle) --------

    def FetchAndWriteNeedle(self, request, context):
        from ..remote_storage import new_client

        v = self._volume(request.volume_id, context)
        rc = request.remote_conf
        conf = {"type": rc.type or "local", "name": rc.name}
        if conf["type"] == "local":
            conf["root"] = rc.local_root
        elif conf["type"] == "s3":
            conf.update(endpoint=rc.s3_endpoint,
                        bucket=request.remote_location.bucket,
                        access_key=rc.s3_access_key,
                        secret_key=rc.s3_secret_key,
                        region=rc.s3_region or "us-east-1")
        try:
            client = new_client(conf)
            data = client.read_file(request.remote_location.path,
                                    request.offset,
                                    request.size if request.size else -1)
        except Exception as e:
            context.abort(grpc.StatusCode.INTERNAL, f"remote fetch: {e}")
        n = Needle.create(request.needle_id, request.cookie, bytes(data))
        v.write_needle(n, check_cookie=False)
        import hashlib as _hashlib

        return vs.FetchAndWriteNeedleResponse(
            e_tag=_hashlib.md5(bytes(data)).hexdigest())

    # ---- select on the volume server (volume_grpc_query.go) --------------

    def Query(self, request, context):
        """Scan the named needles as JSON/CSV records, apply the single
        filter, project `selections`, stream serialized stripes."""
        from ..query import execute_query

        for fid in request.from_file_ids:
            try:
                f = parse_file_id(fid)
            except ValueError:
                context.abort(grpc.StatusCode.INVALID_ARGUMENT,
                              f"bad file id {fid}")
            try:
                n = self.srv.read_needle(f.volume_id, f.key, f.cookie)
            except (NotFoundError, KeyError, CookieMismatch, DeletedError):
                continue  # skip unreadable fids like not-found (query semantics)
            data = n.data
            if n.is_compressed:
                from ..utils.compression import maybe_decompress

                data = maybe_decompress(data)
            try:
                out = execute_query(data, request)
            except Exception as e:
                context.abort(grpc.StatusCode.INVALID_ARGUMENT,
                              f"query {fid}: {e}")
            if out:
                yield vs.QueriedStripe(records=out)

    # ---- helpers

    def _volume(self, vid: int, context):
        v = self.store.find_volume(vid)
        if v is None:
            context.abort(grpc.StatusCode.NOT_FOUND, f"volume {vid} not found")
        if v.native is not None:
            # gRPC handlers read v.nm directly; absorb any idx entries the
            # C++ plane appended first (cheap fstat when nothing changed)
            v.sync_native()
        else:
            # admin handlers read the .dat/.idx files (or their sizes)
            # directly; group-commit may still hold bytes in the write
            # buffer (no-op when empty). Under v._lock: an unlocked idx
            # flush could race a writer mid-append and land an idx entry
            # on the OS before its dat record bytes.
            try:
                with v._lock:
                    v._sync_buffers()
            except OSError:
                pass  # surfaced to writers by their own flush
        return v

    def _ec_base(self, vid: int, collection: str, context) -> str:
        for loc in self.store.locations:
            base = loc.base_name(collection, vid)
            if os.path.exists(base + ".ecx") or os.path.exists(base + ".ec00"):
                return base
        context.abort(grpc.StatusCode.NOT_FOUND, f"ec volume {vid} not found")

    def _ec_geo(self, base: str) -> Geometry:
        from ..storage.ec_volume import load_volume_info

        d = self.srv.ec_geometry
        info = load_volume_info(base)
        return Geometry(
            data_shards=info.get("dataShards", d.data_shards),
            parity_shards=info.get("parityShards", d.parity_shards),
            large_block=info.get("largeBlock", d.large_block),
            small_block=info.get("smallBlock", d.small_block),
            code=info.get("geometry", ""),
        )

    def _geo_coder(self, geo: Geometry):
        # per-geometry coders are cached on the store (ISSUE 11) — each
        # owns its own dispatch scheduler, keeping mixed-geometry slabs
        # out of one stacked dispatch
        return self.store.coder_for(geo)


# -- HTTP data plane -------------------------------------------------------

def _make_http_handler(srv: VolumeServer):
    class Handler(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"

        def log_message(self, fmt, *args):
            glog.v(2, f"volume http: {fmt % args}")

        def _reply(self, code: int, body: bytes = b"",
                   content_type: str = "application/json", headers=None) -> None:
            # an error reply to a body-carrying request may leave the
            # body unread on the socket (failpoint/guard/JWT rejections
            # answer before draining) — a keep-alive client's NEXT
            # request would be parsed against those stale bytes and
            # poisoned with a stock HTML 400. Close instead of letting
            # the connection pool recycle a desynced connection.
            if code >= 400 and self.command in ("PUT", "POST"):
                self.close_connection = True
            self.send_response(code)
            if self.close_connection:
                self.send_header("Connection", "close")
            self.send_header("Content-Type", content_type)
            self.send_header("Content-Length", str(len(body)))
            tid = getattr(self, "_trace_id", "")
            if tid:
                self.send_header("X-Trace-Id", tid)
            # every ordinary reply advertises this server's current
            # backpressure score (ROADMAP 5(b)): the filer's chunk
            # pipeline feeds it into the hot signal, collapsing its
            # readahead/overlap windows BEFORE the first 429
            self.send_header("X-Swfs-Pressure",
                             srv.pressure_header_value())
            for k, v in (headers or {}).items():
                self.send_header(k, v)
            self.end_headers()
            if body and self.command != "HEAD":
                self.wfile.write(body)

        def _json(self, obj, code: int = 200, headers=None) -> None:
            self._reply(code, json.dumps(obj).encode(), headers=headers)

        def _guard_denied(self) -> bool:
            """IP whitelist (privateStoreHandler wrapping, guard.go:52)."""
            if srv.guard is None:
                return False
            if srv.guard.is_allowed(self.client_address[0]):
                return False
            self._json({"error": "forbidden"}, 403)
            return True

        # -- GET/HEAD (volume_server_handlers_read.go:31)

        def do_GET(self):
            self._trace_id = ""  # never leak across keep-alive requests
            if self._guard_denied():
                return
            u = urlparse(self.path)
            if u.path == "/status":
                vols = {}
                for loc in srv.store.locations:
                    for vid, v in loc.volumes.items():
                        vols[vid] = {"size": v.data_size(),
                                     "collection": v.collection,
                                     "fileCount": v.file_count(),
                                     "readOnly": v.read_only
                                     or v._gc_frozen}
                from ..utils.stats import (
                    ec_dispatch_stats,
                    ec_stream_stats,
                    group_commit_stats,
                    http_pool_stats,
                    qos_stats,
                    recovery_stats,
                    scrub_stats,
                )

                plane = srv.native_plane
                return self._json({
                    # unified /status schema (ISSUE 7 satellite):
                    # version/startedAt/uptimeSeconds at top level on
                    # every server
                    **status_base(srv._started_at),
                    "Version": "seaweedfs-tpu", "Volumes": vols,
                    "NativeDataPlane": plane is not None,
                    "NativeRequests":
                        plane.request_count() if plane else 0,
                    # zero-copy GETs served via sendfile(2) (ISSUE 9)
                    "NativeSendfile":
                        plane.sendfile_count() if plane else 0,
                    # wdclient pool economics + TLS handshake counters
                    # (this process's client legs: replication fan-out)
                    "HttpPool": http_pool_stats(),
                    "Trace": trace.STORE.stats(),
                    # flush-batching factor of the python write engine
                    # (ISSUE 2 group commit); the native plane writes
                    # through unbuffered pwrite and does not batch
                    "GroupCommit": group_commit_stats(),
                    # EC dispatch plane (ISSUE 3/5/12): stacked-dispatch
                    # batch factors, reconstructed-interval cache ratios,
                    # per-chip dispatch spread + live per-chip queue
                    # depth, host-memory-plane arena health and the
                    # NUMA pinning state of its flush threads
                    "EcDispatch": {
                        **ec_dispatch_stats(),
                        "chipDepth": srv.ec_dispatch_depths(),
                        "arenaLive": srv.ec_dispatch_arena(),
                        "pinning": numa.pinning_stats(),
                    },
                    # streaming replica->EC conversion (ISSUE 6):
                    # live/resume byte flow, in-flight depth, overlap
                    # ratio, and the copy-fallback comparands
                    "EcStream": ec_stream_stats(),
                    # integrity plane (ISSUE 4): sweep cursors, findings
                    # lifecycle, repair outcomes, pacing
                    "Scrub": {**srv.scrubber.status(),
                              "counters": scrub_stats()},
                    # crash-consistency plane (ISSUE 16): what the mount
                    # ladder detected/repaired after an unclean shutdown
                    "Recovery": {
                        **srv.store.recovery_report.status(),
                        "counters": recovery_stats(),
                    },
                    # QoS plane (ISSUE 8): live pressure score, the
                    # governor's leased class budgets, admission/grant
                    # counters
                    "Qos": {
                        **qos_stats(),
                        "pressure": srv.qos_pressure(),
                        "groupCommitDepth": srv.qos_group_commit_depth(),
                        "dispatchDepth": sum(
                            srv.ec_dispatch_depths().values()),
                        "governor": srv.qos_governor.status(),
                    },
                })
            if u.path == "/metrics":
                q = parse_qs(u.query)
                ex = "exemplars" in q
                return self._reply(
                    200, gather(exemplars=ex).encode(),
                    metrics_content_type(ex))
            if u.path == "/debug/traces":
                q = {k: v[0] for k, v in parse_qs(u.query).items()}
                return self._json(trace.debug_traces_payload(q))
            if u.path == "/healthz":
                return self._json({"ok": True})
            if u.path in ("/", "/ui"):
                from .ui import volume_ui

                return self._reply(200, volume_ui(srv),
                                   "text/html; charset=utf-8")
            srv._fg_rate.note()  # scrub pacing backs off on this rate
            with trace.span("volume.read", carrier=self.headers,
                            component="volume", server=srv.address,
                            path=u.path) as tsp:
                self._trace_id = tsp.trace_id
                with VOLUME_SERVER_REQUEST_HISTOGRAM.time(type="read"):
                    self._serve_needle(u)

        do_HEAD = do_GET

        def _serve_needle(self, u):
            try:
                fid = parse_file_id(u.path.lstrip("/"))
            except ValueError as e:
                return self._json({"error": str(e)}, 400)
            try:
                # chaos hook: a targeted replica answers 500 (or stalls)
                # so client-side replica failover can be exercised
                failpoint.fail("volume.http.read",
                               ctx=f"{srv.address}, {u.path}")
                n = srv.read_needle(fid.volume_id, fid.key, fid.cookie)
            except (NotFoundError, DeletedError):
                return self._reply(404)
            except CookieMismatch:
                return self._reply(404)
            except IOError as e:  # includes injected FailpointError
                return self._json({"error": str(e)}, 500)
            data = failpoint.corrupt("volume.http.read.corrupt", n.data,
                                     ctx=f"{srv.address},")
            etag = f'"{n.etag()}"'
            headers = {"ETag": etag}
            if n.last_modified:
                headers["Last-Modified"] = time.strftime(
                    "%a, %d %b %Y %H:%M:%S GMT", time.gmtime(n.last_modified))
            # conditional GETs (volume_server_handlers_read.go:163-176;
            # RFC 7232 §3.3 precedence + weak entity-tag lists via
            # utils.http.not_modified) — short-circuits BEFORE any
            # decompress/transform/copy work below
            if not_modified(self.headers, etag, n.last_modified):
                from ..utils.stats import HTTP_CONDITIONAL_OPS

                HTTP_CONDITIONAL_OPS.inc(plane="volume", result="304")
                return self._reply(304, b"", headers=headers)
            rng = self.headers.get("Range")
            if rng and not range_applies(self.headers, etag,
                                         n.last_modified):
                # If-Range with a stale validator (RFC 7233 §3.2): the
                # Range header is ignored, the full body is served
                from ..utils.stats import HTTP_CONDITIONAL_OPS

                HTTP_CONDITIONAL_OPS.inc(plane="volume",
                                         result="if_range_stale")
                rng = None
            stored_mime = n.mime.decode() if n.mime else ""
            ctype = stored_mime or "application/octet-stream"
            if n.is_compressed:
                import gzip as _gz

                if "gzip" in (self.headers.get("Accept-Encoding") or "") and not rng:
                    headers["Content-Encoding"] = "gzip"
                else:
                    data = _gz.decompress(data)
            # on-read image transforms (volume_server_handlers_read.go:294)
            q = {k: v[0] for k, v in parse_qs(u.query).items()}
            if ("width" in q or "height" in q) and (
                    stored_mime.startswith("image/") or not stored_mime):
                from ..images import resized

                data, _, _ = resized(
                    data, int(q.get("width", 0)), int(q.get("height", 0)),
                    q.get("mode", ""))
            if rng and rng.startswith("bytes="):
                # shared RFC 7233 span parsing (utils.http): suffix
                # ranges serve the LAST N bytes, unsatisfiable/inverted
                # spans 416, malformed specs serve the full body —
                # identical to the filer plane
                span = parse_range(rng, len(data))
                if span == "invalid":
                    return self._reply(416, b"", headers={
                        **headers,
                        "Content-Range": f"bytes */{len(data)}"})
                if span is None:
                    return self._reply(200, data, ctype, headers)
                start, stop = span
                headers["Content-Range"] = f"bytes {start}-{stop - 1}/{len(data)}"
                # memoryview slice (ISSUE 9): the range body is a view
                # over the needle bytes, not a copy
                return self._reply(206, memoryview(data)[start:stop],
                                   ctype, headers)
            self._reply(200, data, ctype, headers)

        # -- PUT/POST (volume_server_handlers_write.go:18)

        def do_PUT(self):
            self._trace_id = ""
            srv._fg_rate.note()
            u = urlparse(self.path)
            with trace.span("volume.write", carrier=self.headers,
                            component="volume", server=srv.address,
                            path=u.path) as tsp:
                self._trace_id = tsp.trace_id
                with VOLUME_SERVER_REQUEST_HISTOGRAM.time(type="write"):
                    self._handle_write()

        do_POST = do_PUT

        def _handle_write(self):
            if self._guard_denied():
                return
            u = urlparse(self.path)
            q = {k: v[0] for k, v in parse_qs(u.query).items()}
            try:
                fid = parse_file_id(u.path.lstrip("/"))
            except ValueError as e:
                return self._json({"error": str(e)}, 400)
            try:
                # chaos hook: flaky/slow writes on a targeted server
                failpoint.fail("volume.http.write",
                               ctx=f"{srv.address}, {u.path}")
            except failpoint.FailpointError as e:
                return self._json({"error": str(e)}, 500)
            # JWT write authorization (security.toml jwt.signing) — also
            # enforced on replica fan-out (the primary re-signs; exempting
            # ?type=replicate would let anyone forge the param)
            if srv.write_jwt_key:
                from ..security import JwtError, verify_fid_jwt

                token = (self.headers.get("Authorization") or "") \
                    .removeprefix("Bearer ").strip() or q.get("auth", "")
                try:
                    verify_fid_jwt(token, srv.write_jwt_key,
                                   u.path.lstrip("/"))
                except JwtError as e:
                    return self._json({"error": f"jwt: {e}"}, 401)
            length = int(self.headers.get("Content-Length") or 0)
            body = self.rfile.read(length)
            name, data = _extract_upload(self.headers, body)
            ttl = TTL.parse(q["ttl"]) if q.get("ttl") else None
            n = Needle.create(
                fid.key, fid.cookie, data,
                name=name or b"",
                mime=(self.headers.get("Content-Type") or "").encode()
                if not _is_multipart(self.headers) else b"",
                ttl=ttl or TTL.parse(""),
                is_compressed=(self.headers.get("Content-Encoding") == "gzip"),
            )
            try:
                _off, size, unchanged = srv.store.write_needle(fid.volume_id, n)
            except NotFoundError as e:
                return self._json({"error": str(e)}, 404)
            except CookieMismatch as e:
                return self._json({"error": str(e)}, 403)
            except IOError as e:
                return self._json({"error": str(e)}, 500)
            if q.get("type") != "replicate" and \
                    srv.volume_needs_replication(fid.volume_id):
                locs = srv.lookup_volume_locations(fid.volume_id)
                if len(locs) > 1:
                    try:
                        srv.replicate_write(
                            u.path.lstrip("/"), body,
                            {k: v for k, v in q.items() if k != "type"},
                            locs,
                            content_type=self.headers.get(
                                "Content-Type") or "",
                            content_encoding=self.headers.get(
                                "Content-Encoding") or "")
                    except IOError as e:
                        return self._json({"error": f"replication: {e}"}, 500)
            self._json({"name": (name or b"").decode(errors="replace"),
                        "size": size, "eTag": n.etag()}, 201)

        # -- DELETE

        def do_DELETE(self):
            self._trace_id = ""
            if self._guard_denied():
                return
            u = urlparse(self.path)
            with trace.span("volume.delete", carrier=self.headers,
                            component="volume", server=srv.address,
                            path=u.path) as tsp:
                self._trace_id = tsp.trace_id
                self._do_delete(u)

        def _do_delete(self, u):
            q = {k: v[0] for k, v in parse_qs(u.query).items()}
            try:
                fid = parse_file_id(u.path.lstrip("/"))
            except ValueError as e:
                return self._json({"error": str(e)}, 400)
            if srv.write_jwt_key:  # deletes are writes (jwt.go)
                from ..security import JwtError, verify_fid_jwt

                token = (self.headers.get("Authorization") or "") \
                    .removeprefix("Bearer ").strip() or q.get("auth", "")
                try:
                    verify_fid_jwt(token, srv.write_jwt_key,
                                   u.path.lstrip("/"))
                except JwtError as e:
                    return self._json({"error": f"jwt: {e}"}, 401)
            try:
                size = srv.store.delete_needle(fid.volume_id, fid.key, fid.cookie)
            except NotFoundError:
                # EC volumes: tombstone through the EC path
                ev = srv.store.find_ec_volume(fid.volume_id)
                if ev is None:
                    return self._json({"size": 0}, 404)
                ev.delete_needle(fid.key)
                return self._json({"size": 0}, 202)
            except CookieMismatch as e:
                return self._json({"error": str(e)}, 403)
            if q.get("type") != "replicate" and \
                    srv.volume_needs_replication(fid.volume_id):
                del_headers = {}
                if srv.write_jwt_key:
                    from ..security import gen_write_jwt

                    del_headers["Authorization"] = "Bearer " + \
                        gen_write_jwt(srv.write_jwt_key,
                                      u.path.lstrip("/"))
                for addr in srv.lookup_volume_locations(fid.volume_id):
                    if addr == srv.address:
                        continue
                    try:
                        from ..utils import retry as retry_mod
                        from ..wdclient import pool

                        def _leg(a=addr):
                            r = pool.delete(
                                url_for(a, f"{u.path}?type=replicate"),
                                headers=del_headers, timeout=10)
                            # the peer answering an error IS a failed
                            # leg (store OSError -> 500, jwt -> 401):
                            # pool.delete never raises on status, so
                            # without this check a server-side failure
                            # would count as success — the same silent
                            # divergence the transport arm closes (the
                            # replicate WRITE path has the same guard)
                            if r.status >= 300 and r.status != 404:
                                err = (f"replica delete on {a}: "
                                       f"{r.status} {r.text[:200]}")
                                if r.status >= 500:
                                    # transient (peer restarting):
                                    # ConnectionError classifies as
                                    # retryable, so attempts=2 is real
                                    raise ConnectionError(err)
                                raise IOError(err)  # auth/shape: fast

                        # attempts=2 with a 10s leg timeout: this runs
                        # synchronously before the client's 202, and
                        # anti-entropy converges a peer that stays down
                        # — loudness is the goal here, not durability
                        retry_mod.retry("volume.replicate_delete", _leg,
                                        attempts=2)
                    except Exception as e:  # noqa: BLE001
                        # the local tombstone is durable and
                        # anti-entropy's tombstone-wins pass converges
                        # the peer, so the delete still acks — but a
                        # diverged replica is never silent (ISSUE 15:
                        # this was a bare swallow found by SWFS004)
                        glog.warning(
                            f"replicate delete {u.path.lstrip('/')} "
                            f"to {addr} failed after retries: {e}")
                        VOLUME_REPLICA_DELETE_FAILURES.inc(peer=addr)
            self._json({"size": size}, 202)

    return Handler


def _is_multipart(headers) -> bool:
    return "multipart/form-data" in (headers.get("Content-Type") or "")


def _extract_upload(headers, body: bytes) -> tuple[bytes, bytes]:
    """-> (filename, data). Accepts raw bodies or multipart/form-data (the
    reference's upload client posts multipart; ours sends raw by default)."""
    ctype = headers.get("Content-Type") or ""
    if "multipart/form-data" not in ctype:
        return b"", body
    import email
    import email.policy

    msg = email.message_from_bytes(
        b"Content-Type: " + ctype.encode() + b"\r\n\r\n" + body,
        policy=email.policy.HTTP,
    )
    for part in msg.iter_parts():
        fname = part.get_filename()
        payload = part.get_payload(decode=True)
        if payload is not None:
            return (fname or "").encode(), payload
    return b"", b""
